"""Crash-safe atomic checkpoints with resume.

File format (``MXTPUCKPT1``): a single binary container so a checkpoint is
either entirely present or entirely absent — no params/.states file pairs
that can go out of sync when the worker dies between the two writes::

    magic    "MXTPUCKPT1"                 (10 bytes)
    hdr_len  uint32 LE                    (4 bytes)
    header   JSON: {"sections": [{"name", "offset", "length"}], "meta": {}}
    payload  concatenated section bytes   (params = mx.nd zip container,
                                           trainer = pickled states blob)
    footer   uint32 LE CRC32 of everything above + "CKPTEND1" (12 bytes)

Write protocol (the only crash-safe sequence POSIX gives us): serialize to
``<path>.tmp.<pid>``, flush + ``fsync`` the file, ``os.replace`` onto the
final name (atomic within a filesystem), then ``fsync`` the directory so
the rename itself survives power loss. A reader therefore sees either the
old complete file or the new complete file; a torn write is impossible at
the final name, and the CRC footer catches the remaining cases (bit rot,
truncation of the temp file by a copy tool, a partially-synced disk).

:class:`CheckpointManager` numbers checkpoints by step and its
:meth:`~CheckpointManager.load_latest` walks newest → oldest, *skipping*
(and quarantining as ``.corrupt``) any file whose magic/CRC fails —
rollback to last-good instead of refusing to start.

**Sharded (reshard-on-resume) checkpoints** (``save(...,
sharded=True)``): the manifest keeps the ``<prefix>-<step>.ckpt`` name
(so ``load_latest`` walks it unchanged) and holds the trainer blob plus a
JSON shard table recording the saving mesh/axis layout
(``{"dp": 8}``) and every shard's CRC32; the parameters are
round-robin-partitioned by name across ``num_shards`` sibling files
(``<name>.ckpt.shard00-of08`` …), each itself a full ``MXTPUCKPT1``
container with its own CRC. Because each shard carries whole tensors
(the ZeRO-style name partition, not a tensor split), a load reassembles
the full parameter dict from *however many* shards were written and
restores it onto the **current** context list — a dp8 save resumes on a
dp4 mesh (or any other size) with no conversion step. A corrupt/missing
shard fails the whole step's load atomically and the manager quarantines
the manifest *and* its shards together.

:class:`ResilientCheckpointHandler` is the ``gluon.contrib.estimator``
integration: periodic atomic snapshots of block parameters + Trainer
state + progress meta, and a :meth:`~ResilientCheckpointHandler.resume`
that restores all three so an injected mid-epoch worker death continues on
the same loss trajectory.
"""
from __future__ import annotations

import json
import os
import struct
import zlib

from ..base import MXNetError
from ..gluon.contrib.estimator.event_handler import (BatchEnd, EpochEnd,
                                                     TrainBegin, TrainEnd)
from ..profiler import core as _prof
from . import counters as _counters

MAGIC = b"MXTPUCKPT1"
END_MAGIC = b"CKPTEND1"


class CheckpointCorruptError(MXNetError):
    """The file failed magic/CRC/structure validation on load."""


# -- low-level container ----------------------------------------------------


def _pack(sections, meta):
    """sections: list of (name, bytes). Returns the full container bytes."""
    hdr = {"sections": [], "meta": meta or {}}
    offset = 0
    for name, blob in sections:
        hdr["sections"].append(
            {"name": name, "offset": offset, "length": len(blob)})
        offset += len(blob)
    hdr_bytes = json.dumps(hdr).encode()
    body = MAGIC + struct.pack("<I", len(hdr_bytes)) + hdr_bytes \
        + b"".join(blob for _, blob in sections)
    return body + struct.pack("<I", zlib.crc32(body)) + END_MAGIC


def _unpack(raw, path="<buffer>"):
    """Validate magic + CRC footer; returns ({name: bytes}, meta)."""
    foot = 4 + len(END_MAGIC)
    if len(raw) < len(MAGIC) + 4 + foot or not raw.startswith(MAGIC):
        raise CheckpointCorruptError(f"{path}: not a {MAGIC.decode()} file")
    if not raw.endswith(END_MAGIC):
        raise CheckpointCorruptError(
            f"{path}: missing {END_MAGIC.decode()} footer (torn write?)")
    body, crc_raw = raw[:-foot], raw[-foot:-len(END_MAGIC)]
    (crc,) = struct.unpack("<I", crc_raw)
    actual = zlib.crc32(body)
    if crc != actual:
        raise CheckpointCorruptError(
            f"{path}: CRC mismatch (stored {crc:#010x}, actual "
            f"{actual:#010x}) — checkpoint is corrupt")
    (hdr_len,) = struct.unpack("<I", body[len(MAGIC):len(MAGIC) + 4])
    hdr_start = len(MAGIC) + 4
    try:
        hdr = json.loads(body[hdr_start:hdr_start + hdr_len])
    except ValueError as e:
        raise CheckpointCorruptError(f"{path}: bad header JSON: {e}") from None
    payload = body[hdr_start + hdr_len:]
    out = {}
    for s in hdr["sections"]:
        blob = payload[s["offset"]:s["offset"] + s["length"]]
        if len(blob) != s["length"]:
            raise CheckpointCorruptError(
                f"{path}: section {s['name']!r} truncated")
        out[s["name"]] = blob
    return out, hdr.get("meta", {})


def _atomic_write(path, raw):
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    # fsync the directory: os.replace is atomic in the namespace but the
    # rename record itself needs a journal flush to survive power loss
    dirfd = None
    try:
        dirfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        os.fsync(dirfd)
    except OSError:
        pass  # e.g. filesystems that refuse O_RDONLY dir fsync
    finally:
        if dirfd is not None:
            os.close(dirfd)


# -- public save/load -------------------------------------------------------


def _trainer_blob(trainer):
    return trainer.states_to_bytes()


def _restore_trainer(trainer, raw):
    trainer.load_states_from_bytes(raw)


def _data_state_blob(state):
    import pickle

    return pickle.dumps(state, protocol=pickle.HIGHEST_PROTOCOL)


def _data_state_from_blob(raw):
    import pickle

    return pickle.loads(raw)


def _restore_data_iter(path, sections, data_iter):
    """Restore a data iterator's position from the ``datastate`` section.
    A checkpoint written without one warns instead of raising — the
    params/trainer restore is still valid, only the data position resets
    (the pre-resumable-iterator behavior, now loud instead of silent)."""
    if data_iter is None:
        return
    if "datastate" not in sections:
        import warnings

        warnings.warn(
            f"{os.path.basename(str(path))}: checkpoint carries no "
            "datastate section — data iterator position NOT restored, the "
            "epoch will replay from the iterator's current position",
            RuntimeWarning, stacklevel=4)
        return
    data_iter.load_state_dict(_data_state_from_blob(sections["datastate"]))


def _snapshot_params(params):
    """Point-in-time host copy of a params dict — the synchronous half
    (the 'stall') of an async save. Device→host transfers happen here;
    serialization/CRC/write happen off-thread against this snapshot, so
    continued training never races the in-flight write."""
    import numpy as _np

    from ..ndarray.ndarray import NDArray

    out = {}
    for name, v in params.items():
        if isinstance(v, (list, tuple)):
            # pre-split tensor slices (layout-carrying sharded saves)
            out[name] = [s if isinstance(s, _np.ndarray)
                         else (s.asnumpy() if hasattr(s, "asnumpy")
                               else _np.asarray(s)) for s in v]
        elif hasattr(v, "asnumpy"):
            out[name] = NDArray(v.asnumpy())
        else:
            out[name] = NDArray(_np.ascontiguousarray(_np.asarray(v)))
    return out


def _write_container(path, raw, shard=None):
    """One container write, instrumented as the ``ckpt:write`` fault
    site: a ``die`` rule kills the writer BEFORE this container lands
    (the crash-mid-sequence case — for sharded saves the manifest never
    commits and last-good stands), a ``torn`` marker lands truncated
    bytes at the FINAL name — the corrupt-file state the CRC footer +
    quarantine rollback must catch."""
    slot = _faults_slot()
    if slot is not None:
        marker = slot.check("ckpt:write",
                            {"path": os.path.basename(str(path)),
                             "shard": shard})
        if isinstance(marker, dict) and marker.get("kind") == "torn":
            with open(path, "wb") as f:
                f.write(raw[:max(1, len(raw) // 2)])
            return
    _atomic_write(path, raw)


#: last measured synchronous stall of an async save, in ms (bench hook)
LAST_STALL_MS = None


def _note_stall(stall_ms):
    """Account one async save's synchronous stall; warns when it blows
    the ``MXNET_CKPT_STALL_BUDGET_MS`` budget (0 = unbudgeted)."""
    global LAST_STALL_MS
    LAST_STALL_MS = stall_ms
    _counters.incr("resilience.ckpt_async_saves")
    from .. import config

    budget = float(config.get("MXNET_CKPT_STALL_BUDGET_MS") or 0)
    if budget and stall_ms > budget:
        _counters.incr("resilience.ckpt_stall_overruns")
        n = _counters.get("resilience.ckpt_stall_overruns")
        if _counters.should_warn(n):
            import warnings

            warnings.warn(
                f"async checkpoint stall {stall_ms:.1f}ms exceeds "
                f"MXNET_CKPT_STALL_BUDGET_MS={budget:g} ({n} overrun(s) "
                "this process) — the host snapshot itself is too slow, "
                "not the background write", RuntimeWarning, stacklevel=4)
    if _prof.ENABLED:
        _prof.record_instant("resilience::ckpt_stall", "resilience",
                             args={"ms": round(float(stall_ms), 3)})


class AsyncCheckpoint:
    """Handle for one in-flight background checkpoint write.

    The save call already snapshotted params/trainer/data state to host
    (the bounded stall, recorded in :attr:`stall_ms`); the thread behind
    this handle owns serialization + CRC + atomic write. :meth:`join` is
    the consistency fence — a second save, a load, a quarantine, or a
    shutdown must join the in-flight write first. A failed background
    write (including an injected ``die`` at ``ckpt:write``) does NOT
    raise into the joiner: the generation simply never commits, readers
    fall back to last-good, and the failure is counted
    (``resilience.ckpt_async_failed``) and warned about."""

    def __init__(self, path, stall_ms):
        self.path = path
        self.stall_ms = stall_ms
        self.error = None
        self._thread = None

    def in_flight(self):
        return self._thread is not None and self._thread.is_alive()

    def join(self, timeout=None):
        """Fence: block until the write lands (or fails). Returns True
        when the checkpoint committed."""
        if self._thread is not None:
            self._thread.join(timeout)
        return self.error is None


def _spawn_commit(commit, path, stall_ms):
    import threading

    handle = AsyncCheckpoint(path, stall_ms)

    def run():
        _prof.register_thread_name()
        try:
            commit()
        except BaseException as exc:  # incl. SimulatedWorkerDeath
            handle.error = exc
            _counters.incr("resilience.ckpt_async_failed")
            n = _counters.get("resilience.ckpt_async_failed")
            if _counters.should_warn(n):
                import warnings

                warnings.warn(
                    f"async checkpoint write failed for "
                    f"{os.path.basename(str(path))}: "
                    f"{type(exc).__name__}: {exc} — generation never "
                    "committed, resume falls back to last-good",
                    RuntimeWarning, stacklevel=2)

    t = threading.Thread(target=run, daemon=True, name="mxtpu-ckpt-write")
    handle._thread = t
    t.start()
    return handle


def save_checkpoint(path, net=None, trainer=None, params=None, meta=None,
                    data_state=None, async_write=False):
    """Atomically write one checkpoint file covering block parameters
    (``net`` or an explicit name->NDArray ``params`` dict) and, when given,
    the Trainer's optimizer state + step count. ``data_state`` (any
    pickleable object, typically an iterator's ``state_dict()``) rides
    along as a ``datastate`` section so resume restores the data position
    sample-exactly.

    ``async_write=True`` splits the save: params/trainer/data state are
    snapshotted to host synchronously (the bounded stall), then
    pack/CRC/atomic-write run on a background thread; returns an
    :class:`AsyncCheckpoint` handle whose :meth:`~AsyncCheckpoint.join`
    fences the write. Synchronous saves return ``path``."""
    import time as _time

    if net is None and params is None:
        raise MXNetError("save_checkpoint needs a net or a params dict")
    if params is None:
        params = net._params_data()
    t0 = _prof.begin()
    tw = _time.perf_counter()
    host = _snapshot_params(params)
    trainer_blob = _trainer_blob(trainer) if trainer is not None else None
    data_blob = (_data_state_blob(data_state) if data_state is not None
                 else None)
    stall_ms = (_time.perf_counter() - tw) * 1e3

    def commit():
        from ..ndarray.utils import save_parameters_buffer

        sections = [("params", save_parameters_buffer(host))]
        if trainer_blob is not None:
            sections.append(("trainer", trainer_blob))
        if data_blob is not None:
            sections.append(("datastate", data_blob))
        _write_container(path, _pack(sections, meta))
        _prof.record_duration("resilience::checkpoint_save", "resilience",
                              t0, args={"path": os.path.basename(str(path))})
        _counters.incr("resilience.checkpoints_saved")

    if async_write:
        _note_stall(stall_ms)
        return _spawn_commit(commit, path, stall_ms)
    commit()
    return path


def _shard_path(path, i, n):
    return f"{path}.shard{i:02d}-of{n:02d}"


def _slice_name(name, j):
    """Entry name of tensor-split slice ``j`` of parameter ``name``
    inside the shard containers (layout-carrying saves only)."""
    return f"{name}::{j:02d}"


def save_sharded_checkpoint(path, net=None, trainer=None, params=None,
                            meta=None, num_shards=None, mesh_axes=None,
                            axis="dp", layouts=None, data_state=None,
                            async_write=False):
    """Write one *sharded* checkpoint: ``num_shards`` sibling containers
    each holding a round-robin name-partition of the parameters (whole
    tensors — a ZeRO-style ownership split, not a tensor split), plus a
    manifest at ``path`` recording the saving mesh/axis layout and every
    shard's CRC32, with the trainer blob inside the manifest.

    ``layouts`` records *tensor-split* (tp/pp-sharded) parameters:
    ``{name: {"axis": <mesh axis>, "dim": <tensor dim>, "parts": N}}``.
    A laid-out parameter's value may be the full tensor (split here into
    ``parts`` equal slices along ``dim``) or a pre-split list of the
    per-rank slices in rank order; either way each slice is stored as its
    own ``name::NN`` entry and the manifest carries the layout, so a load
    can reassemble the full tensor and re-lay it out onto whatever mesh
    is current (see :func:`_load_sharded`).

    Write order is shards-first, manifest-last (each write atomic): a
    crash mid-sequence leaves shard files with no manifest — invisible to
    ``CheckpointManager.load_latest``, cleaned by rotation — never a
    manifest pointing at missing shards. ``data_state`` rides in the
    manifest container (written last, atomically) as a ``datastate``
    section. ``async_write=True`` snapshots everything to host
    synchronously and runs the whole shard+manifest write sequence on a
    background thread, returning an :class:`AsyncCheckpoint` handle;
    synchronous saves return ``path``."""
    import time as _time

    from ..ndarray.ndarray import NDArray

    if net is None and params is None:
        raise MXNetError("save_sharded_checkpoint needs a net or params")
    if params is None:
        params = net._params_data()
    num_shards = int(num_shards or 1)
    if num_shards < 1:
        raise MXNetError(f"num_shards must be >= 1, got {num_shards}")
    layouts = dict(layouts or {})
    t0 = _prof.begin()
    tw = _time.perf_counter()
    entries = {}
    for name, value in params.items():
        lay = layouts.get(name)
        if lay is None:
            if isinstance(value, (list, tuple)):
                raise MXNetError(
                    f"save_sharded_checkpoint: {name!r} is a slice list "
                    "but has no layouts entry describing its split")
            entries[name] = value
            continue
        parts, dim = int(lay["parts"]), int(lay.get("dim", 0))
        if isinstance(value, (list, tuple)):
            slices = list(value)
            if len(slices) != parts:
                raise MXNetError(
                    f"save_sharded_checkpoint: {name!r} has {len(slices)} "
                    f"slices but its layout declares parts={parts}")
        else:
            host = value.asnumpy() if hasattr(value, "asnumpy") else value
            if host.shape[dim] % parts:
                raise MXNetError(
                    f"save_sharded_checkpoint: {name!r} dim {dim} of size "
                    f"{host.shape[dim]} does not split into {parts} equal "
                    f"{lay.get('axis', '?')}-slices")
            import numpy as _np

            slices = _np.split(host, parts, axis=dim)
        import numpy as _np

        for j, s in enumerate(slices):
            if not isinstance(s, NDArray):
                if hasattr(s, "asnumpy"):
                    s = s.asnumpy()
                s = NDArray(_np.ascontiguousarray(s))
            entries[_slice_name(name, j)] = s
    # synchronous half ends here: host snapshot of every entry plus the
    # trainer/data blobs — the background thread touches no live state
    entries = _snapshot_params(entries)
    trainer_blob = _trainer_blob(trainer) if trainer is not None else None
    data_blob = (_data_state_blob(data_state) if data_state is not None
                 else None)
    stall_ms = (_time.perf_counter() - tw) * 1e3
    names = list(entries)

    def commit():
        from ..ndarray.utils import save_parameters_buffer

        shard_table = []
        for i in range(num_shards):
            own = names[i::num_shards]
            blob = _pack([("params", save_parameters_buffer(
                {n: entries[n] for n in own}))],
                {"shard": i, "num_shards": num_shards})
            spath = _shard_path(path, i, num_shards)
            _write_container(spath, blob, shard=i)
            shard_table.append({"name": os.path.basename(spath),
                                "crc": zlib.crc32(blob), "params": own})
        manifest = {"shards": shard_table, "num_shards": num_shards,
                    "mesh_axes": dict(mesh_axes or {axis: num_shards}),
                    "axis": axis}
        if layouts:
            manifest["layouts"] = {
                n: {"axis": lay.get("axis", "tp"),
                    "dim": int(lay.get("dim", 0)),
                    "parts": int(lay["parts"])}
                for n, lay in layouts.items()}
        mmeta = dict(meta or {})
        mmeta.update({"sharded": True, "num_shards": num_shards,
                      "mesh_axes": manifest["mesh_axes"], "axis": axis})
        sections = [("manifest", json.dumps(manifest).encode())]
        if trainer_blob is not None:
            sections.append(("trainer", trainer_blob))
        if data_blob is not None:
            sections.append(("datastate", data_blob))
        _write_container(path, _pack(sections, mmeta), shard="manifest")
        _prof.record_duration("resilience::checkpoint_save", "resilience",
                              t0, args={"path": os.path.basename(str(path)),
                                        "shards": num_shards})
        _counters.incr("resilience.checkpoints_saved")

    if async_write:
        _note_stall(stall_ms)
        return _spawn_commit(commit, path, stall_ms)
    commit()
    return path


def _note_reshard(path, saved_axes, cur_axes):
    """Count + warn one reshard-on-resume event, split by mesh axis:
    ``resilience.reshard_resumes`` fires once per load whose layout
    changed at all, and ``resilience.reshard_resumes[<ax>]`` names each
    axis whose extent differs between the saving and resuming mesh."""
    changed = sorted(
        ax for ax in set(saved_axes) | set(cur_axes)
        if int(saved_axes.get(ax, 1)) != int(cur_axes.get(ax, 1)))
    if not changed:
        return
    _counters.incr("resilience.reshard_resumes")
    for ax in changed:
        _counters.incr(f"resilience.reshard_resumes[{ax}]")
    if _prof.ENABLED:
        _prof.record_instant("resilience::reshard", "resilience",
                             args={"axes": changed,
                                   "from": dict(saved_axes),
                                   "to": dict(cur_axes)})
    import warnings

    frm = "×".join(f"{a}{saved_axes.get(a, 1)}" for a in changed)
    to = "×".join(f"{a}{cur_axes.get(a, 1)}" for a in changed)
    warnings.warn(
        f"resharding checkpoint {os.path.basename(str(path))}: saved at "
        f"{frm}, restoring onto {to}", RuntimeWarning, stacklevel=4)


def _reassemble_layouts(path, params, manifest):
    """Rebuild full tensors from the ``name::NN`` tensor-split slices a
    layout-carrying save wrote (tp/pp-sharded parameters). The manifest's
    layout is authoritative: a missing/mismatched slice set — the
    signature of a tp-extent change the shards cannot express — raises
    :class:`CheckpointCorruptError` loudly instead of silently misplacing
    shard contents."""
    import numpy as _np

    from ..ndarray.ndarray import NDArray

    for name, lay in (manifest.get("layouts") or {}).items():
        parts, dim = int(lay["parts"]), int(lay.get("dim", 0))
        slices = []
        missing = []
        for j in range(parts):
            key = _slice_name(name, j)
            if key in params:
                slices.append(params.pop(key))
            else:
                missing.append(key)
        if missing:
            raise CheckpointCorruptError(
                f"{path}: laid-out parameter {name!r} (axis "
                f"{lay.get('axis')!r}, {parts} parts) cannot be "
                f"reconstructed — slice(s) {missing} are absent from the "
                "shard set; a save under a different tp extent cannot be "
                "reinterpreted, resave or restore the matching layout")
        if name in params:
            raise CheckpointCorruptError(
                f"{path}: parameter {name!r} appears both whole and as "
                f"{parts} layout slices — ambiguous shard set")
        full = _np.concatenate([s.asnumpy() for s in slices], axis=dim)
        params[name] = NDArray(_np.ascontiguousarray(full))
    return params


def _load_sharded(path, sections, meta, net=None, trainer=None,
                  mesh_axes=None, data_iter=None):
    """Manifest half of :func:`load_checkpoint`: validate every shard
    (manifest CRC of the file bytes, then the shard's own container CRC),
    reassemble the full parameter dict — including tensor-split (tp/pp)
    slices recorded in the manifest's ``layouts`` — and restore it onto
    the CURRENT mesh layout: the saving layout in ``meta['mesh_axes']``
    does not have to match (reshard-on-resume). ``mesh_axes`` names the
    resuming layout for the per-axis reshard accounting; when omitted it
    is inferred from ``net``'s replica count (the pure-dp path)."""
    from ..ndarray.utils import load_parameters_buffer

    if trainer is not None and "trainer" not in sections:
        raise MXNetError(f"{path}: sharded checkpoint has no trainer "
                         "section")
    try:
        manifest = json.loads(sections["manifest"])
    except (KeyError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{path}: bad sharded manifest: {e}") from None
    directory = os.path.dirname(os.path.abspath(path))
    params = {}
    for entry in manifest.get("shards", []):
        spath = os.path.join(directory, entry["name"])
        try:
            with open(spath, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"{path}: missing shard {entry['name']} ({e})") from None
        actual = zlib.crc32(raw)
        if actual != entry["crc"]:
            raise CheckpointCorruptError(
                f"{path}: shard {entry['name']} CRC mismatch (manifest "
                f"{entry['crc']:#010x}, actual {actual:#010x})")
        ssec, _smeta = _unpack(raw, path=spath)
        if "params" not in ssec:
            raise CheckpointCorruptError(
                f"{path}: shard {entry['name']} has no params section")
        params.update(load_parameters_buffer(ssec["params"]))
    params = _reassemble_layouts(path, params, manifest)
    saved_axes = dict(meta.get("mesh_axes") or {})
    axis = meta.get("axis", "dp")
    saved_axes.setdefault(axis, int(meta.get("num_shards", 1)))
    if net is not None:
        net_params = net.collect_params()
        missing = set(net_params) - set(params)
        if missing:
            raise MXNetError(
                f"{path}: sharded checkpoint missing parameters "
                f"{sorted(missing)}")
        if mesh_axes is None:
            cur_dp = max([len(p._data) for p in net_params.values()
                          if p._data is not None] or [1])
            mesh_axes = {axis: cur_dp}
        _note_reshard(path, saved_axes, mesh_axes)
        for name, p in net_params.items():
            p.set_data(params[name])
    elif mesh_axes is not None:
        # no net to restore into (e.g. a ShardedTrainer resume pushes the
        # returned dict itself) — the caller still declared the resuming
        # layout, so the reshard event is still accounted per axis
        _note_reshard(path, saved_axes, mesh_axes)
    if trainer is not None:
        _restore_trainer(trainer, sections["trainer"])
    _restore_data_iter(path, sections, data_iter)
    return params, meta


def load_checkpoint(path, net=None, trainer=None, mesh_axes=None,
                    data_iter=None):
    """Load + validate one checkpoint; restores into ``net`` / ``trainer``
    when given. Raises :class:`CheckpointCorruptError` on a bad file
    (nothing is restored in that case). Sharded manifests (see
    :func:`save_sharded_checkpoint`) reassemble from their shard files —
    tensor-split (tp/pp) slices included — and may restore onto a
    different mesh layout than they were saved with; pass ``mesh_axes``
    (``{"dp": 2, "tp": 2}``-style) to declare the resuming layout for the
    per-axis reshard accounting. ``data_iter`` restores an iterator's
    position from the checkpoint's ``datastate`` section (see
    ``save_checkpoint(..., data_state=...)``) — a checkpoint without one
    warns and leaves the iterator untouched. Returns
    ``(params_dict, meta)``."""
    from ..ndarray.utils import load_parameters_buffer

    with open(path, "rb") as f:
        raw = f.read()
    sections, meta = _unpack(raw, path=str(path))
    if meta.get("sharded"):
        return _load_sharded(path, sections, meta, net=net,
                             trainer=trainer, mesh_axes=mesh_axes,
                             data_iter=data_iter)
    if "params" not in sections:
        raise CheckpointCorruptError(f"{path}: no params section")
    if trainer is not None and "trainer" not in sections:
        # validated BEFORE any mutation: a params-only checkpoint loaded
        # with a trainer must fail atomically, not leave checkpoint
        # weights paired with stale optimizer state
        raise MXNetError(f"{path}: checkpoint has no trainer section")
    params = load_parameters_buffer(sections["params"])
    if net is not None:
        net_params = net.collect_params()
        missing = set(net_params) - set(params)
        if missing:
            raise MXNetError(
                f"{path}: checkpoint missing parameters {sorted(missing)}")
        for name, p in net_params.items():
            p.set_data(params[name])
    if trainer is not None:
        _restore_trainer(trainer, sections["trainer"])
    _restore_data_iter(path, sections, data_iter)
    return params, meta


class CheckpointManager:
    """Numbered atomic checkpoints in a directory, with last-good rollback.

    Files are ``<prefix>-<step:012d>.ckpt``; ``load_latest`` walks newest →
    oldest and quarantines corrupt files as ``<name>.corrupt`` instead of
    failing, so one torn/bit-rotted checkpoint costs one save interval, not
    the whole run.

    **Async writes** (``async_write=True`` or ``MXNET_CKPT_ASYNC=1``):
    :meth:`save` stalls only for the host snapshot and hands
    serialization + atomic write to a background thread. A generation is
    advertised only after its COMMIT (the ``os.replace``) lands — the
    manager's own reads (:meth:`save`, :meth:`load_latest`,
    :meth:`quarantine`, :meth:`wait`) all fence on the in-flight write
    first, and since :meth:`list_steps` is disk truth an uncommitted
    write is simply invisible. A save arriving while the previous one is
    still writing counts ``resilience.ckpt_backpressure`` (saves are
    outpacing checkpoint I/O) before joining; a write that dies mid-flight
    never commits, so readers fall back to last-good.
    """

    def __init__(self, directory, prefix="ckpt", max_keep=3,
                 async_write=None):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.prefix = prefix
        self.max_keep = int(max_keep)
        if async_write is None:
            from .. import config

            async_write = bool(config.get("MXNET_CKPT_ASYNC"))
        self.async_write = bool(async_write)
        self.last_stall_ms = None
        self._inflight = None

    def _path(self, step):
        return os.path.join(self.directory, f"{self.prefix}-{step:012d}.ckpt")

    def list_steps(self):
        """Existing checkpoint steps, ascending."""
        steps = []
        want = self.prefix + "-"
        for name in os.listdir(self.directory):
            if name.startswith(want) and name.endswith(".ckpt"):
                try:
                    steps.append(int(name[len(want):-len(".ckpt")]))
                except ValueError:
                    continue
        return sorted(steps)

    def _fence(self, next_step=None):
        """Join any in-flight async write (the consistency fence). When a
        NEW save arrives while the previous generation is still writing
        (``next_step`` given), the backpressure is counted and warned
        about first — an operator must be able to see saves outpacing
        checkpoint I/O, not just feel the joins."""
        handle, self._inflight = self._inflight, None
        if handle is None:
            return
        if next_step is not None and handle.in_flight():
            _counters.incr("resilience.ckpt_backpressure")
            n = _counters.get("resilience.ckpt_backpressure")
            if _counters.should_warn(n):
                import warnings

                warnings.warn(
                    f"checkpoint save backpressure: "
                    f"{os.path.basename(handle.path)} still writing when "
                    f"the step-{next_step} save arrived ({n} "
                    "occurrence(s) this process) — saves are outpacing "
                    "checkpoint I/O, lengthen the save period or speed up "
                    "the checkpoint disk", RuntimeWarning, stacklevel=4)
        if handle.join():
            self._rotate()

    def wait(self):
        """Public fence: block until the in-flight async write (if any)
        commits and rotation runs. Returns True when the last write landed
        cleanly (or none was pending) — call before process exit so a
        preempted worker never abandons a half-written generation."""
        handle = self._inflight
        self._fence()
        return handle is None or handle.error is None

    def save(self, step, net=None, trainer=None, params=None, meta=None,
             sharded=False, num_shards=None, mesh_axes=None, axis="dp",
             layouts=None, data_state=None):
        self._fence(next_step=step)
        meta = dict(meta or {})
        meta["step"] = int(step)
        if sharded:
            out = save_sharded_checkpoint(
                self._path(step), net=net, trainer=trainer, params=params,
                meta=meta, num_shards=num_shards, mesh_axes=mesh_axes,
                axis=axis, layouts=layouts, data_state=data_state,
                async_write=self.async_write)
        else:
            out = save_checkpoint(self._path(step), net=net,
                                  trainer=trainer, params=params,
                                  meta=meta, data_state=data_state,
                                  async_write=self.async_write)
        if self.async_write:
            self._inflight = out
            self.last_stall_ms = out.stall_ms
            return out.path
        self._rotate()
        return out

    def _shard_files(self, step):
        """LIVE shard siblings of step's manifest (present only for
        sharded saves). Anchored to the ``shardII-ofNN`` suffix so
        already-quarantined ``.corrupt``/``.poisoned`` siblings are never
        swept back up — rotation must not delete quarantined evidence,
        and quarantine must not double-rename it."""
        import re

        want = os.path.basename(self._path(step)) + ".shard"
        live = re.compile(r"\.shard\d+-of\d+$")
        return sorted(os.path.join(self.directory, n)
                      for n in os.listdir(self.directory)
                      if n.startswith(want) and live.search(n))

    def _rotate(self):
        steps = self.list_steps()
        while len(steps) > self.max_keep:
            old = steps.pop(0)
            for path in [self._path(old)] + self._shard_files(old):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def quarantine(self, step, suffix=".corrupt"):
        """Move one checkpoint out of the rotation by renaming it (and,
        for sharded checkpoints, every shard sibling) with ``suffix``
        (``.corrupt`` for CRC/structure failures, ``.poisoned`` when the
        guardrails find non-finite parameters in a CRC-valid file).
        Returns True if the manifest/container was moved.

        Every quarantine is counted (``resilience.checkpoints_quarantined``)
        and warned about by file name, rate-limited to powers of ten — an
        operator watching a fleet must be able to see corruption
        *frequency*, not just the per-run rollback."""
        self._fence()
        path = self._path(step)
        try:
            os.replace(path, path + suffix)
        except OSError:
            return False
        for spath in self._shard_files(step):
            try:
                os.replace(spath, spath + suffix)
            except OSError:
                pass  # manifest is gone from rotation either way
        _counters.incr("resilience.checkpoints_quarantined")
        n = _counters.get("resilience.checkpoints_quarantined")
        if _prof.ENABLED:
            _prof.record_instant("resilience::checkpoint_quarantine",
                                 "resilience",
                                 args={"file": os.path.basename(path),
                                       "suffix": suffix})
        from ..profiler import recorder as _recorder

        _recorder.dump("checkpoint_quarantine",
                       args={"file": os.path.basename(path),
                             "suffix": suffix, "count": n})
        if _counters.should_warn(n):
            import warnings

            warnings.warn(
                f"checkpoint quarantined: {os.path.basename(path)} -> "
                f"*{suffix} ({n} quarantine(s) so far this process) — "
                "rising counts mean recurring corruption (disk, copy "
                "tool, or a poisoning bug), not one-off bit rot",
                RuntimeWarning, stacklevel=3)
        return True

    def load_latest(self, net=None, trainer=None, mesh_axes=None,
                    data_iter=None):
        """Restore the newest valid checkpoint; corrupt files roll back to
        the previous one. Returns its ``meta`` dict (contains ``step``),
        or ``None`` when no valid checkpoint exists. ``mesh_axes``
        declares the resuming mesh layout (forwarded to
        :func:`load_checkpoint` for the per-axis reshard accounting);
        ``data_iter`` restores the iterator position saved alongside.
        Fences on any in-flight async write first, so a load never races
        its own manager's background writer."""
        import warnings

        self._fence()
        for step in reversed(self.list_steps()):
            path = self._path(step)
            try:
                _, meta = load_checkpoint(path, net=net, trainer=trainer,
                                          mesh_axes=mesh_axes,
                                          data_iter=data_iter)
                return meta
            except CheckpointCorruptError as e:
                _counters.incr("resilience.checkpoints_corrupt")
                warnings.warn(
                    f"skipping corrupt checkpoint: {e}", RuntimeWarning,
                    stacklevel=2)
                self.quarantine(step)
            except MXNetError as e:
                # CRC-valid but incompatible with THIS net/trainer (e.g. a
                # params-only snapshot restored with a trainer, missing
                # params after a model change): the file is healthy, so
                # don't quarantine it — but keep rolling back, an older
                # compatible checkpoint beats refusing to resume
                warnings.warn(
                    f"skipping incompatible checkpoint: {e}",
                    RuntimeWarning, stacklevel=2)
        return None


class ResilientCheckpointHandler(TrainBegin, BatchEnd, EpochEnd, TrainEnd):
    """Estimator event handler: periodic atomic checkpoints + resume.

    Unlike the reference-shaped ``CheckpointHandler`` (two files, plain
    writes), this one writes the single-file atomic container with the
    Trainer state and progress meta inside, so the worker can die at ANY
    point — including between params and states — and resume consistently.

    Usage::

        handler = ResilientCheckpointHandler(dir, batch_period=10)
        start = handler.resume(est)      # 0 on a fresh run
        est.fit(train_data, epochs=N, event_handlers=[handler])
    """

    def __init__(self, model_dir, model_prefix="model", epoch_period=1,
                 batch_period=None, max_keep=3, data_iter=None,
                 async_write=None):
        self.manager = CheckpointManager(model_dir, prefix=model_prefix,
                                         max_keep=max_keep,
                                         async_write=async_write)
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0
        # resumable data iterator: its state_dict rides in every save and
        # resume() restores it, so the epoch continues sample-exact
        self.data_iter = data_iter

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        # injection site for the kill-and-resume scenario: dies AFTER the
        # optimizer step, BEFORE the periodic save below — the worst case
        fault_slot = _faults_slot()
        if fault_slot is not None:
            fault_slot.check("estimator:batch")
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator)

    def train_end(self, estimator, *args, **kwargs):
        # fence: a run must not exit with its final save still in flight
        self.manager.wait()

    def _save(self, estimator):
        data_state = (self.data_iter.state_dict()
                      if self.data_iter is not None else None)
        self.manager.save(
            self.current_batch, net=estimator.net, trainer=estimator.trainer,
            meta={"batch": self.current_batch, "epoch": self.current_epoch},
            data_state=data_state)

    def resume(self, estimator):
        """Restore the newest valid checkpoint into the estimator's net and
        trainer (and the data iterator's position, when one was given).
        Returns the batch index to continue from (0 = fresh)."""
        meta = self.manager.load_latest(net=estimator.net,
                                        trainer=estimator.trainer,
                                        data_iter=self.data_iter)
        if meta is None:
            return 0
        self.current_batch = int(meta.get("batch", meta.get("step", 0)))
        self.current_epoch = int(meta.get("epoch", 0))
        return self.current_batch


def _faults_slot():
    from . import faults

    return faults.get_plan()
