"""Crash-safe atomic checkpoints with resume.

File format (``MXTPUCKPT1``): a single binary container so a checkpoint is
either entirely present or entirely absent — no params/.states file pairs
that can go out of sync when the worker dies between the two writes::

    magic    "MXTPUCKPT1"                 (10 bytes)
    hdr_len  uint32 LE                    (4 bytes)
    header   JSON: {"sections": [{"name", "offset", "length"}], "meta": {}}
    payload  concatenated section bytes   (params = mx.nd zip container,
                                           trainer = pickled states blob)
    footer   uint32 LE CRC32 of everything above + "CKPTEND1" (12 bytes)

Write protocol (the only crash-safe sequence POSIX gives us): serialize to
``<path>.tmp.<pid>``, flush + ``fsync`` the file, ``os.replace`` onto the
final name (atomic within a filesystem), then ``fsync`` the directory so
the rename itself survives power loss. A reader therefore sees either the
old complete file or the new complete file; a torn write is impossible at
the final name, and the CRC footer catches the remaining cases (bit rot,
truncation of the temp file by a copy tool, a partially-synced disk).

:class:`CheckpointManager` numbers checkpoints by step and its
:meth:`~CheckpointManager.load_latest` walks newest → oldest, *skipping*
(and quarantining as ``.corrupt``) any file whose magic/CRC fails —
rollback to last-good instead of refusing to start.

**Sharded (reshard-on-resume) checkpoints** (``save(...,
sharded=True)``): the manifest keeps the ``<prefix>-<step>.ckpt`` name
(so ``load_latest`` walks it unchanged) and holds the trainer blob plus a
JSON shard table recording the saving mesh/axis layout
(``{"dp": 8}``) and every shard's CRC32; the parameters are
round-robin-partitioned by name across ``num_shards`` sibling files
(``<name>.ckpt.shard00-of08`` …), each itself a full ``MXTPUCKPT1``
container with its own CRC. Because each shard carries whole tensors
(the ZeRO-style name partition, not a tensor split), a load reassembles
the full parameter dict from *however many* shards were written and
restores it onto the **current** context list — a dp8 save resumes on a
dp4 mesh (or any other size) with no conversion step. A corrupt/missing
shard fails the whole step's load atomically and the manager quarantines
the manifest *and* its shards together.

:class:`ResilientCheckpointHandler` is the ``gluon.contrib.estimator``
integration: periodic atomic snapshots of block parameters + Trainer
state + progress meta, and a :meth:`~ResilientCheckpointHandler.resume`
that restores all three so an injected mid-epoch worker death continues on
the same loss trajectory.
"""
from __future__ import annotations

import json
import os
import struct
import zlib

from ..base import MXNetError
from ..gluon.contrib.estimator.event_handler import (BatchEnd, EpochEnd,
                                                     TrainBegin)
from ..profiler import core as _prof
from . import counters as _counters

MAGIC = b"MXTPUCKPT1"
END_MAGIC = b"CKPTEND1"


class CheckpointCorruptError(MXNetError):
    """The file failed magic/CRC/structure validation on load."""


# -- low-level container ----------------------------------------------------


def _pack(sections, meta):
    """sections: list of (name, bytes). Returns the full container bytes."""
    hdr = {"sections": [], "meta": meta or {}}
    offset = 0
    for name, blob in sections:
        hdr["sections"].append(
            {"name": name, "offset": offset, "length": len(blob)})
        offset += len(blob)
    hdr_bytes = json.dumps(hdr).encode()
    body = MAGIC + struct.pack("<I", len(hdr_bytes)) + hdr_bytes \
        + b"".join(blob for _, blob in sections)
    return body + struct.pack("<I", zlib.crc32(body)) + END_MAGIC


def _unpack(raw, path="<buffer>"):
    """Validate magic + CRC footer; returns ({name: bytes}, meta)."""
    foot = 4 + len(END_MAGIC)
    if len(raw) < len(MAGIC) + 4 + foot or not raw.startswith(MAGIC):
        raise CheckpointCorruptError(f"{path}: not a {MAGIC.decode()} file")
    if not raw.endswith(END_MAGIC):
        raise CheckpointCorruptError(
            f"{path}: missing {END_MAGIC.decode()} footer (torn write?)")
    body, crc_raw = raw[:-foot], raw[-foot:-len(END_MAGIC)]
    (crc,) = struct.unpack("<I", crc_raw)
    actual = zlib.crc32(body)
    if crc != actual:
        raise CheckpointCorruptError(
            f"{path}: CRC mismatch (stored {crc:#010x}, actual "
            f"{actual:#010x}) — checkpoint is corrupt")
    (hdr_len,) = struct.unpack("<I", body[len(MAGIC):len(MAGIC) + 4])
    hdr_start = len(MAGIC) + 4
    try:
        hdr = json.loads(body[hdr_start:hdr_start + hdr_len])
    except ValueError as e:
        raise CheckpointCorruptError(f"{path}: bad header JSON: {e}") from None
    payload = body[hdr_start + hdr_len:]
    out = {}
    for s in hdr["sections"]:
        blob = payload[s["offset"]:s["offset"] + s["length"]]
        if len(blob) != s["length"]:
            raise CheckpointCorruptError(
                f"{path}: section {s['name']!r} truncated")
        out[s["name"]] = blob
    return out, hdr.get("meta", {})


def _atomic_write(path, raw):
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    # fsync the directory: os.replace is atomic in the namespace but the
    # rename record itself needs a journal flush to survive power loss
    dirfd = None
    try:
        dirfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        os.fsync(dirfd)
    except OSError:
        pass  # e.g. filesystems that refuse O_RDONLY dir fsync
    finally:
        if dirfd is not None:
            os.close(dirfd)


# -- public save/load -------------------------------------------------------


def _trainer_blob(trainer):
    return trainer.states_to_bytes()


def _restore_trainer(trainer, raw):
    trainer.load_states_from_bytes(raw)


def save_checkpoint(path, net=None, trainer=None, params=None, meta=None):
    """Atomically write one checkpoint file covering block parameters
    (``net`` or an explicit name->NDArray ``params`` dict) and, when given,
    the Trainer's optimizer state + step count. Returns ``path``."""
    from ..ndarray.utils import save_parameters_buffer

    if net is None and params is None:
        raise MXNetError("save_checkpoint needs a net or a params dict")
    if params is None:
        params = net._params_data()
    sections = [("params", save_parameters_buffer(params))]
    if trainer is not None:
        sections.append(("trainer", _trainer_blob(trainer)))
    t0 = _prof.begin()
    _atomic_write(path, _pack(sections, meta))
    _prof.record_duration("resilience::checkpoint_save", "resilience", t0,
                          args={"path": os.path.basename(str(path))})
    _counters.incr("resilience.checkpoints_saved")
    return path


def _shard_path(path, i, n):
    return f"{path}.shard{i:02d}-of{n:02d}"


def _slice_name(name, j):
    """Entry name of tensor-split slice ``j`` of parameter ``name``
    inside the shard containers (layout-carrying saves only)."""
    return f"{name}::{j:02d}"


def save_sharded_checkpoint(path, net=None, trainer=None, params=None,
                            meta=None, num_shards=None, mesh_axes=None,
                            axis="dp", layouts=None):
    """Write one *sharded* checkpoint: ``num_shards`` sibling containers
    each holding a round-robin name-partition of the parameters (whole
    tensors — a ZeRO-style ownership split, not a tensor split), plus a
    manifest at ``path`` recording the saving mesh/axis layout and every
    shard's CRC32, with the trainer blob inside the manifest.

    ``layouts`` records *tensor-split* (tp/pp-sharded) parameters:
    ``{name: {"axis": <mesh axis>, "dim": <tensor dim>, "parts": N}}``.
    A laid-out parameter's value may be the full tensor (split here into
    ``parts`` equal slices along ``dim``) or a pre-split list of the
    per-rank slices in rank order; either way each slice is stored as its
    own ``name::NN`` entry and the manifest carries the layout, so a load
    can reassemble the full tensor and re-lay it out onto whatever mesh
    is current (see :func:`_load_sharded`).

    Write order is shards-first, manifest-last (each write atomic): a
    crash mid-sequence leaves shard files with no manifest — invisible to
    ``CheckpointManager.load_latest``, cleaned by rotation — never a
    manifest pointing at missing shards. Returns ``path``."""
    from ..ndarray.ndarray import NDArray
    from ..ndarray.utils import save_parameters_buffer

    if net is None and params is None:
        raise MXNetError("save_sharded_checkpoint needs a net or params")
    if params is None:
        params = net._params_data()
    num_shards = int(num_shards or 1)
    if num_shards < 1:
        raise MXNetError(f"num_shards must be >= 1, got {num_shards}")
    layouts = dict(layouts or {})
    entries = {}
    for name, value in params.items():
        lay = layouts.get(name)
        if lay is None:
            if isinstance(value, (list, tuple)):
                raise MXNetError(
                    f"save_sharded_checkpoint: {name!r} is a slice list "
                    "but has no layouts entry describing its split")
            entries[name] = value
            continue
        parts, dim = int(lay["parts"]), int(lay.get("dim", 0))
        if isinstance(value, (list, tuple)):
            slices = list(value)
            if len(slices) != parts:
                raise MXNetError(
                    f"save_sharded_checkpoint: {name!r} has {len(slices)} "
                    f"slices but its layout declares parts={parts}")
        else:
            host = value.asnumpy() if hasattr(value, "asnumpy") else value
            if host.shape[dim] % parts:
                raise MXNetError(
                    f"save_sharded_checkpoint: {name!r} dim {dim} of size "
                    f"{host.shape[dim]} does not split into {parts} equal "
                    f"{lay.get('axis', '?')}-slices")
            import numpy as _np

            slices = _np.split(host, parts, axis=dim)
        import numpy as _np

        for j, s in enumerate(slices):
            if not isinstance(s, NDArray):
                if hasattr(s, "asnumpy"):
                    s = s.asnumpy()
                s = NDArray(_np.ascontiguousarray(s))
            entries[_slice_name(name, j)] = s
    names = list(entries)
    t0 = _prof.begin()
    shard_table = []
    for i in range(num_shards):
        own = names[i::num_shards]
        blob = _pack([("params", save_parameters_buffer(
            {n: entries[n] for n in own}))],
            {"shard": i, "num_shards": num_shards})
        spath = _shard_path(path, i, num_shards)
        _atomic_write(spath, blob)
        shard_table.append({"name": os.path.basename(spath),
                            "crc": zlib.crc32(blob), "params": own})
    manifest = {"shards": shard_table, "num_shards": num_shards,
                "mesh_axes": dict(mesh_axes or {axis: num_shards}),
                "axis": axis}
    if layouts:
        manifest["layouts"] = {
            n: {"axis": lay.get("axis", "tp"), "dim": int(lay.get("dim", 0)),
                "parts": int(lay["parts"])}
            for n, lay in layouts.items()}
    mmeta = dict(meta or {})
    mmeta.update({"sharded": True, "num_shards": num_shards,
                  "mesh_axes": manifest["mesh_axes"], "axis": axis})
    sections = [("manifest", json.dumps(manifest).encode())]
    if trainer is not None:
        sections.append(("trainer", _trainer_blob(trainer)))
    _atomic_write(path, _pack(sections, mmeta))
    _prof.record_duration("resilience::checkpoint_save", "resilience", t0,
                          args={"path": os.path.basename(str(path)),
                                "shards": num_shards})
    _counters.incr("resilience.checkpoints_saved")
    return path


def _note_reshard(path, saved_axes, cur_axes):
    """Count + warn one reshard-on-resume event, split by mesh axis:
    ``resilience.reshard_resumes`` fires once per load whose layout
    changed at all, and ``resilience.reshard_resumes[<ax>]`` names each
    axis whose extent differs between the saving and resuming mesh."""
    changed = sorted(
        ax for ax in set(saved_axes) | set(cur_axes)
        if int(saved_axes.get(ax, 1)) != int(cur_axes.get(ax, 1)))
    if not changed:
        return
    _counters.incr("resilience.reshard_resumes")
    for ax in changed:
        _counters.incr(f"resilience.reshard_resumes[{ax}]")
    if _prof.ENABLED:
        _prof.record_instant("resilience::reshard", "resilience",
                             args={"axes": changed,
                                   "from": dict(saved_axes),
                                   "to": dict(cur_axes)})
    import warnings

    frm = "×".join(f"{a}{saved_axes.get(a, 1)}" for a in changed)
    to = "×".join(f"{a}{cur_axes.get(a, 1)}" for a in changed)
    warnings.warn(
        f"resharding checkpoint {os.path.basename(str(path))}: saved at "
        f"{frm}, restoring onto {to}", RuntimeWarning, stacklevel=4)


def _reassemble_layouts(path, params, manifest):
    """Rebuild full tensors from the ``name::NN`` tensor-split slices a
    layout-carrying save wrote (tp/pp-sharded parameters). The manifest's
    layout is authoritative: a missing/mismatched slice set — the
    signature of a tp-extent change the shards cannot express — raises
    :class:`CheckpointCorruptError` loudly instead of silently misplacing
    shard contents."""
    import numpy as _np

    from ..ndarray.ndarray import NDArray

    for name, lay in (manifest.get("layouts") or {}).items():
        parts, dim = int(lay["parts"]), int(lay.get("dim", 0))
        slices = []
        missing = []
        for j in range(parts):
            key = _slice_name(name, j)
            if key in params:
                slices.append(params.pop(key))
            else:
                missing.append(key)
        if missing:
            raise CheckpointCorruptError(
                f"{path}: laid-out parameter {name!r} (axis "
                f"{lay.get('axis')!r}, {parts} parts) cannot be "
                f"reconstructed — slice(s) {missing} are absent from the "
                "shard set; a save under a different tp extent cannot be "
                "reinterpreted, resave or restore the matching layout")
        if name in params:
            raise CheckpointCorruptError(
                f"{path}: parameter {name!r} appears both whole and as "
                f"{parts} layout slices — ambiguous shard set")
        full = _np.concatenate([s.asnumpy() for s in slices], axis=dim)
        params[name] = NDArray(_np.ascontiguousarray(full))
    return params


def _load_sharded(path, sections, meta, net=None, trainer=None,
                  mesh_axes=None):
    """Manifest half of :func:`load_checkpoint`: validate every shard
    (manifest CRC of the file bytes, then the shard's own container CRC),
    reassemble the full parameter dict — including tensor-split (tp/pp)
    slices recorded in the manifest's ``layouts`` — and restore it onto
    the CURRENT mesh layout: the saving layout in ``meta['mesh_axes']``
    does not have to match (reshard-on-resume). ``mesh_axes`` names the
    resuming layout for the per-axis reshard accounting; when omitted it
    is inferred from ``net``'s replica count (the pure-dp path)."""
    from ..ndarray.utils import load_parameters_buffer

    if trainer is not None and "trainer" not in sections:
        raise MXNetError(f"{path}: sharded checkpoint has no trainer "
                         "section")
    try:
        manifest = json.loads(sections["manifest"])
    except (KeyError, ValueError) as e:
        raise CheckpointCorruptError(
            f"{path}: bad sharded manifest: {e}") from None
    directory = os.path.dirname(os.path.abspath(path))
    params = {}
    for entry in manifest.get("shards", []):
        spath = os.path.join(directory, entry["name"])
        try:
            with open(spath, "rb") as f:
                raw = f.read()
        except OSError as e:
            raise CheckpointCorruptError(
                f"{path}: missing shard {entry['name']} ({e})") from None
        actual = zlib.crc32(raw)
        if actual != entry["crc"]:
            raise CheckpointCorruptError(
                f"{path}: shard {entry['name']} CRC mismatch (manifest "
                f"{entry['crc']:#010x}, actual {actual:#010x})")
        ssec, _smeta = _unpack(raw, path=spath)
        if "params" not in ssec:
            raise CheckpointCorruptError(
                f"{path}: shard {entry['name']} has no params section")
        params.update(load_parameters_buffer(ssec["params"]))
    params = _reassemble_layouts(path, params, manifest)
    saved_axes = dict(meta.get("mesh_axes") or {})
    axis = meta.get("axis", "dp")
    saved_axes.setdefault(axis, int(meta.get("num_shards", 1)))
    if net is not None:
        net_params = net.collect_params()
        missing = set(net_params) - set(params)
        if missing:
            raise MXNetError(
                f"{path}: sharded checkpoint missing parameters "
                f"{sorted(missing)}")
        if mesh_axes is None:
            cur_dp = max([len(p._data) for p in net_params.values()
                          if p._data is not None] or [1])
            mesh_axes = {axis: cur_dp}
        _note_reshard(path, saved_axes, mesh_axes)
        for name, p in net_params.items():
            p.set_data(params[name])
    elif mesh_axes is not None:
        # no net to restore into (e.g. a ShardedTrainer resume pushes the
        # returned dict itself) — the caller still declared the resuming
        # layout, so the reshard event is still accounted per axis
        _note_reshard(path, saved_axes, mesh_axes)
    if trainer is not None:
        _restore_trainer(trainer, sections["trainer"])
    return params, meta


def load_checkpoint(path, net=None, trainer=None, mesh_axes=None):
    """Load + validate one checkpoint; restores into ``net`` / ``trainer``
    when given. Raises :class:`CheckpointCorruptError` on a bad file
    (nothing is restored in that case). Sharded manifests (see
    :func:`save_sharded_checkpoint`) reassemble from their shard files —
    tensor-split (tp/pp) slices included — and may restore onto a
    different mesh layout than they were saved with; pass ``mesh_axes``
    (``{"dp": 2, "tp": 2}``-style) to declare the resuming layout for the
    per-axis reshard accounting. Returns ``(params_dict, meta)``."""
    from ..ndarray.utils import load_parameters_buffer

    with open(path, "rb") as f:
        raw = f.read()
    sections, meta = _unpack(raw, path=str(path))
    if meta.get("sharded"):
        return _load_sharded(path, sections, meta, net=net,
                             trainer=trainer, mesh_axes=mesh_axes)
    if "params" not in sections:
        raise CheckpointCorruptError(f"{path}: no params section")
    if trainer is not None and "trainer" not in sections:
        # validated BEFORE any mutation: a params-only checkpoint loaded
        # with a trainer must fail atomically, not leave checkpoint
        # weights paired with stale optimizer state
        raise MXNetError(f"{path}: checkpoint has no trainer section")
    params = load_parameters_buffer(sections["params"])
    if net is not None:
        net_params = net.collect_params()
        missing = set(net_params) - set(params)
        if missing:
            raise MXNetError(
                f"{path}: checkpoint missing parameters {sorted(missing)}")
        for name, p in net_params.items():
            p.set_data(params[name])
    if trainer is not None:
        _restore_trainer(trainer, sections["trainer"])
    return params, meta


class CheckpointManager:
    """Numbered atomic checkpoints in a directory, with last-good rollback.

    Files are ``<prefix>-<step:012d>.ckpt``; ``load_latest`` walks newest →
    oldest and quarantines corrupt files as ``<name>.corrupt`` instead of
    failing, so one torn/bit-rotted checkpoint costs one save interval, not
    the whole run.
    """

    def __init__(self, directory, prefix="ckpt", max_keep=3):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.prefix = prefix
        self.max_keep = int(max_keep)

    def _path(self, step):
        return os.path.join(self.directory, f"{self.prefix}-{step:012d}.ckpt")

    def list_steps(self):
        """Existing checkpoint steps, ascending."""
        steps = []
        want = self.prefix + "-"
        for name in os.listdir(self.directory):
            if name.startswith(want) and name.endswith(".ckpt"):
                try:
                    steps.append(int(name[len(want):-len(".ckpt")]))
                except ValueError:
                    continue
        return sorted(steps)

    def save(self, step, net=None, trainer=None, params=None, meta=None,
             sharded=False, num_shards=None, mesh_axes=None, axis="dp",
             layouts=None):
        meta = dict(meta or {})
        meta["step"] = int(step)
        if sharded:
            path = save_sharded_checkpoint(
                self._path(step), net=net, trainer=trainer, params=params,
                meta=meta, num_shards=num_shards, mesh_axes=mesh_axes,
                axis=axis, layouts=layouts)
        else:
            path = save_checkpoint(self._path(step), net=net,
                                   trainer=trainer, params=params,
                                   meta=meta)
        self._rotate()
        return path

    def _shard_files(self, step):
        """LIVE shard siblings of step's manifest (present only for
        sharded saves). Anchored to the ``shardII-ofNN`` suffix so
        already-quarantined ``.corrupt``/``.poisoned`` siblings are never
        swept back up — rotation must not delete quarantined evidence,
        and quarantine must not double-rename it."""
        import re

        want = os.path.basename(self._path(step)) + ".shard"
        live = re.compile(r"\.shard\d+-of\d+$")
        return sorted(os.path.join(self.directory, n)
                      for n in os.listdir(self.directory)
                      if n.startswith(want) and live.search(n))

    def _rotate(self):
        steps = self.list_steps()
        while len(steps) > self.max_keep:
            old = steps.pop(0)
            for path in [self._path(old)] + self._shard_files(old):
                try:
                    os.remove(path)
                except OSError:
                    pass

    def quarantine(self, step, suffix=".corrupt"):
        """Move one checkpoint out of the rotation by renaming it (and,
        for sharded checkpoints, every shard sibling) with ``suffix``
        (``.corrupt`` for CRC/structure failures, ``.poisoned`` when the
        guardrails find non-finite parameters in a CRC-valid file).
        Returns True if the manifest/container was moved.

        Every quarantine is counted (``resilience.checkpoints_quarantined``)
        and warned about by file name, rate-limited to powers of ten — an
        operator watching a fleet must be able to see corruption
        *frequency*, not just the per-run rollback."""
        path = self._path(step)
        try:
            os.replace(path, path + suffix)
        except OSError:
            return False
        for spath in self._shard_files(step):
            try:
                os.replace(spath, spath + suffix)
            except OSError:
                pass  # manifest is gone from rotation either way
        _counters.incr("resilience.checkpoints_quarantined")
        n = _counters.get("resilience.checkpoints_quarantined")
        if _prof.ENABLED:
            _prof.record_instant("resilience::checkpoint_quarantine",
                                 "resilience",
                                 args={"file": os.path.basename(path),
                                       "suffix": suffix})
        from ..profiler import recorder as _recorder

        _recorder.dump("checkpoint_quarantine",
                       args={"file": os.path.basename(path),
                             "suffix": suffix, "count": n})
        if _counters.should_warn(n):
            import warnings

            warnings.warn(
                f"checkpoint quarantined: {os.path.basename(path)} -> "
                f"*{suffix} ({n} quarantine(s) so far this process) — "
                "rising counts mean recurring corruption (disk, copy "
                "tool, or a poisoning bug), not one-off bit rot",
                RuntimeWarning, stacklevel=3)
        return True

    def load_latest(self, net=None, trainer=None, mesh_axes=None):
        """Restore the newest valid checkpoint; corrupt files roll back to
        the previous one. Returns its ``meta`` dict (contains ``step``),
        or ``None`` when no valid checkpoint exists. ``mesh_axes``
        declares the resuming mesh layout (forwarded to
        :func:`load_checkpoint` for the per-axis reshard accounting)."""
        import warnings

        for step in reversed(self.list_steps()):
            path = self._path(step)
            try:
                _, meta = load_checkpoint(path, net=net, trainer=trainer,
                                          mesh_axes=mesh_axes)
                return meta
            except CheckpointCorruptError as e:
                _counters.incr("resilience.checkpoints_corrupt")
                warnings.warn(
                    f"skipping corrupt checkpoint: {e}", RuntimeWarning,
                    stacklevel=2)
                self.quarantine(step)
            except MXNetError as e:
                # CRC-valid but incompatible with THIS net/trainer (e.g. a
                # params-only snapshot restored with a trainer, missing
                # params after a model change): the file is healthy, so
                # don't quarantine it — but keep rolling back, an older
                # compatible checkpoint beats refusing to resume
                warnings.warn(
                    f"skipping incompatible checkpoint: {e}",
                    RuntimeWarning, stacklevel=2)
        return None


class ResilientCheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Estimator event handler: periodic atomic checkpoints + resume.

    Unlike the reference-shaped ``CheckpointHandler`` (two files, plain
    writes), this one writes the single-file atomic container with the
    Trainer state and progress meta inside, so the worker can die at ANY
    point — including between params and states — and resume consistently.

    Usage::

        handler = ResilientCheckpointHandler(dir, batch_period=10)
        start = handler.resume(est)      # 0 on a fresh run
        est.fit(train_data, epochs=N, event_handlers=[handler])
    """

    def __init__(self, model_dir, model_prefix="model", epoch_period=1,
                 batch_period=None, max_keep=3):
        self.manager = CheckpointManager(model_dir, prefix=model_prefix,
                                         max_keep=max_keep)
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        # injection site for the kill-and-resume scenario: dies AFTER the
        # optimizer step, BEFORE the periodic save below — the worst case
        fault_slot = _faults_slot()
        if fault_slot is not None:
            fault_slot.check("estimator:batch")
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator)

    def _save(self, estimator):
        self.manager.save(
            self.current_batch, net=estimator.net, trainer=estimator.trainer,
            meta={"batch": self.current_batch, "epoch": self.current_epoch})

    def resume(self, estimator):
        """Restore the newest valid checkpoint into the estimator's net and
        trainer. Returns the batch index to continue from (0 = fresh)."""
        meta = self.manager.load_latest(net=estimator.net,
                                        trainer=estimator.trainer)
        if meta is None:
            return 0
        self.current_batch = int(meta.get("batch", meta.get("step", 0)))
        self.current_epoch = int(meta.get("epoch", 0))
        return self.current_batch


def _faults_slot():
    from . import faults

    return faults.get_plan()
