"""Crash-safe atomic checkpoints with resume.

File format (``MXTPUCKPT1``): a single binary container so a checkpoint is
either entirely present or entirely absent — no params/.states file pairs
that can go out of sync when the worker dies between the two writes::

    magic    "MXTPUCKPT1"                 (10 bytes)
    hdr_len  uint32 LE                    (4 bytes)
    header   JSON: {"sections": [{"name", "offset", "length"}], "meta": {}}
    payload  concatenated section bytes   (params = mx.nd zip container,
                                           trainer = pickled states blob)
    footer   uint32 LE CRC32 of everything above + "CKPTEND1" (12 bytes)

Write protocol (the only crash-safe sequence POSIX gives us): serialize to
``<path>.tmp.<pid>``, flush + ``fsync`` the file, ``os.replace`` onto the
final name (atomic within a filesystem), then ``fsync`` the directory so
the rename itself survives power loss. A reader therefore sees either the
old complete file or the new complete file; a torn write is impossible at
the final name, and the CRC footer catches the remaining cases (bit rot,
truncation of the temp file by a copy tool, a partially-synced disk).

:class:`CheckpointManager` numbers checkpoints by step and its
:meth:`~CheckpointManager.load_latest` walks newest → oldest, *skipping*
(and quarantining as ``.corrupt``) any file whose magic/CRC fails —
rollback to last-good instead of refusing to start.

:class:`ResilientCheckpointHandler` is the ``gluon.contrib.estimator``
integration: periodic atomic snapshots of block parameters + Trainer
state + progress meta, and a :meth:`~ResilientCheckpointHandler.resume`
that restores all three so an injected mid-epoch worker death continues on
the same loss trajectory.
"""
from __future__ import annotations

import json
import os
import struct
import zlib

from ..base import MXNetError
from ..gluon.contrib.estimator.event_handler import (BatchEnd, EpochEnd,
                                                     TrainBegin)
from ..profiler import core as _prof
from . import counters as _counters

MAGIC = b"MXTPUCKPT1"
END_MAGIC = b"CKPTEND1"


class CheckpointCorruptError(MXNetError):
    """The file failed magic/CRC/structure validation on load."""


# -- low-level container ----------------------------------------------------


def _pack(sections, meta):
    """sections: list of (name, bytes). Returns the full container bytes."""
    hdr = {"sections": [], "meta": meta or {}}
    offset = 0
    for name, blob in sections:
        hdr["sections"].append(
            {"name": name, "offset": offset, "length": len(blob)})
        offset += len(blob)
    hdr_bytes = json.dumps(hdr).encode()
    body = MAGIC + struct.pack("<I", len(hdr_bytes)) + hdr_bytes \
        + b"".join(blob for _, blob in sections)
    return body + struct.pack("<I", zlib.crc32(body)) + END_MAGIC


def _unpack(raw, path="<buffer>"):
    """Validate magic + CRC footer; returns ({name: bytes}, meta)."""
    foot = 4 + len(END_MAGIC)
    if len(raw) < len(MAGIC) + 4 + foot or not raw.startswith(MAGIC):
        raise CheckpointCorruptError(f"{path}: not a {MAGIC.decode()} file")
    if not raw.endswith(END_MAGIC):
        raise CheckpointCorruptError(
            f"{path}: missing {END_MAGIC.decode()} footer (torn write?)")
    body, crc_raw = raw[:-foot], raw[-foot:-len(END_MAGIC)]
    (crc,) = struct.unpack("<I", crc_raw)
    actual = zlib.crc32(body)
    if crc != actual:
        raise CheckpointCorruptError(
            f"{path}: CRC mismatch (stored {crc:#010x}, actual "
            f"{actual:#010x}) — checkpoint is corrupt")
    (hdr_len,) = struct.unpack("<I", body[len(MAGIC):len(MAGIC) + 4])
    hdr_start = len(MAGIC) + 4
    try:
        hdr = json.loads(body[hdr_start:hdr_start + hdr_len])
    except ValueError as e:
        raise CheckpointCorruptError(f"{path}: bad header JSON: {e}") from None
    payload = body[hdr_start + hdr_len:]
    out = {}
    for s in hdr["sections"]:
        blob = payload[s["offset"]:s["offset"] + s["length"]]
        if len(blob) != s["length"]:
            raise CheckpointCorruptError(
                f"{path}: section {s['name']!r} truncated")
        out[s["name"]] = blob
    return out, hdr.get("meta", {})


def _atomic_write(path, raw):
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(raw)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    # fsync the directory: os.replace is atomic in the namespace but the
    # rename record itself needs a journal flush to survive power loss
    dirfd = None
    try:
        dirfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        os.fsync(dirfd)
    except OSError:
        pass  # e.g. filesystems that refuse O_RDONLY dir fsync
    finally:
        if dirfd is not None:
            os.close(dirfd)


# -- public save/load -------------------------------------------------------


def _trainer_blob(trainer):
    return trainer.states_to_bytes()


def _restore_trainer(trainer, raw):
    trainer.load_states_from_bytes(raw)


def save_checkpoint(path, net=None, trainer=None, params=None, meta=None):
    """Atomically write one checkpoint file covering block parameters
    (``net`` or an explicit name->NDArray ``params`` dict) and, when given,
    the Trainer's optimizer state + step count. Returns ``path``."""
    from ..ndarray.utils import save_parameters_buffer

    if net is None and params is None:
        raise MXNetError("save_checkpoint needs a net or a params dict")
    if params is None:
        params = net._params_data()
    sections = [("params", save_parameters_buffer(params))]
    if trainer is not None:
        sections.append(("trainer", _trainer_blob(trainer)))
    t0 = _prof.begin()
    _atomic_write(path, _pack(sections, meta))
    _prof.record_duration("resilience::checkpoint_save", "resilience", t0,
                          args={"path": os.path.basename(str(path))})
    _counters.incr("resilience.checkpoints_saved")
    return path


def load_checkpoint(path, net=None, trainer=None):
    """Load + validate one checkpoint; restores into ``net`` / ``trainer``
    when given. Raises :class:`CheckpointCorruptError` on a bad file
    (nothing is restored in that case). Returns ``(params_dict, meta)``."""
    from ..ndarray.utils import load_parameters_buffer

    with open(path, "rb") as f:
        raw = f.read()
    sections, meta = _unpack(raw, path=str(path))
    if "params" not in sections:
        raise CheckpointCorruptError(f"{path}: no params section")
    if trainer is not None and "trainer" not in sections:
        # validated BEFORE any mutation: a params-only checkpoint loaded
        # with a trainer must fail atomically, not leave checkpoint
        # weights paired with stale optimizer state
        raise MXNetError(f"{path}: checkpoint has no trainer section")
    params = load_parameters_buffer(sections["params"])
    if net is not None:
        net_params = net.collect_params()
        missing = set(net_params) - set(params)
        if missing:
            raise MXNetError(
                f"{path}: checkpoint missing parameters {sorted(missing)}")
        for name, p in net_params.items():
            p.set_data(params[name])
    if trainer is not None:
        _restore_trainer(trainer, sections["trainer"])
    return params, meta


class CheckpointManager:
    """Numbered atomic checkpoints in a directory, with last-good rollback.

    Files are ``<prefix>-<step:012d>.ckpt``; ``load_latest`` walks newest →
    oldest and quarantines corrupt files as ``<name>.corrupt`` instead of
    failing, so one torn/bit-rotted checkpoint costs one save interval, not
    the whole run.
    """

    def __init__(self, directory, prefix="ckpt", max_keep=3):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.prefix = prefix
        self.max_keep = int(max_keep)

    def _path(self, step):
        return os.path.join(self.directory, f"{self.prefix}-{step:012d}.ckpt")

    def list_steps(self):
        """Existing checkpoint steps, ascending."""
        steps = []
        want = self.prefix + "-"
        for name in os.listdir(self.directory):
            if name.startswith(want) and name.endswith(".ckpt"):
                try:
                    steps.append(int(name[len(want):-len(".ckpt")]))
                except ValueError:
                    continue
        return sorted(steps)

    def save(self, step, net=None, trainer=None, params=None, meta=None):
        meta = dict(meta or {})
        meta["step"] = int(step)
        path = save_checkpoint(self._path(step), net=net, trainer=trainer,
                               params=params, meta=meta)
        self._rotate()
        return path

    def _rotate(self):
        steps = self.list_steps()
        while len(steps) > self.max_keep:
            old = steps.pop(0)
            try:
                os.remove(self._path(old))
            except OSError:
                pass

    def quarantine(self, step, suffix=".corrupt"):
        """Move one checkpoint out of the rotation by renaming it with
        ``suffix`` (``.corrupt`` for CRC/structure failures, ``.poisoned``
        when the guardrails find non-finite parameters in a CRC-valid
        file). Returns True if the file was moved."""
        path = self._path(step)
        try:
            os.replace(path, path + suffix)
            return True
        except OSError:
            return False

    def load_latest(self, net=None, trainer=None):
        """Restore the newest valid checkpoint; corrupt files roll back to
        the previous one. Returns its ``meta`` dict (contains ``step``),
        or ``None`` when no valid checkpoint exists."""
        import warnings

        for step in reversed(self.list_steps()):
            path = self._path(step)
            try:
                _, meta = load_checkpoint(path, net=net, trainer=trainer)
                return meta
            except CheckpointCorruptError as e:
                _counters.incr("resilience.checkpoints_corrupt")
                warnings.warn(
                    f"skipping corrupt checkpoint: {e}", RuntimeWarning,
                    stacklevel=2)
                self.quarantine(step)
            except MXNetError as e:
                # CRC-valid but incompatible with THIS net/trainer (e.g. a
                # params-only snapshot restored with a trainer, missing
                # params after a model change): the file is healthy, so
                # don't quarantine it — but keep rolling back, an older
                # compatible checkpoint beats refusing to resume
                warnings.warn(
                    f"skipping incompatible checkpoint: {e}",
                    RuntimeWarning, stacklevel=2)
        return None


class ResilientCheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Estimator event handler: periodic atomic checkpoints + resume.

    Unlike the reference-shaped ``CheckpointHandler`` (two files, plain
    writes), this one writes the single-file atomic container with the
    Trainer state and progress meta inside, so the worker can die at ANY
    point — including between params and states — and resume consistently.

    Usage::

        handler = ResilientCheckpointHandler(dir, batch_period=10)
        start = handler.resume(est)      # 0 on a fresh run
        est.fit(train_data, epochs=N, event_handlers=[handler])
    """

    def __init__(self, model_dir, model_prefix="model", epoch_period=1,
                 batch_period=None, max_keep=3):
        self.manager = CheckpointManager(model_dir, prefix=model_prefix,
                                         max_keep=max_keep)
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        # injection site for the kill-and-resume scenario: dies AFTER the
        # optimizer step, BEFORE the periodic save below — the worst case
        fault_slot = _faults_slot()
        if fault_slot is not None:
            fault_slot.check("estimator:batch")
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator)

    def _save(self, estimator):
        self.manager.save(
            self.current_batch, net=estimator.net, trainer=estimator.trainer,
            meta={"batch": self.current_batch, "epoch": self.current_epoch})

    def resume(self, estimator):
        """Restore the newest valid checkpoint into the estimator's net and
        trainer. Returns the batch index to continue from (0 = fresh)."""
        meta = self.manager.load_latest(net=estimator.net,
                                        trainer=estimator.trainer)
        if meta is None:
            return 0
        self.current_batch = int(meta.get("batch", meta.get("step", 0)))
        self.current_epoch = int(meta.get("epoch", 0))
        return self.current_batch


def _faults_slot():
    from . import faults

    return faults.get_plan()
