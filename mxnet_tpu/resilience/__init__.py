"""Fault-tolerance subsystem: fault injection, retry/backoff, collective
circuit breaker, atomic checkpoint/resume.

PR 1 (the telemetry subsystem) made failures *visible*; this package makes
the runtime *survive* them. Three cooperating layers:

* :mod:`.faults` — deterministic, seedable fault plans (programmatic or
  ``MXNET_FAULT_PLAN``) with injection hooks wired into op dispatch,
  CachedOp compile, the dist_tpu collectives and engine wait points, so
  every recovery path is testable on a CPU dev box.
* :mod:`.retry` — transient-vs-fatal error classification, bounded
  exponential backoff around XLA compiles and collectives, the
  ``MXNET_COLLECTIVE_TIMEOUT`` hung-collective watchdog, and the
  closed/open/half-open :class:`~.retry.CircuitBreaker` dist_tpu uses to
  degrade to its eager fallback after repeated fast-path failures.
* :mod:`.checkpoint` — crash-safe single-file checkpoints (write-temp +
  fsync + atomic rename, CRC32 footer), corruption rollback to last-good,
  ``load_latest`` resume, and the estimator-integrated
  :class:`~.checkpoint.ResilientCheckpointHandler`.
* :mod:`.guardrails` — numerical failure: NaN/Inf sentinels with
  per-parameter attribution, the dist_tpu pre-collective NaN quarantine,
  EWMA+z-score loss-spike detection, and the
  :class:`~.guardrails.GuardrailHandler` skip-step → rewind-and-skip →
  :class:`~.guardrails.DivergenceError` recovery policy.
* :mod:`.elastic` — mesh-level failure: mesh-loss classification
  (:class:`~.elastic.MeshDegraded`) + elastic dp-shrink restart from
  reshard-on-resume sharded checkpoints
  (:class:`~.elastic.ElasticTrainingHandler`), the cross-replica
  parameter-fingerprint desync audit
  (:class:`~.elastic.DesyncAuditHandler`), and per-replica straggler
  detection (:class:`~.elastic.StragglerMonitor`).
* :mod:`.preemption` — scheduled death: SIGTERM/SIGINT graceful drain
  (finish the step → force-save through the async checkpoint writer →
  fence → clean stop; serving routes the signal to the fleet/batcher
  drain), with the ``preempt:deliver`` fault site for deterministic
  CPU-box injection (:class:`~.preemption.PreemptionHandler`).

Everything emits ``resilience::*`` events/counters on the PR-1 profiler
bus; :func:`resilience_stats` snapshots them for bench/BENCH rows.
"""
from __future__ import annotations

from . import faults, retry
from .faults import (FaultPlan, InjectedFaultError, SimulatedWorkerDeath,
                     TransientFaultError, clear_plan, fault_point, get_plan,
                     install_plan)
from .retry import (CircuitBreaker, CollectiveTimeoutError, RetryPolicy,
                    call_with_retry, collective_policy, collective_timeout,
                    compile_policy, is_transient, run_with_watchdog)

# checkpoint and guardrails pull gluon (event-handler bases); load them on
# first touch so `from mxnet_tpu.resilience import faults` stays light
_CHECKPOINT_NAMES = (
    "checkpoint", "CheckpointCorruptError", "CheckpointManager",
    "ResilientCheckpointHandler", "load_checkpoint", "save_checkpoint",
)
_GUARDRAIL_NAMES = (
    "guardrails", "DivergenceError", "GuardrailHandler",
    "NonFiniteGradError", "SpikeDetector", "all_finite",
    "attribute_nonfinite", "clip_by_global_norm", "nonfinite_count",
)
_ELASTIC_NAMES = (
    "elastic", "MeshDegraded", "ElasticTrainingHandler",
    "ElasticBatchProcessor", "DesyncAuditHandler", "StragglerMonitor",
    "is_mesh_loss", "probe_contexts", "replica_fingerprints",
)
_PREEMPTION_NAMES = ("preemption", "PreemptionHandler")
_LOCKDEP_NAMES = ("lockdep",)


def __getattr__(name):
    # NOT `from . import <mod>`: the fromlist handler getattrs the
    # package and would re-enter this __getattr__ unboundedly
    if name in _CHECKPOINT_NAMES:
        import importlib

        _ckpt = importlib.import_module(__name__ + ".checkpoint")
        globals()["checkpoint"] = _ckpt
        for n in _CHECKPOINT_NAMES[1:]:
            globals()[n] = getattr(_ckpt, n)
        return globals()[name]
    if name in _GUARDRAIL_NAMES:
        import importlib

        _gr = importlib.import_module(__name__ + ".guardrails")
        globals()["guardrails"] = _gr
        for n in _GUARDRAIL_NAMES[1:]:
            globals()[n] = getattr(_gr, n)
        return globals()[name]
    if name in _ELASTIC_NAMES:
        import importlib

        _el = importlib.import_module(__name__ + ".elastic")
        globals()["elastic"] = _el
        for n in _ELASTIC_NAMES[1:]:
            globals()[n] = getattr(_el, n)
        return globals()[name]
    if name in _PREEMPTION_NAMES:
        import importlib

        _pre = importlib.import_module(__name__ + ".preemption")
        globals()["preemption"] = _pre
        for n in _PREEMPTION_NAMES[1:]:
            globals()[n] = getattr(_pre, n)
        return globals()[name]
    if name in _LOCKDEP_NAMES:
        import importlib

        _ld = importlib.import_module(__name__ + ".lockdep")
        globals()["lockdep"] = _ld
        return _ld
    raise AttributeError(
        f"module 'mxnet_tpu.resilience' has no attribute {name!r}")


def resilience_stats():
    """Process-wide resilience counters: retries, degradations, watchdog
    timeouts, breaker trips, checkpoint traffic, injected faults. Source
    of truth is the resilience-private store (mirrored to the profiler
    bus but NOT cleared by ``profiler.reset()``). bench.py prints this
    next to the telemetry summary so BENCH rounds track robustness
    cost."""
    from . import counters as _counters

    keys = (
        "resilience.retries",
        "resilience.degradations",
        "resilience.watchdog_timeouts",
        "resilience.breaker_trips",
        "resilience.checkpoints_saved",
        "resilience.checkpoints_corrupt",
        "resilience.faults_injected",
        # numerical guardrails (resilience.guardrails)
        "resilience.sentinel_trips",
        "resilience.guardrail_skips",
        "resilience.guardrail_rewinds",
        "resilience.nan_quarantined",
        "resilience.loss_scale_overflows",
        # elastic multichip training (resilience.elastic)
        "resilience.mesh_losses",
        "resilience.elastic_restarts",
        "resilience.reshard_resumes",
        "resilience.desync_trips",
        "resilience.desync_resyncs",
        "resilience.desync_rewinds",
        "resilience.stragglers",
        "resilience.checkpoints_quarantined",
        # preemption + async checkpointing (resilience.preemption)
        "resilience.preemptions",
        "resilience.preempt_saves",
        "resilience.preempt_drains",
        "resilience.ckpt_async_saves",
        "resilience.ckpt_async_failed",
        "resilience.ckpt_backpressure",
        "resilience.ckpt_stall_overruns",
    )
    out = {k.split(".", 1)[1]: _counters.get(k) for k in keys}
    out["fault_plan_active"] = faults._active is not None
    return out
