"""Preemption-aware graceful drain (SIGTERM/SIGINT lifecycle).

A preempted TPU worker gets a SIGTERM and a short grace window — the most
common production interruption there is. This module turns that signal
into a clean, resumable exit instead of lost work:

* **Training**: :func:`install` sets a process-wide *requested* flag in
  the signal handler (signal-safe: no I/O, no locks held across user
  code). A :class:`PreemptionHandler` in the estimator's event-handler
  list polls the flag once per batch — AFTER the optimizer step — and on
  delivery force-saves through its checkpoint handler (async write, then
  :meth:`~.checkpoint.CheckpointManager.wait` as the commit fence) and
  stops training cleanly. The next process resumes from that generation,
  sample-exact when a resumable data iterator was checkpointed along.
* **Serving**: the handler routes the signal to the serving stack on a
  background thread — every registered drainable plus every live
  ``serve.fleet.Router`` — so in-flight requests settle before exit
  (``Router.drain`` / ``DynamicBatcher.drain`` semantics), bounded by
  ``MXNET_PREEMPT_GRACE_S``.

Determinism on a CPU dev box: the ``preempt:deliver`` fault site fires in
:meth:`PreemptionHandler.batch_end` with ``info={"batch": n}`` — a
``{"kind": "preempt", "at": [k]}`` rule injects the SIGTERM-equivalent at
exactly batch ``k`` with no real signal delivery, so the whole
drain → force-save → resume path is testable and seedable.

Lifecycle: ``signal → request() → finish current step → force-save →
fence (join the async write) → stop/exit → next process resumes``.
"""
from __future__ import annotations

import signal as _signal
import threading
import weakref

from ..gluon.contrib.estimator.event_handler import (BatchEnd, TrainBegin,
                                                     TrainEnd)
from ..profiler import core as _prof
from ..profiler import recorder as _recorder
from . import counters as _counters

_lock = threading.Lock()
_requested = threading.Event()
_reason = None
_installed = {}      # signum -> previous handler (for uninstall/chaining)
_drainables = []     # weakrefs to serving objects with drain()/close()
_exit_after_drain = False


def _grace_s():
    from .. import config

    try:
        return float(config.get("MXNET_PREEMPT_GRACE_S"))
    except (TypeError, ValueError):
        return 30.0


def requested():
    """True once a preemption (signal or injected) has been delivered."""
    return _requested.is_set()


def reason():
    """Why preemption was requested (``None`` if it wasn't)."""
    return _reason


def clear():
    """Reset the delivered flag (test hygiene / a survived drill)."""
    global _reason
    with _lock:
        _requested.clear()
        _reason = None


def request(why="api"):
    """Mark preemption requested — the programmatic SIGTERM-equivalent
    (the ``preempt:deliver`` fault site and the real signal handler both
    land here). Idempotent; only the first delivery counts."""
    global _reason
    with _lock:
        if _requested.is_set():
            return
        _reason = str(why)
        _requested.set()
    _counters.incr("resilience.preemptions")
    _recorder.note("preempt", "deliver", {"reason": str(why)})
    if _prof.ENABLED:
        _prof.record_instant("resilience::preempt", "resilience",
                             args={"reason": str(why)})


def register_drainable(obj):
    """Register a serving-side object to drain on preemption: anything
    with ``drain(timeout=...)`` (preferred — in-flight work settles) or
    ``close(timeout=...)``. Held by weakref; live ``serve.fleet.Router``
    instances are drained without registration."""
    with _lock:
        _drainables.append(weakref.ref(obj))


def drain_serving(timeout=None):
    """Route a preemption to the serving stack: drain every registered
    drainable and every live ``serve.fleet.Router`` within ``timeout``
    seconds (default ``MXNET_PREEMPT_GRACE_S``). Returns how many objects
    were drained cleanly."""
    import sys

    budget = _grace_s() if timeout is None else float(timeout)
    targets = []
    with _lock:
        live = []
        for ref in _drainables:
            obj = ref()
            if obj is not None:
                targets.append(obj)
                live.append(ref)
        _drainables[:] = live
    fleet = sys.modules.get("mxnet_tpu.serve.fleet")
    if fleet is not None:
        for router in list(getattr(fleet, "_routers", ()) or ()):
            if router not in targets:
                targets.append(router)
    n = 0
    for obj in targets:
        try:
            if hasattr(obj, "drain"):
                ok = obj.drain(timeout=budget)
            else:
                obj.close(timeout=budget)
                ok = True
            n += 1 if ok is not False else 0
        except Exception as exc:  # noqa: BLE001 — drain the rest anyway
            import warnings

            warnings.warn(
                f"preemption drain of {type(obj).__name__} failed: "
                f"{type(exc).__name__}: {exc}", RuntimeWarning,
                stacklevel=2)
    _counters.incr("resilience.preempt_drains")
    return n


_drain_thread = None


def drain_in_progress():
    """True while the post-signal background drain is still running —
    the liveness probe for the ``mxtpu-preempt-drain`` thread."""
    t = _drain_thread
    return t is not None and t.is_alive()


def _handler(signum, frame):
    global _drain_thread
    prev = _installed.get(signum)
    request(f"signal {signum}")
    # serving drains on a background thread: the main thread may be deep
    # in a training step and must keep running to finish it
    _drain_thread = threading.Thread(target=_drain_then_exit, daemon=True,
                                     name="mxtpu-preempt-drain")
    _drain_thread.start()
    if callable(prev) and prev not in (_signal.SIG_IGN, _signal.SIG_DFL):
        prev(signum, frame)  # preserve application handlers


def _drain_then_exit():
    _prof.register_thread_name()
    drain_serving()
    if _exit_after_drain:
        import os

        os._exit(0)


def install(signals=(_signal.SIGTERM, _signal.SIGINT), exit_after_drain=False):
    """Install the preemption handlers (main thread only — CPython
    restriction). ``exit_after_drain=True`` is for serving-only daemons
    with no training loop to drive the exit: once the serving stack has
    drained, the process exits 0. Training processes leave it False — the
    :class:`PreemptionHandler` stops the fit loop and the script exits on
    its own. Idempotent; :func:`uninstall` restores the previous
    handlers."""
    global _exit_after_drain
    _exit_after_drain = bool(exit_after_drain)
    for signum in signals:
        if signum in _installed:
            continue
        _installed[signum] = _signal.signal(signum, _handler)


def uninstall():
    """Restore the signal handlers :func:`install` replaced."""
    while _installed:
        signum, prev = _installed.popitem()
        try:
            _signal.signal(signum, prev)
        except (TypeError, ValueError):
            _signal.signal(signum, _signal.SIG_DFL)


class PreemptionHandler(TrainBegin, BatchEnd, TrainEnd):
    """Estimator guard: finish the step, force-save, stop.

    Runs AFTER the checkpoint handler in the batch_end order (priority
    100 > the checkpoint handlers' 0), so the force-save snapshots the
    batch counter the periodic saves use. On a delivered preemption —
    real signal via :func:`install`, programmatic :func:`request`, or an
    injected ``preempt:deliver`` fault — it:

    1. force-saves through ``ckpt_handler`` (its ``_save``: async
       snapshot + background write),
    2. fences (``manager.wait()``) so the generation COMMITS before the
       process exits, and
    3. sets ``stop_training`` — the fit loop exits after this batch.

    Works with both :class:`~.checkpoint.ResilientCheckpointHandler` and
    :class:`~.elastic.ElasticTrainingHandler` (anything with ``_save`` +
    ``manager``)."""

    def __init__(self, ckpt_handler=None, priority=100):
        self.ckpt = ckpt_handler
        self.priority = priority
        self.stop_training = False
        self.preempted = False
        self._batch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.stop_training = False

    def batch_end(self, estimator, *args, **kwargs):
        self._batch += 1
        from . import faults

        plan = faults.get_plan()
        if plan is not None:
            marker = plan.check("preempt:deliver", {"batch": self._batch})
            if isinstance(marker, dict) and marker.get("kind") == "preempt":
                request(f"injected at batch {self._batch}")
        if not requested() or self.stop_training:
            return
        self.preempted = True
        if self.ckpt is not None:
            self.ckpt._save(estimator)
            self.ckpt.manager.wait()  # commit fence: never exit mid-write
            _counters.incr("resilience.preempt_saves")
        self.stop_training = True

    def train_end(self, estimator, *args, **kwargs):
        if self.preempted and _prof.ENABLED:
            _prof.record_instant("resilience::preempt_stop", "resilience",
                                 args={"batch": self._batch})
