"""Autograd: tape-based reverse-mode differentiation over JAX vjps.

TPU-native re-design of the reference's autograd (``src/imperative/
imperative.cc`` ``MarkVariables:134`` / ``RecordOp:204`` / ``Backward:385``
and Python ``python/mxnet/autograd.py:121-519``).

Reference mechanism: every recorded op attaches an ``AGInfo`` node to an nnvm
graph; ``Backward`` runs the nnvm ``Gradient`` pass and executes the grad
graph through the engine.

TPU mechanism: every recorded op is dispatched through ``jax.vjp`` — the
forward runs once (XLA, async) and the returned vjp closure *is* the gradient
graph node. ``backward()`` walks the tape in reverse sequence order calling
the stored vjp closures and accumulates cotangents into the arrays registered
by ``mark_variables`` honoring ``grad_req`` write/add/null — the same
contract ``Imperative::Backward`` honors (``imperative.cc:630``).

Hybridized blocks contribute a *single* tape node whose forward and backward
are each one compiled XLA computation (see ``mxnet_tpu.cachedop``) — the
analog of a ``_CachedOp`` node on the reference tape
(``src/imperative/cached_op.cc:836-845``).
"""
from __future__ import annotations

import threading
from typing import List, Optional, Sequence

from .base import MXNetError

# ---------------------------------------------------------------------------
# Thread-local recording / training state
# (reference: Imperative's thread-local is_recording/is_training,
#  include/mxnet/imperative.h:51-335)
# ---------------------------------------------------------------------------


class _State(threading.local):
    def __init__(self):
        super().__init__()
        self.recording = False
        self.training = False
        self.seq = 0


_state = _State()


def is_recording() -> bool:
    return _state.recording


def is_training() -> bool:
    return _state.training


def set_recording(is_record: bool) -> bool:
    prev, _state.recording = _state.recording, bool(is_record)
    return prev


def set_training(train_mode: bool) -> bool:
    prev, _state.training = _state.training, bool(train_mode)
    return prev


class _RecordingStateScope:
    """Scope guard mirroring ``autograd.py:121`` in the reference."""

    def __init__(self, is_record: Optional[bool], train_mode: Optional[bool]):
        self._enter_record = is_record
        self._enter_train = train_mode
        self._prev_record = None
        self._prev_train = None

    def __enter__(self):
        if self._enter_record is not None:
            self._prev_record = set_recording(self._enter_record)
        if self._enter_train is not None:
            self._prev_train = set_training(self._enter_train)
        return self

    def __exit__(self, *exc):
        if self._enter_record is not None:
            set_recording(self._prev_record)
        if self._enter_train is not None:
            set_training(self._prev_train)
        return False


def record(train_mode: bool = True):
    """``with autograd.record():`` — turn on recording (and train mode)."""
    return _RecordingStateScope(True, train_mode)


def pause(train_mode: bool = False):
    """``with autograd.pause():`` — turn off recording."""
    return _RecordingStateScope(False, train_mode)


def train_mode():
    return _RecordingStateScope(None, True)


def predict_mode():
    return _RecordingStateScope(None, False)


# ---------------------------------------------------------------------------
# Tape structures
# ---------------------------------------------------------------------------


class Leaf:
    """A differentiable variable registered via ``mark_variables``.

    Holds the gradient buffer and the grad_req, the role of the reference's
    variable ``AGInfo`` + pre-registered grad array (``imperative.cc:134``).
    """

    __slots__ = ("grad_array", "grad_req", "_accum")

    def __init__(self, grad_array, grad_req: str = "write"):
        if grad_req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {grad_req!r}")
        self.grad_array = grad_array  # NDArray or None (for grad() API use)
        self.grad_req = grad_req
        self._accum = None  # transient cotangent during a backward walk


class TapeNode:
    """One recorded op: a vjp closure plus wiring to producers/leaves.

    ``in_slots[i]`` is either a :class:`Leaf`, a ``(TapeNode, out_idx)``
    pair, or ``None`` (constant / untracked input).

    ``fwd_fn``/``in_arrays`` (optional) let ``create_graph=True`` rebuild
    the vjp *differentiably*: the backward walk re-linearizes ``fwd_fn`` at
    the saved inputs as a recorded op, so grad-of-grad sees the full input
    dependence (the reference builds the grad graph symbolically for the
    same reason, ``src/nnvm/gradient.cc``).
    """

    __slots__ = ("vjp_fn", "in_slots", "out_avals", "seq", "name",
                 "fwd_fn", "in_arrays", "out_container", "__weakref__")

    def __init__(self, vjp_fn, in_slots, out_avals, name="",
                 fwd_fn=None, in_arrays=None):
        self.vjp_fn = vjp_fn
        self.in_slots = in_slots
        self.out_avals = out_avals  # list of (shape, dtype) per output leaf
        _state.seq += 1
        self.seq = _state.seq
        self.name = name
        self.fwd_fn = fwd_fn
        self.in_arrays = in_arrays
        self.out_container = False  # fwd returns a tuple even when len==1


def mark_variables(variables, gradients, grad_reqs="write"):
    """Associate gradient buffers with variables (``autograd.py:196``)."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        var._leaf = Leaf(grad, req)
        var._tape = None


# ---------------------------------------------------------------------------
# Backward walk
# ---------------------------------------------------------------------------


def _collect_nodes(head_arrays):
    """Reachable tape nodes from the heads, returned sorted by seq desc."""
    seen = set()
    stack = []
    for a in head_arrays:
        t = getattr(a, "_tape", None)
        if t is not None and id(t[0]) not in seen:
            seen.add(id(t[0]))
            stack.append(t[0])
    nodes = []
    while stack:
        node = stack.pop()
        nodes.append(node)
        for slot in node.in_slots:
            if isinstance(slot, tuple):
                prod = slot[0]
                if id(prod) not in seen:
                    seen.add(id(prod))
                    stack.append(prod)
    nodes.sort(key=lambda n: n.seq, reverse=True)
    return nodes


def _zeros_like_aval(aval):
    import jax.numpy as jnp

    shape, dtype = aval
    return jnp.zeros(shape, dtype)


def _add_ct(table, key, val):
    cur = table.get(key)
    table[key] = val if cur is None else cur + val


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):  # pylint: disable=unused-argument
    """Run backward from ``heads``, writing gradients into marked variables.

    Mirrors ``mxnet.autograd.backward`` (``autograd.py:245``) →
    ``Imperative::Backward`` (``imperative.cc:385``).
    """
    import jax.numpy as jnp

    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    leaves = _run_backward(heads, head_grads, retain_graph)
    # write into registered grad buffers honoring grad_req
    from .ndarray.sparse import RowSparseNDArray

    for leaf in leaves:
        ct = leaf._accum
        leaf._accum = None
        if ct is None or leaf.grad_req == "null" or leaf.grad_array is None:
            continue
        ga = leaf.grad_array
        if isinstance(ct, RowSparseNDArray):
            # sparse cotangent (embedding sparse_grad): keep it O(nnz)
            # when the grad buffer is row_sparse; storage-fallback to
            # dense otherwise (exec_utils.h:138 role)
            if isinstance(ga, RowSparseNDArray):
                if leaf.grad_req == "add":
                    ga._set_sparse(ga + ct)
                else:
                    ga._set_sparse(ct)
            elif leaf.grad_req == "add":
                ga._set_data_internal(ga._data + ct._data)
            else:
                ga._set_data_internal(ct._data)
        elif leaf.grad_req == "add":
            ga._set_data_internal(ga._data + ct)
        else:
            ga._set_data_internal(jnp.asarray(ct, ga.dtype) if ct.dtype != ga.dtype else ct)


def _node_vjp_recorded(node, cts):
    """create_graph=True step: re-linearize ``node.fwd_fn`` at the saved
    inputs *as a recorded op*, so the produced input-cotangents carry tape
    links to both the cotangents and the original inputs — grad-of-grad
    sees d(residual)/dx, which the stored first-order vjp closure cannot
    provide (its residuals are baked constants)."""
    from .ndarray.ndarray import NDArray
    from .ops import registry

    if node.fwd_fn is None or node.in_arrays is None:
        raise MXNetError(
            f"create_graph=True is not supported through node "
            f"{node.name!r} (hybridized CachedOp or custom Function); "
            f"compute the inner function imperatively for higher-order "
            f"gradients")
    n_out = len(node.out_avals)
    as_tuple = n_out > 1 or node.out_container

    def hfn(*args):
        import jax

        cs, xs = args[:n_out], args[n_out:]
        _, vjp = jax.vjp(node.fwd_fn, *xs)
        r = vjp(tuple(cs) if as_tuple else cs[0])
        return tuple(r)

    all_args = tuple(cts) + tuple(node.in_arrays)
    out = registry.apply(hfn, all_args, name=(node.name or "op") + "_grad",
                         sync_outputs=False, cacheable=False)
    return out if isinstance(out, (list, tuple)) else (out,)


def _run_backward(heads, head_grads, retain_graph, create_graph=False):
    """Shared tape walk. Returns the list of leaves touched (with _accum).

    ``create_graph=True`` runs the walk with NDArray cotangents and records
    every vjp application back onto the tape (the reference's re-recorded
    grad graph, ``python/mxnet/autograd.py:309``).
    """
    import jax.numpy as jnp

    from . import engine
    from .ndarray.ndarray import NDArray

    # tape boundary: any pending bulk segment must flush BEFORE the walk —
    # it installs the segment tape nodes the heads' _tape links point at
    engine.flush_current("tape")

    def lift(x):
        return NDArray(x) if create_graph and not isinstance(x, NDArray) else x

    node_cts = {}  # (id(node), out_idx) -> cotangent (jax array / NDArray)
    touched_leaves = []

    def touch(leaf, ct):
        if leaf._accum is None:
            touched_leaves.append(leaf)
            leaf._accum = ct
        else:
            leaf._accum = leaf._accum + ct

    any_graph = False
    for arr, hg in zip(heads, head_grads):
        tape = getattr(arr, "_tape", None)
        leaf = getattr(arr, "_leaf", None)
        if hg is None:
            # MXNet semantics: default head gradient is ones_like(head)
            ct = lift(jnp.ones(arr.shape, arr.dtype))
        elif create_graph:
            ct = hg if isinstance(hg, NDArray) else NDArray(jnp.asarray(hg))
        else:
            ct = hg._data if hasattr(hg, "_data") else jnp.asarray(hg)
        if tape is not None:
            any_graph = True
            _add_ct(node_cts, (id(tape[0]), tape[1]), ct)
        elif leaf is not None:
            any_graph = True
            touch(leaf, ct)
    if not any_graph:
        raise MXNetError(
            "cannot differentiate: none of the heads is connected to the "
            "autograd tape (did you compute them inside autograd.record()?)"
        )

    nodes = _collect_nodes(heads)
    for node in nodes:
        cts = []
        has_any = False
        for i, aval in enumerate(node.out_avals):
            ct = node_cts.pop((id(node), i), None)
            if ct is None:
                ct = lift(_zeros_like_aval(aval))
            else:
                has_any = True
                if not create_graph and hasattr(ct, "_stype"):
                    # a sparse cotangent reaching a dense vjp: the
                    # storage-fallback boundary — densify here
                    ct = ct._data
            cts.append(ct)
        if not has_any:
            continue
        if create_graph:
            in_cts = _node_vjp_recorded(node, cts)
        else:
            engine._count_dispatch()  # one backward executable per node
            in_cts = node.vjp_fn(tuple(cts) if len(cts) > 1 else cts[0])
        if not isinstance(in_cts, (tuple, list)):
            in_cts = (in_cts,)
        for slot, ict in zip(node.in_slots, in_cts):
            if slot is None or ict is None:
                continue
            if isinstance(slot, Leaf):
                touch(slot, ict)
            else:
                _add_ct(node_cts, (id(slot[0]), slot[1]), ict)
        if not retain_graph and not create_graph:
            # free residuals AND the saved forward inputs eagerly — the
            # higher-order bookkeeping must not raise ordinary training's
            # peak activation memory
            node.vjp_fn = None
            node.fwd_fn = None
            node.in_arrays = None
    return touched_leaves


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):  # pylint: disable=unused-argument
    """Return gradients of heads w.r.t. variables (``autograd.py:309``).

    ``create_graph=True`` re-records every vjp application onto the tape
    (via the saved forward functions), so the returned gradients are
    themselves differentiable — ``grad(grad(f))`` works, matching the
    reference's re-recorded grad graph and its
    ``test_higher_order_grad.py`` contract.
    """
    from .ndarray.ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if isinstance(variables, NDArray):
        variables = [variables]
    if retain_graph is None:
        retain_graph = create_graph
    if head_grads is not None and not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    # temporarily mark: ensure each variable has a leaf
    tmp_leaves = []
    for v in variables:
        if getattr(v, "_leaf", None) is None:
            v._leaf = Leaf(None, "write")
            tmp_leaves.append(v)
    prev_rec = None
    if create_graph:
        # the walk's vjp applications must themselves be recorded
        prev_rec = set_recording(True)
    try:
        touched = _run_backward(heads, head_grads, retain_graph,
                                create_graph=create_graph)
        out = []
        for v in variables:
            ct = v._leaf._accum
            v._leaf._accum = None
            if ct is None:
                import jax.numpy as jnp

                ct = jnp.zeros(v.shape, v.dtype)
            out.append(ct if isinstance(ct, NDArray) else NDArray(ct))
        # leaves the walk touched but the caller didn't ask about (e.g.
        # network params during a grad-penalty grad-wrt-input) must not
        # keep stale accumulators — they'd poison the next backward()
        for leaf in touched:
            leaf._accum = None
        return out
    finally:
        if prev_rec is not None:
            set_recording(prev_rec)
        for v in tmp_leaves:
            v._leaf = None


def get_symbol(x):  # pragma: no cover - legacy API surface
    """Reference returns the recorded Symbol; here tracing is jax-side."""
    raise NotImplementedError(
        "autograd.get_symbol is a legacy-graph API; use HybridBlock.export "
        "for a serialized compiled graph"
    )


# ---------------------------------------------------------------------------
# Custom differentiable Function (reference autograd.Function,
# python/mxnet/autograd.py:369 + src/c_api/c_api_function.cc)
# ---------------------------------------------------------------------------


class Function:
    """User-defined differentiable operation.

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` with NDArray in/out, then call the
    instance. Matches the reference contract: ``save_for_backward`` style
    state can simply be attached to ``self``.
    """

    def __init__(self):
        self._in_slots = None

    def save_for_backward(self, *arrays):
        self.saved_tensors = arrays

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from . import engine
        from .ndarray.ndarray import NDArray, _tracked, _slot_of

        # custom Functions capture input tape slots eagerly — pending bulk
        # segments must install their tape nodes first
        engine.flush_current("tape")
        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording() and any(_tracked(a) for a in inputs):
            func = self

            def vjp_fn(cts):
                if not isinstance(cts, tuple):
                    cts = (cts,)
                with pause():
                    grads = func.backward(*[NDArray(c) for c in cts])
                if not isinstance(grads, (list, tuple)):
                    grads = (grads,)
                return tuple(g._data if g is not None else None for g in grads)

            node = TapeNode(
                vjp_fn,
                [_slot_of(a) for a in inputs],
                [(o.shape, o.dtype) for o in outs],
                name=type(self).__name__,
            )
            for i, o in enumerate(outs):
                o._tape = (node, i)
                o._leaf = None
        return outs[0] if single else outs
