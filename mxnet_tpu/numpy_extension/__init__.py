"""``mx.npx`` — NumPy-extension namespace (NN ops and framework extras).

Reference: ``python/mxnet/numpy_extension/`` exposing the ``_npx_*`` operator
family (``fully_connected``, ``batch_norm``, ``convolution``, ... registered
with aliases in e.g. ``src/operator/nn/fully_connected.cc:251``). Here these
are implemented TPU-first in ``mxnet_tpu.ops.nn`` on lax/jnp (and Pallas for
attention) and re-exported.
"""
from __future__ import annotations

from ..ops.nn import *  # noqa: F401,F403
from ..ops import nn as _nn
from ..ops.control_flow import cond, foreach, while_loop  # noqa: F401
from ..ops.detection import (  # noqa: F401
    box_iou,
    box_nms,
    multibox_detection,
    multibox_prior,
    multibox_target,
    roi_align,
    roi_pooling,
)
from ..ops.spatial import (  # noqa: F401
    correlation,
    deformable_convolution,
    bilinear_sampler,
    grid_generator,
    spatial_transformer,
)
from ..util import (  # noqa: F401
    is_np_array,
    is_np_default_dtype,
    is_np_shape,
    reset_np,
    set_np,
    set_np_default_dtype,
)
# device helpers the reference's npx re-exports (numpy_extension/__init__.py
# pulls in mxnet.context): npx.cpu()/npx.gpu() appear throughout the
# reference's mx.np docstrings
from ..device import (  # noqa: F401
    Context,
    cpu,
    cpu_pinned,
    current_context,
    gpu,
    num_gpus,
    tpu,
)


def set_np_float64(default_float64=True):
    """Switch creation-default dtype to float64 (the reference documents
    this npx helper in its own mx.np docstrings, e.g. multiarray.py:1320,
    though it never shipped it; equivalent to ``set_np_default_dtype``)."""
    from ..util import set_np_default_dtype

    return set_np_default_dtype(default_float64)


def seed(s):
    from .. import random as _rng

    _rng.seed(s)


def waitall():
    from .. import engine

    engine.wait_all()


# framework extras the reference's npx also carries
# (``python/mxnet/numpy_extension/__init__.py`` __all__): NDArray
# persistence, dlpack interchange, numpy zero-copy, and the one-key
# samplers ``bernoulli``/``normal_n``/``uniform_n``
from ..dlpack import (  # noqa: F401,E402
    from_dlpack,
    to_dlpack_for_read,
    to_dlpack_for_write,
)
from ..ndarray.utils import load, save  # noqa: F401,E402


def from_numpy(ndarray, zero_copy=True):  # pylint: disable=unused-argument
    """Wrap a host numpy array as an NDArray (XLA owns device buffers, so
    a host->device transfer replaces the reference's zero-copy view)."""
    from .. import numpy as mnp

    return mnp.array(ndarray)


def bernoulli(prob=None, logit=None, size=None, dtype=None, ctx=None,
              device=None):  # pylint: disable=unused-argument
    """Bernoulli sampling (reference ``_npx_bernoulli``)."""
    from ..gluon.probability import Bernoulli

    out = Bernoulli(prob=prob, logit=logit).sample(size)
    return out.astype(dtype) if dtype else out


def _n_shape(batch_shape, *params):
    import numpy as onp

    bcast = onp.broadcast_shapes(
        *[tuple(getattr(p, "shape", ())) for p in params])
    if batch_shape is None:
        return bcast or None
    if isinstance(batch_shape, int):
        batch_shape = (batch_shape,)
    return tuple(batch_shape) + bcast


def normal_n(loc=0.0, scale=1.0, batch_shape=None, dtype=None, ctx=None,
             device=None):  # pylint: disable=unused-argument
    """``np.random.normal`` with shape = batch_shape + broadcast(params)
    (reference ``_npi_normal_n``)."""
    from .. import numpy as mnp

    return mnp.random.normal(loc, scale, size=_n_shape(batch_shape, loc,
                                                       scale), dtype=dtype)


def uniform_n(low=0.0, high=1.0, batch_shape=None, dtype=None, ctx=None,
              device=None):  # pylint: disable=unused-argument
    """``np.random.uniform`` with shape = batch_shape + broadcast(params)
    (reference ``_npi_uniform_n``)."""
    from .. import numpy as mnp

    return mnp.random.uniform(low, high, size=_n_shape(batch_shape, low,
                                                       high), dtype=dtype)


__all__ = [n for n in dir(_nn) if not n.startswith("_")] + [
    "seed", "waitall", "set_np", "reset_np", "is_np_array", "is_np_shape",
    "save", "load", "from_dlpack", "from_numpy", "to_dlpack_for_read",
    "to_dlpack_for_write", "bernoulli", "normal_n", "uniform_n",
    "grid_generator", "bilinear_sampler", "spatial_transformer",
    "multibox_prior", "multibox_target", "multibox_detection", "box_nms",
    "box_iou", "roi_align", "roi_pooling", "correlation",
    "deformable_convolution",
]
