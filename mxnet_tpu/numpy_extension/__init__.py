"""``mx.npx`` — NumPy-extension namespace (NN ops and framework extras).

Reference: ``python/mxnet/numpy_extension/`` exposing the ``_npx_*`` operator
family (``fully_connected``, ``batch_norm``, ``convolution``, ... registered
with aliases in e.g. ``src/operator/nn/fully_connected.cc:251``). Here these
are implemented TPU-first in ``mxnet_tpu.ops.nn`` on lax/jnp (and Pallas for
attention) and re-exported.
"""
from __future__ import annotations

from ..ops.nn import *  # noqa: F401,F403
from ..ops import nn as _nn
from ..ops.control_flow import cond, foreach, while_loop  # noqa: F401
from ..util import is_np_array, is_np_shape, set_np, reset_np  # noqa: F401


def seed(s):
    from .. import random as _rng

    _rng.seed(s)


def waitall():
    from .. import engine

    engine.wait_all()


__all__ = [n for n in dir(_nn) if not n.startswith("_")] + [
    "seed", "waitall", "set_np", "reset_np", "is_np_array", "is_np_shape",
]
