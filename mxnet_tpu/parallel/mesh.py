"""Device-mesh management for SPMD parallelism.

No reference analog (the reference's parallelism is PS/NCCL data-parallel
only, SURVEY.md §2.3 "absent" list) — this module is the foundation the TPU
build adds: a global ``jax.sharding.Mesh`` with named axes (``dp``, ``fsdp``,
``tp``, ``sp``, ``ep``...) that KVStore, Trainer, and the model zoo's
sharding rules all reference.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as _onp

from ..base import MXNetError

_state = threading.local()


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the public API renamed the
    replication-check kwarg (check_vma) and older versions only ship
    ``jax.experimental.shard_map`` (check_rep). One shim, shared by the
    pipeline and ring-attention modules."""
    try:
        from jax import shard_map

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_mesh(shape: Dict[str, int] = None, devices=None):
    """Create a Mesh from an axis-name->size dict, e.g. {'dp': 2, 'tp': 4}."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = {"dp": len(devices)}
    sizes = list(shape.values())
    total = int(_onp.prod(sizes))
    if total > len(devices):
        raise MXNetError(
            f"mesh shape {shape} needs {total} devices, have {len(devices)}")
    arr = _onp.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))


def set_mesh(mesh):
    _state.mesh = mesh
    return mesh


def get_mesh(create=False):
    mesh = getattr(_state, "mesh", None)
    if mesh is None and create:
        import jax

        if len(jax.devices()) >= 1:
            mesh = make_mesh({"dp": len(jax.devices())})
            _state.mesh = mesh
    return mesh


def shrink_mesh(mesh, lost, axis="dp", power_of_two=True):
    """Rebuild ``mesh`` without the ``lost`` index(es) along ``axis`` —
    the elastic-restart primitive (``resilience.elastic``): a chip loss
    takes its whole slice of the named axis (its ICI ring segment), and
    the surviving devices form a smaller mesh of the same axis names.

    ``power_of_two=True`` (default) additionally truncates the surviving
    axis to the largest power of two — collectives on TPU meshes are
    ring-scheduled over power-of-two groups, and dp8→dp4 keeps per-shape
    executables reusable where dp7 would not. Returns the new Mesh (the
    caller decides whether to :func:`set_mesh` it).

    Only data-parallel-like axes (``dp``/``fsdp``) can shrink: dropping a
    slice of a model-parallel axis would change every sharded parameter's
    shape, so that raises :class:`~..resilience.elastic.MeshDegraded`
    naming the unsupported axis. Likewise a non-power-of-two survivor
    count on a *composite* (multi-axis) mesh is rejected even with
    ``power_of_two=False`` — the other axes' ring schedules assume
    power-of-two groups (a single-axis dp mesh may shrink to any size;
    regression-pinned dp8→dp7).
    """
    from jax.sharding import Mesh

    if axis not in mesh.axis_names:
        raise MXNetError(
            f"shrink_mesh: axis {axis!r} not in mesh axes {mesh.axis_names}")
    lost = sorted({int(i) for i in (lost if hasattr(lost, "__iter__")
                                    else [lost])})
    if axis not in ("dp", "fsdp"):
        from ..resilience.elastic import MeshDegraded

        raise MeshDegraded(
            f"shrink_mesh: axis {axis!r} is not a data-parallel axis — "
            "dropping a slice of a model-parallel axis would change every "
            "sharded parameter's shape; only 'dp'/'fsdp' replicas can be "
            "dropped elastically", lost_replicas=lost,
            mesh_size=int(mesh.devices.size))
    ax = mesh.axis_names.index(axis)
    size = mesh.devices.shape[ax]
    bad = [i for i in lost if not 0 <= i < size]
    if bad:
        raise MXNetError(
            f"shrink_mesh: lost indices {bad} out of range for axis "
            f"{axis!r} of size {size}")
    keep = [i for i in range(size) if i not in lost]
    if not power_of_two and len(mesh.axis_names) > 1 \
            and len(keep) > 1 and (len(keep) & (len(keep) - 1)):
        from ..resilience.elastic import MeshDegraded

        raise MeshDegraded(
            f"shrink_mesh: axis {axis!r} would survive with {len(keep)} "
            "slots — not a power of two. On a composite mesh "
            f"{dict(zip(mesh.axis_names, mesh.devices.shape))} the other "
            "axes' ring schedules assume power-of-two groups; use "
            "power_of_two=True to truncate, or rebuild the mesh",
            lost_replicas=lost, mesh_size=int(mesh.devices.size))
    if power_of_two and len(keep) > 1:
        target = 1 << (len(keep).bit_length() - 1)
        keep = keep[:target]
    if not keep:
        raise MXNetError(
            f"shrink_mesh: no surviving devices on axis {axis!r} "
            f"(lost {lost} of {size})")
    arr = _onp.take(mesh.devices, keep, axis=ax)
    return Mesh(arr, mesh.axis_names)


def touched_groups(mesh, lost_devices, axis="dp"):
    """Map arbitrary lost-device addresses to the set of ``axis`` indices
    (dp-groups) they touch. Each entry of ``lost_devices`` is either a flat
    device index into ``mesh.devices`` (C order) or a coordinate dict
    ``{"axis": name, "index": i}`` addressing a whole slice of a named
    axis. Addressing a slice of a *different* axis touches every
    ``axis``-group (the slice crosses all of them)."""
    names = mesh.axis_names
    if axis not in names:
        raise MXNetError(
            f"touched_groups: axis {axis!r} not in mesh axes {names}")
    ax = names.index(axis)
    shape = mesh.devices.shape
    if isinstance(lost_devices, (int, dict)):
        lost_devices = [lost_devices]
    touched = set()
    for dev in lost_devices:
        if isinstance(dev, dict):
            a = dev.get("axis")
            if a not in names:
                raise MXNetError(
                    f"touched_groups: lost-device axis {a!r} not in mesh "
                    f"axes {names}")
            i = int(dev.get("index", 0))
            extent = shape[names.index(a)]
            if not 0 <= i < extent:
                raise MXNetError(
                    f"touched_groups: lost-device index {i} out of range "
                    f"for axis {a!r} of size {extent}")
            if a == axis:
                touched.add(i)
            else:
                # a whole slice of another axis crosses every dp-group
                touched.update(range(shape[ax]))
        else:
            f = int(dev)
            if not 0 <= f < mesh.devices.size:
                raise MXNetError(
                    f"touched_groups: flat device index {f} out of range "
                    f"for mesh of size {mesh.devices.size}")
            coords = _onp.unravel_index(f, shape)
            touched.add(int(coords[ax]))
    return touched


def rebuild_mesh(mesh, lost_devices, axis="dp", power_of_two=True):
    """Composed-mesh elasticity policy: given arbitrary lost device
    coordinates on a (possibly multi-axis) mesh, keep every non-``axis``
    extent (tp/pp) fixed and drop each ``axis``-group (dp-group) touched
    by a loss. A chip loss anywhere in a dp-group breaks that group's ICI
    rings, so the whole group leaves the mesh; the tp/pp structure of the
    survivors is untouched and their sharded parameters keep their shapes.

    ``lost_devices`` entries are flat device indices or coordinate dicts
    ``{"axis": ..., "index": ...}`` (see :func:`touched_groups` —
    coordinate-addressed ``chip_loss`` faults arrive in either form). On a
    composite mesh the survivor count is truncated to the largest power of
    two (ring schedules on the remaining axes assume power-of-two groups);
    a single-axis mesh honors the existing any-size exception when
    ``power_of_two=False``, exactly like :func:`shrink_mesh`.

    Compositions that shard over expert (``ep``, :mod:`.moe`) or sequence
    (``sp``, :mod:`.ring_attention`) axes are pinned *unsupported*: a
    dp-group drop cannot preserve their all-to-all / ring layouts, so the
    loss raises :class:`~..resilience.elastic.MeshDegraded` loudly (with
    ``lost_replicas``/``mesh_size`` populated) instead of silently
    misplacing shards.

    Returns ``(new_mesh, group_map)`` where ``group_map`` maps each
    surviving old dp-group index to its index on the new mesh.
    """
    from jax.sharding import Mesh

    from ..resilience.elastic import MeshDegraded

    names = mesh.axis_names
    if axis not in names:
        raise MXNetError(
            f"rebuild_mesh: axis {axis!r} not in mesh axes {names}")
    ax = names.index(axis)
    size = mesh.devices.shape[ax]
    touched = touched_groups(mesh, lost_devices, axis=axis)
    unsupported = [a for a in names if a in ("ep", "sp")]
    if unsupported and touched:
        raise MeshDegraded(
            f"rebuild_mesh: mesh axes {unsupported} are pinned unsupported "
            "under mesh loss — dropping a dp-group cannot preserve the "
            "MoE all-to-all ('ep') / ring-attention ('sp') layouts; "
            "restart on a fresh mesh instead",
            lost_replicas=sorted(touched), mesh_size=int(mesh.devices.size))
    keep = [i for i in range(size) if i not in touched]
    if not keep:
        raise MeshDegraded(
            f"rebuild_mesh: the loss touches every {axis!r}-group "
            f"(lost {sorted(touched)} of {size}) — no survivor mesh",
            lost_replicas=sorted(touched), mesh_size=int(mesh.devices.size))
    composite = len(names) > 1
    if composite and (len(keep) & (len(keep) - 1)):
        if not power_of_two:
            raise MeshDegraded(
                f"rebuild_mesh: axis {axis!r} would survive with "
                f"{len(keep)} groups — not a power of two. On a composite "
                f"mesh {dict(zip(names, mesh.devices.shape))} the other "
                "axes' ring schedules assume power-of-two groups",
                lost_replicas=sorted(touched),
                mesh_size=int(mesh.devices.size))
        keep = keep[:1 << (len(keep).bit_length() - 1)]
    elif power_of_two and len(keep) > 1:
        keep = keep[:1 << (len(keep).bit_length() - 1)]
    arr = _onp.take(mesh.devices, keep, axis=ax)
    group_map = {int(old): new for new, old in enumerate(keep)}
    return Mesh(arr, names), group_map


def mesh_contexts(mesh, axis="dp", full=False):
    """The :class:`~..device.Context` list matching ``mesh``'s slots along
    ``axis`` (one context per axis index, resolved via the device at the
    zero position of every other axis) — what a data-parallel training
    loop initializes parameter replicas on.

    On a composed mesh each ``axis``-group spans the whole cross-section
    of the other axes; ``full=True`` returns one context *list* per group
    (every device in the group's slice, C order) instead of just the
    zero-position representative — what composed-mesh elasticity uses to
    attribute a lost chip to its dp-group."""
    from ..device import from_jax_device

    if axis not in mesh.axis_names:
        raise MXNetError(
            f"mesh_contexts: axis {axis!r} not in {mesh.axis_names}")
    ax = mesh.axis_names.index(axis)
    if full:
        groups = _onp.moveaxis(mesh.devices, ax, 0)
        return [[from_jax_device(d) for d in grp.ravel()] for grp in groups]
    sel = [0] * mesh.devices.ndim
    out = []
    for i in range(mesh.devices.shape[ax]):
        sel[ax] = i
        out.append(from_jax_device(mesh.devices[tuple(sel)]))
    return out


class mesh_scope:
    """``with mesh_scope({'dp': 4, 'tp': 2}):`` — set + restore global mesh."""

    def __init__(self, shape_or_mesh):
        from jax.sharding import Mesh

        if isinstance(shape_or_mesh, Mesh):
            self._mesh = shape_or_mesh
        else:
            self._mesh = make_mesh(shape_or_mesh)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_state, "mesh", None)
        _state.mesh = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        _state.mesh = self._prev
        return False


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Multi-host init (reference: ps-lite scheduler env / dmlc tracker).

    Maps ``DMLC_*``-style launch to ``jax.distributed.initialize``: no
    scheduler/server roles — every process is a worker (SPMD
    multi-controller, SURVEY.md §7 translation table).

    Arguments left ``None`` are read from the environment the
    ``tools/launch.py`` launcher sets (``MXNET_TPU_COORDINATOR``,
    ``MXNET_TPU_NUM_PROCS``, ``MXNET_TPU_PROC_ID``) with the reference's
    ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``/``DMLC_NUM_WORKER``/
    ``DMLC_WORKER_ID`` accepted as aliases (`tools/launch.py:67-72`,
    `distributed_training.md:262`).
    """
    import os

    import jax

    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("MXNET_TPU_COORDINATOR")
        if coordinator_address is None and "DMLC_PS_ROOT_URI" in env:
            coordinator_address = (env["DMLC_PS_ROOT_URI"] + ":" +
                                   env.get("DMLC_PS_ROOT_PORT", "9091"))
    if num_processes is None:
        v = env.get("MXNET_TPU_NUM_PROCS", env.get("DMLC_NUM_WORKER"))
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = env.get("MXNET_TPU_PROC_ID", env.get("DMLC_WORKER_ID"))
        process_id = int(v) if v is not None else None

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
