"""Device-mesh management for SPMD parallelism.

No reference analog (the reference's parallelism is PS/NCCL data-parallel
only, SURVEY.md §2.3 "absent" list) — this module is the foundation the TPU
build adds: a global ``jax.sharding.Mesh`` with named axes (``dp``, ``fsdp``,
``tp``, ``sp``, ``ep``...) that KVStore, Trainer, and the model zoo's
sharding rules all reference.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence

import numpy as _onp

from ..base import MXNetError

_state = threading.local()


def shard_map_compat(f, mesh, in_specs, out_specs):
    """``jax.shard_map`` across jax versions: the public API renamed the
    replication-check kwarg (check_vma) and older versions only ship
    ``jax.experimental.shard_map`` (check_rep). One shim, shared by the
    pipeline and ring-attention modules."""
    try:
        from jax import shard_map

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def make_mesh(shape: Dict[str, int] = None, devices=None):
    """Create a Mesh from an axis-name->size dict, e.g. {'dp': 2, 'tp': 4}."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = {"dp": len(devices)}
    sizes = list(shape.values())
    total = int(_onp.prod(sizes))
    if total > len(devices):
        raise MXNetError(
            f"mesh shape {shape} needs {total} devices, have {len(devices)}")
    arr = _onp.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, tuple(shape.keys()))


def set_mesh(mesh):
    _state.mesh = mesh
    return mesh


def get_mesh(create=False):
    mesh = getattr(_state, "mesh", None)
    if mesh is None and create:
        import jax

        if len(jax.devices()) >= 1:
            mesh = make_mesh({"dp": len(jax.devices())})
            _state.mesh = mesh
    return mesh


def shrink_mesh(mesh, lost, axis="dp", power_of_two=True):
    """Rebuild ``mesh`` without the ``lost`` index(es) along ``axis`` —
    the elastic-restart primitive (``resilience.elastic``): a chip loss
    takes its whole slice of the named axis (its ICI ring segment), and
    the surviving devices form a smaller mesh of the same axis names.

    ``power_of_two=True`` (default) additionally truncates the surviving
    axis to the largest power of two — collectives on TPU meshes are
    ring-scheduled over power-of-two groups, and dp8→dp4 keeps per-shape
    executables reusable where dp7 would not. Returns the new Mesh (the
    caller decides whether to :func:`set_mesh` it).

    Only data-parallel-like axes (``dp``/``fsdp``) can shrink: dropping a
    slice of a model-parallel axis would change every sharded parameter's
    shape, so that raises :class:`~..resilience.elastic.MeshDegraded`
    naming the unsupported axis. Likewise a non-power-of-two survivor
    count on a *composite* (multi-axis) mesh is rejected even with
    ``power_of_two=False`` — the other axes' ring schedules assume
    power-of-two groups (a single-axis dp mesh may shrink to any size;
    regression-pinned dp8→dp7).
    """
    from jax.sharding import Mesh

    if axis not in mesh.axis_names:
        raise MXNetError(
            f"shrink_mesh: axis {axis!r} not in mesh axes {mesh.axis_names}")
    if axis not in ("dp", "fsdp"):
        from ..resilience.elastic import MeshDegraded

        raise MeshDegraded(
            f"shrink_mesh: axis {axis!r} is not a data-parallel axis — "
            "dropping a slice of a model-parallel axis would change every "
            "sharded parameter's shape; only 'dp'/'fsdp' replicas can be "
            "dropped elastically", mesh_size=int(mesh.devices.size))
    ax = mesh.axis_names.index(axis)
    lost = sorted({int(i) for i in (lost if hasattr(lost, "__iter__")
                                    else [lost])})
    size = mesh.devices.shape[ax]
    bad = [i for i in lost if not 0 <= i < size]
    if bad:
        raise MXNetError(
            f"shrink_mesh: lost indices {bad} out of range for axis "
            f"{axis!r} of size {size}")
    keep = [i for i in range(size) if i not in lost]
    if not power_of_two and len(mesh.axis_names) > 1 \
            and len(keep) > 1 and (len(keep) & (len(keep) - 1)):
        from ..resilience.elastic import MeshDegraded

        raise MeshDegraded(
            f"shrink_mesh: axis {axis!r} would survive with {len(keep)} "
            "slots — not a power of two. On a composite mesh "
            f"{dict(zip(mesh.axis_names, mesh.devices.shape))} the other "
            "axes' ring schedules assume power-of-two groups; use "
            "power_of_two=True to truncate, or rebuild the mesh",
            mesh_size=int(mesh.devices.size))
    if power_of_two and len(keep) > 1:
        target = 1 << (len(keep).bit_length() - 1)
        keep = keep[:target]
    if not keep:
        raise MXNetError(
            f"shrink_mesh: no surviving devices on axis {axis!r} "
            f"(lost {lost} of {size})")
    arr = _onp.take(mesh.devices, keep, axis=ax)
    return Mesh(arr, mesh.axis_names)


def mesh_contexts(mesh, axis="dp"):
    """The :class:`~..device.Context` list matching ``mesh``'s slots along
    ``axis`` (one context per axis index, resolved via the device at the
    zero position of every other axis) — what a data-parallel training
    loop initializes parameter replicas on."""
    from ..device import from_jax_device

    if axis not in mesh.axis_names:
        raise MXNetError(
            f"mesh_contexts: axis {axis!r} not in {mesh.axis_names}")
    ax = mesh.axis_names.index(axis)
    sel = [0] * mesh.devices.ndim
    out = []
    for i in range(mesh.devices.shape[ax]):
        sel[ax] = i
        out.append(from_jax_device(mesh.devices[tuple(sel)]))
    return out


class mesh_scope:
    """``with mesh_scope({'dp': 4, 'tp': 2}):`` — set + restore global mesh."""

    def __init__(self, shape_or_mesh):
        from jax.sharding import Mesh

        if isinstance(shape_or_mesh, Mesh):
            self._mesh = shape_or_mesh
        else:
            self._mesh = make_mesh(shape_or_mesh)
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_state, "mesh", None)
        _state.mesh = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        _state.mesh = self._prev
        return False


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None):
    """Multi-host init (reference: ps-lite scheduler env / dmlc tracker).

    Maps ``DMLC_*``-style launch to ``jax.distributed.initialize``: no
    scheduler/server roles — every process is a worker (SPMD
    multi-controller, SURVEY.md §7 translation table).

    Arguments left ``None`` are read from the environment the
    ``tools/launch.py`` launcher sets (``MXNET_TPU_COORDINATOR``,
    ``MXNET_TPU_NUM_PROCS``, ``MXNET_TPU_PROC_ID``) with the reference's
    ``DMLC_PS_ROOT_URI``/``DMLC_PS_ROOT_PORT``/``DMLC_NUM_WORKER``/
    ``DMLC_WORKER_ID`` accepted as aliases (`tools/launch.py:67-72`,
    `distributed_training.md:262`).
    """
    import os

    import jax

    env = os.environ
    if coordinator_address is None:
        coordinator_address = env.get("MXNET_TPU_COORDINATOR")
        if coordinator_address is None and "DMLC_PS_ROOT_URI" in env:
            coordinator_address = (env["DMLC_PS_ROOT_URI"] + ":" +
                                   env.get("DMLC_PS_ROOT_PORT", "9091"))
    if num_processes is None:
        v = env.get("MXNET_TPU_NUM_PROCS", env.get("DMLC_NUM_WORKER"))
        num_processes = int(v) if v is not None else None
    if process_id is None:
        v = env.get("MXNET_TPU_PROC_ID", env.get("DMLC_WORKER_ID"))
        process_id = int(v) if v is not None else None

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)
