"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp``
mesh axis.

SURVEY.md §2.3: the reference has NO pipeline parallelism (model
parallelism exists only as a manual per-layer device-placement doc) — this
is one of the design-fresh TPU components. The design is the canonical
SPMD pipeline: each device along ``pp`` owns one stage's parameters
(stacked and sharded on the leading axis), activations march through the
ring with ``lax.ppermute`` inside ``shard_map``, and the fill/drain bubble
costs (S-1)/(M+S-1) of the ticks for M microbatches over S stages. The
whole schedule is one differentiable XLA program — reverse-mode flows
back through the permutes, so training works with plain ``jax.grad``.
"""
from __future__ import annotations

from functools import partial

from ..base import MXNetError


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions — shared shim in parallel.mesh."""
    from .mesh import shard_map_compat

    return shard_map_compat(f, mesh, in_specs, out_specs)


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis="pp",
                   num_microbatches=None):
    """Run ``x`` through S pipelined stages.

    ``stage_fn(params, mb) -> mb``: one stage on one microbatch; every
    stage must preserve the microbatch shape (uniform blocks, e.g.
    transformer layers).
    ``stacked_params``: pytree whose leaves are stacked per-stage along a
    leading S axis (sharded ``P(axis)`` on the mesh).
    ``x``: (B, ...) batch, replicated; B must divide into microbatches.

    Returns (B, ...) outputs (replicated), identical to applying the S
    stages sequentially.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        raise MXNetError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    m = num_microbatches or n_stages
    if batch % m:
        raise MXNetError(f"batch {batch} not divisible into {m} microbatches")
    mb = batch // m

    leaves = jax.tree_util.tree_leaves(stacked_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise MXNetError(
                f"stacked param leading dim {leaf.shape[0]} != pipeline "
                f"stages {n_stages}")

    x_mb = x.reshape((m, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_device(params, xs):
        # params: leaves (1, ...) — this device's stage; xs: full (m, mb,...)
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_id = jax.lax.axis_index(axis)
        ticks = m + n_stages - 1

        def tick(t, carry):
            recv, outs = carry
            feed = x_mb_at(xs, t)
            cur = jnp.where(stage_id == 0, feed, recv)
            out = stage_fn(my_params, cur)
            # collect from the last stage once the pipe is full
            is_out = jnp.logical_and(stage_id == n_stages - 1,
                                     t >= n_stages - 1)
            idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            outs = outs.at[idx].set(
                jnp.where(is_out, out, outs[idx]))
            recv = jax.lax.ppermute(out, axis, perm)
            return recv, outs

        def x_mb_at(xs, t):
            idx = jnp.clip(t, 0, m - 1)
            return jax.lax.dynamic_index_in_dim(xs, idx, keepdims=False)

        recv0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        _, outs = jax.lax.fori_loop(0, ticks, tick, (recv0, outs0))
        # only the last stage holds real outputs; broadcast via psum after
        # zeroing every other stage's buffer
        outs = jnp.where(stage_id == n_stages - 1, outs, 0.0)
        return jax.lax.psum(outs, axis)

    pspecs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    result = _shard_map(
        per_device, mesh, (pspecs, P()), P())(stacked_params, x_mb)
    return result.reshape((batch,) + x.shape[1:])


class PipelinedBlock:
    """User-facing pipeline parallelism: wrap a model as
    ``prefix -> [uniform layers] -> suffix`` and train it through
    ``ShardedTrainer`` on a mesh with a ``pp`` axis — the layers are
    partitioned into stages (params stacked on a leading S axis, sharded
    ``P(pp)``), activations march through ``pipeline_apply``'s GPipe
    schedule. Off-mesh (eager, single device, no ``pp`` axis) it runs the
    layers sequentially, so the same object tests/serves everywhere.

    ``layers`` must be structurally uniform, shape-preserving blocks
    (e.g. transformer encoder layers); ``prefix``/``suffix`` are ordinary
    blocks (embedding, head) replicated across the mesh. Schedule: GPipe
    fill/drain — bubble fraction (S-1)/(M+S-1) for M microbatches; with
    the default M = 4*S that is <= 3/(4S+3) (~8.6% at S=8). 1F1B would
    shrink peak activation memory, not the bubble; GPipe is kept for its
    single-``fori_loop`` SPMD form.

    Usage::

        net = PipelinedBlock(prefix=emb, layers=[Layer() for _ in range(8)],
                             suffix=head)
        net.initialize()
        trainer = ShardedTrainer(net, loss, 'adam', {},
                                 mesh=make_mesh({'pp': 4}))
    """

    _pp_axis = "pp"

    def __init__(self, layers, prefix=None, suffix=None, axis="pp",
                 num_microbatches=None, remat=False):
        from ..gluon.nn import HybridSequential

        self._pp_axis = axis
        self._num_microbatches = num_microbatches
        # remat=True wraps each stage application in jax.checkpoint:
        # activations recompute in backward instead of being stored per
        # pipeline tick — the peak-activation-memory benefit 1F1B exists
        # for, delivered compiler-natively (the GPipe bubble itself is
        # schedule-equivalent: (S-1)/(M+S-1) either way)
        self._remat = remat
        self._body = list(layers)
        if not self._body:
            raise MXNetError("PipelinedBlock needs at least one layer")
        self._prefix = prefix
        self._suffix = suffix
        # one container so initialize()/collect_params()/save see all
        self._all = HybridSequential()
        if prefix is not None:
            self._all.add(prefix)
        for b in self._body:
            self._all.add(b)
        if suffix is not None:
            self._all.add(suffix)

    # -- Block-ish surface -------------------------------------------------
    def initialize(self, *a, **k):
        return self._all.initialize(*a, **k)

    def collect_params(self, *a, **k):
        return self._all.collect_params(*a, **k)

    @property
    def _children(self):
        return self._all._children

    def forward(self, x):
        h = x if self._prefix is None else self._prefix(x)
        for b in self._body:
            h = b(h)
        return h if self._suffix is None else self._suffix(h)

    __call__ = forward

    # -- ShardedTrainer hook ----------------------------------------------
    def _pp_functionalize(self, mesh):
        """(apply_fn, params, meta) with body params stacked as
        ``pp::<relative-name>`` leaves; prefix/suffix params keep their
        ordinary names. meta maps stacked names -> per-layer param names
        (for sync_to_block's unstacking)."""
        import jax
        import jax.numpy as jnp

        from .. import autograd
        from .. import random as _rng
        from ..cachedop import _ParamBinding
        from ..ndarray.ndarray import NDArray

        axis = self._pp_axis
        n_stages = mesh.shape[axis]
        if len(self._body) % n_stages:
            raise MXNetError(
                f"{len(self._body)} layers do not partition into "
                f"{n_stages} pipeline stages")
        per_stage = len(self._body) // n_stages

        # name every param by its key in the BLOCK's collect_params() (the
        # names the Trainer, checkpoints and sync_to_block all use)
        all_od = self.collect_params()
        id2name = {id(p): n for n, p in all_od.items()}

        def _is_running_stat(block_or_list, pname):
            # BatchNorm-style state is identified by its layer, not by
            # grad_req: frozen (grad_req='null') ordinary weights and
            # Constants are legitimate and handled as non-trained leaves
            from ..gluon.nn.basic_layers import BatchNorm

            blocks = block_or_list if isinstance(block_or_list, list) \
                else [block_or_list]
            for b in blocks:
                stack = [b]
                while stack:
                    cur = stack.pop()
                    for p in getattr(cur, "_reg_params", {}).values():
                        if p is pname and isinstance(cur, BatchNorm):
                            return True
                    stack.extend(getattr(cur, "_children", {}).values())
            return False

        frozen = set()
        outer = [b for b in (self._prefix, self._suffix) if b is not None]
        outer_names = []
        outer_params = []
        for b in outer:
            for p in b.collect_params().values():
                n = id2name[id(p)]
                outer_names.append(n)
                outer_params.append(p)
                if p.grad_req == "null":
                    if _is_running_stat(b, p):
                        raise MXNetError(
                            "PipelinedBlock does not support mutable-state "
                            f"layers (BatchNorm running stats: {n}); use "
                            "stateless normalization (LayerNorm)")
                    frozen.add(n)  # intentionally frozen: carried untrained
        outer_arrays = [p.data() for p in outer_params]

        layer_ods = [b.collect_params() for b in self._body]
        rel_keys = list(layer_ods[0])
        for od in layer_ods[1:]:
            if list(od) != rel_keys:
                raise MXNetError(
                    "pipeline layers are not structurally uniform")
        frozen_count = {}
        for b, od in zip(self._body, layer_ods):
            for k, p in od.items():
                if p.grad_req == "null":
                    if _is_running_stat(b, p):
                        raise MXNetError(
                            "PipelinedBlock does not support mutable-state "
                            f"layers (BatchNorm running stats: {k}) in the "
                            "pipeline body; use stateless normalization "
                            "(LayerNorm)")
                    frozen_count[k] = frozen_count.get(k, 0) + 1
        for k, c in frozen_count.items():
            if c != len(self._body):
                # one stacked leaf updates as a unit: freezing SOME layers
                # of it cannot be honored — reject loudly rather than
                # silently freezing the rest
                raise MXNetError(
                    f"pipeline body param {k!r} is frozen in {c} of "
                    f"{len(self._body)} layers; freezing must be uniform "
                    "across the pipeline body (one stacked leaf trains as "
                    "a unit)")
            frozen.add(f"pp::{k}")
        layer0 = self._body[0]
        layer0_arrays = [p.data() for p in layer_ods[0].values()]

        params = {}
        meta = {}
        for j, rel in enumerate(rel_keys):
            stacked = jnp.stack(
                [list(od.values())[j].data()._data for od in layer_ods])
            # (L, ...) -> (S, per_stage, ...): stage-major for P(pp)
            stacked = stacked.reshape(
                (n_stages, per_stage) + stacked.shape[1:])
            params[f"pp::{rel}"] = stacked
            meta[f"pp::{rel}"] = [
                id2name[id(list(od.values())[j])] for od in layer_ods]
        for n, arr in zip(outer_names, outer_arrays):
            params[n] = arr._data

        prefix, suffix = self._prefix, self._suffix
        num_mb = self._num_microbatches

        def _one_layer(tracer_list, h):
            with _ParamBinding(layer0_arrays, list(tracer_list)):
                return layer0.forward(NDArray(h))._data

        if self._remat:
            _one_layer = jax.checkpoint(_one_layer)

        def stage_fn(pslice, mb):
            # pslice leaves: (per_stage, ...) — apply the per_stage layers
            # this device owns, sequentially, re-binding layer0's arrays
            h = mb
            for li in range(per_stage):
                tracers = tuple(
                    pslice[f"pp::{rel}"][li] for rel in rel_keys)
                h = _one_layer(tracers, h)
            return h

        def apply_fn(param_datas, x, rng_key=None):
            if rng_key is None:
                rng_key = _rng.next_key()
            _rng.push_trace_rng(rng_key)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(True)
            try:
                tracers = [param_datas[n] for n in outer_names]
                with _ParamBinding(outer_arrays, tracers):
                    h_nd = x if isinstance(x, NDArray) else NDArray(x)
                    if prefix is not None:
                        h_nd = prefix(h_nd)
                    stacked = {k: v for k, v in param_datas.items()
                               if k.startswith("pp::")}
                    hd = pipeline_apply(
                        lambda ps, mb: stage_fn(ps, mb),
                        stacked, h_nd._data, mesh, axis=axis,
                        num_microbatches=num_mb)
                    h_nd = NDArray(hd)
                    if suffix is not None:
                        h_nd = suffix(h_nd)
                return h_nd._data
            finally:
                autograd.set_training(prev_train)
                autograd.set_recording(prev_rec)
                _rng.pop_trace_rng()

        meta["__frozen__"] = frozen
        return apply_fn, params, meta


def stack_stage_params(param_list, mesh=None, axis="pp"):
    """Stack per-stage param pytrees along a leading axis and (optionally)
    shard them ``P(axis)`` — the layout ``pipeline_apply`` consumes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *param_list)
    if mesh is not None:
        def place(leaf):
            spec = P(axis, *([None] * (leaf.ndim - 1)))
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        stacked = jax.tree_util.tree_map(place, stacked)
    return stacked
