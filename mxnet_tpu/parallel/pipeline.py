"""Pipeline parallelism: GPipe-style microbatch schedule over a ``pp``
mesh axis.

SURVEY.md §2.3: the reference has NO pipeline parallelism (model
parallelism exists only as a manual per-layer device-placement doc) — this
is one of the design-fresh TPU components. The design is the canonical
SPMD pipeline: each device along ``pp`` owns one stage's parameters
(stacked and sharded on the leading axis), activations march through the
ring with ``lax.ppermute`` inside ``shard_map``, and the fill/drain bubble
costs (S-1)/(M+S-1) of the ticks for M microbatches over S stages. The
whole schedule is one differentiable XLA program — reverse-mode flows
back through the permutes, so training works with plain ``jax.grad``.
"""
from __future__ import annotations

from functools import partial

from ..base import MXNetError


def _shard_map(f, mesh, in_specs, out_specs):
    """shard_map across jax versions (check_vma vs check_rep kwarg)."""
    try:
        from jax import shard_map

        return shard_map(f, mesh=mesh, in_specs=in_specs,
                         out_specs=out_specs, check_vma=False)
    except (ImportError, TypeError):
        from jax.experimental.shard_map import shard_map as sm

        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_rep=False)


def pipeline_apply(stage_fn, stacked_params, x, mesh, axis="pp",
                   num_microbatches=None):
    """Run ``x`` through S pipelined stages.

    ``stage_fn(params, mb) -> mb``: one stage on one microbatch; every
    stage must preserve the microbatch shape (uniform blocks, e.g.
    transformer layers).
    ``stacked_params``: pytree whose leaves are stacked per-stage along a
    leading S axis (sharded ``P(axis)`` on the mesh).
    ``x``: (B, ...) batch, replicated; B must divide into microbatches.

    Returns (B, ...) outputs (replicated), identical to applying the S
    stages sequentially.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    if axis not in mesh.axis_names:
        raise MXNetError(f"mesh has no {axis!r} axis: {mesh.axis_names}")
    n_stages = mesh.shape[axis]
    batch = x.shape[0]
    m = num_microbatches or n_stages
    if batch % m:
        raise MXNetError(f"batch {batch} not divisible into {m} microbatches")
    mb = batch // m

    leaves = jax.tree_util.tree_leaves(stacked_params)
    for leaf in leaves:
        if leaf.shape[0] != n_stages:
            raise MXNetError(
                f"stacked param leading dim {leaf.shape[0]} != pipeline "
                f"stages {n_stages}")

    x_mb = x.reshape((m, mb) + x.shape[1:])
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def per_device(params, xs):
        # params: leaves (1, ...) — this device's stage; xs: full (m, mb,...)
        my_params = jax.tree_util.tree_map(lambda p: p[0], params)
        stage_id = jax.lax.axis_index(axis)
        ticks = m + n_stages - 1

        def tick(t, carry):
            recv, outs = carry
            feed = x_mb_at(xs, t)
            cur = jnp.where(stage_id == 0, feed, recv)
            out = stage_fn(my_params, cur)
            # collect from the last stage once the pipe is full
            is_out = jnp.logical_and(stage_id == n_stages - 1,
                                     t >= n_stages - 1)
            idx = jnp.clip(t - (n_stages - 1), 0, m - 1)
            outs = outs.at[idx].set(
                jnp.where(is_out, out, outs[idx]))
            recv = jax.lax.ppermute(out, axis, perm)
            return recv, outs

        def x_mb_at(xs, t):
            idx = jnp.clip(t, 0, m - 1)
            return jax.lax.dynamic_index_in_dim(xs, idx, keepdims=False)

        recv0 = jnp.zeros_like(xs[0])
        outs0 = jnp.zeros_like(xs)
        _, outs = jax.lax.fori_loop(0, ticks, tick, (recv0, outs0))
        # only the last stage holds real outputs; broadcast via psum after
        # zeroing every other stage's buffer
        outs = jnp.where(stage_id == n_stages - 1, outs, 0.0)
        return jax.lax.psum(outs, axis)

    pspecs = jax.tree_util.tree_map(lambda _: P(axis), stacked_params)
    result = _shard_map(
        per_device, mesh, (pspecs, P()), P())(stacked_params, x_mb)
    return result.reshape((batch,) + x.shape[1:])


def stack_stage_params(param_list, mesh=None, axis="pp"):
    """Stack per-stage param pytrees along a leading axis and (optionally)
    shard them ``P(axis)`` — the layout ``pipeline_apply`` consumes."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs), *param_list)
    if mesh is not None:
        def place(leaf):
            spec = P(axis, *([None] * (leaf.ndim - 1)))
            return jax.device_put(leaf, NamedSharding(mesh, spec))

        stacked = jax.tree_util.tree_map(place, stacked)
    return stacked
