"""Mixture-of-Experts with expert parallelism over an ``ep`` mesh axis.

SURVEY.md §2.3: expert parallelism is absent in the reference — another
design-fresh TPU component. The layer is the Switch/Mesh-TensorFlow
formulation: a learned router picks top-k experts per token, tokens are
dispatched into fixed-capacity expert buffers with one einsum (static
shapes — no dynamic gather, SURVEY §7 hard part 3), expert FFNs run
sharded over ``ep`` (XLA inserts the all-to-all when token and expert
shardings differ), and a second einsum combines weighted outputs.
Everything is differentiable; router load-balancing uses the standard
auxiliary loss (Shazeer et al., Switch Transformer).
"""
from __future__ import annotations

import math

from ..base import MXNetError
from ..gluon.block import HybridBlock
from ..gluon.parameter import Parameter
from ..ndarray.ndarray import NDArray
from ..ops import registry as _registry


def _maybe_constrain(arr, mesh, axis):
    """Pin the expert dim to the ``ep`` axis (this is what makes XLA place
    the all-to-all) — skipped in eager execution where a single-device
    array can't take a mesh-wide constraint."""
    if mesh is None or axis not in mesh.axis_names:
        return arr
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    spec = P(axis, *([None] * (arr.ndim - 1)))
    try:
        return jax.lax.with_sharding_constraint(
            arr, NamedSharding(mesh, spec))
    except Exception:
        return arr


def moe_dispatch_combine(x, router_logits, expert_fn, num_experts,
                         capacity, mesh=None, axis="ep"):
    """Functional MoE core on raw arrays (jit/shard-friendly).

    x: (N, d) tokens; router_logits: (N, E); expert_fn(i_params?) — here
    expert computation is a closure ``expert_fn(expert_inputs) ->
    expert_outputs`` mapping (E, C, d) -> (E, C, d_out).
    Returns (out (N, d_out), aux_loss scalar).
    """
    import jax
    import jax.numpy as jnp

    n, _ = x.shape
    e, c = num_experts, capacity
    probs = jax.nn.softmax(router_logits, axis=-1)          # (N, E)
    expert_idx = jnp.argmax(probs, axis=-1)                 # top-1 (N,)
    expert_1h = jax.nn.one_hot(expert_idx, e, dtype=x.dtype)
    gate = jnp.sum(probs * expert_1h, axis=-1)              # (N,)

    # position of each token inside its expert's buffer; tokens past the
    # capacity are dropped (residual passes them through unchanged)
    pos = jnp.cumsum(expert_1h, axis=0) * expert_1h - 1.0   # (N, E)
    in_cap = (pos < c) & (expert_1h > 0)
    pos_1h = jax.nn.one_hot(pos.astype(jnp.int32), c, dtype=x.dtype)
    dispatch = expert_1h[:, :, None] * pos_1h * in_cap[:, :, None]
    # (N, E, C) 0/1 dispatch tensor
    expert_inputs = jnp.einsum("nec,nd->ecd", dispatch, x)
    expert_inputs = _maybe_constrain(expert_inputs, mesh, axis)
    expert_outputs = expert_fn(expert_inputs)               # (E, C, do)
    expert_outputs = _maybe_constrain(expert_outputs, mesh, axis)
    combine = dispatch * gate[:, None, None]                # (N, E, C)
    out = jnp.einsum("nec,ecd->nd", combine, expert_outputs)

    # Switch-style load-balance loss: E * sum_e fraction_e * prob_mass_e
    frac = expert_1h.mean(axis=0)
    mass = probs.mean(axis=0)
    aux = e * jnp.sum(frac * mass)
    return out, aux


class MoEBlock(HybridBlock):
    """Drop-in FFN replacement: router + E expert FFNs, expert-parallel.

    Usage in a transformer: swap ``PositionwiseFFN`` for
    ``MoEBlock(units, hidden_size, num_experts=8)``; shard expert params
    with ``moe_sharding_rules()`` (P('ep', ...) on the leading expert dim).

    Load-balance auxiliary loss: each forward sets ``self.aux_loss``.
    ``ShardedTrainer`` collects it automatically inside its compiled step
    (``aux_loss_weight``). In eager training add it to the objective
    yourself (``loss = ce + 0.01 * net.moe.aux_loss``); under plain
    ``hybridize()`` the attribute holds a stale trace value — use
    ShardedTrainer (or eager) when training MoE.
    """

    def __init__(self, units, hidden_size, num_experts=8,
                 capacity_factor=1.25, activation="gelu", **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._hidden = hidden_size
        self._e = num_experts
        self._cap_factor = capacity_factor
        self._act = activation
        self.router = Parameter("router", shape=(units, num_experts))
        # expert weights carry a leading E axis -> shardable over 'ep'
        self.w1 = Parameter("w1", shape=(num_experts, units, hidden_size))
        self.b1 = Parameter("b1", shape=(num_experts, hidden_size),
                            init="zeros")
        self.w2 = Parameter("w2", shape=(num_experts, hidden_size, units))
        self.b2 = Parameter("b2", shape=(num_experts, units), init="zeros")
        self.aux_loss = None

    def forward(self, x):
        import jax
        import jax.numpy as jnp

        from . import mesh as mesh_mod

        b, t, d = x.shape
        cap = max(1, int(math.ceil(b * t / self._e * self._cap_factor)))
        act_name = self._act
        e = self._e
        mesh = mesh_mod.get_mesh()

        def f(xd, router, w1, b1, w2, b2):
            tokens = xd.reshape(b * t, d)
            logits = tokens @ router

            def experts(inp):  # (E, C, d)
                h = jnp.einsum("ecd,edh->ech", inp, w1) + b1[:, None, :]
                if act_name == "gelu":
                    h = jax.nn.gelu(h)
                elif act_name == "relu":
                    h = jax.nn.relu(h)
                else:
                    h = jnp.tanh(h)
                return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]

            out, aux = moe_dispatch_combine(
                tokens, logits, experts, e, cap, mesh=mesh)
            return out.reshape(b, t, d), aux

        out, aux = _registry.apply(
            f, (x, self.router.data(), self.w1.data(), self.b1.data(),
                self.w2.data(), self.b2.data()),
            name="moe", cacheable=False)
        self.aux_loss = aux
        return out


def moe_sharding_rules(prefix=""):
    """PartitionSpecs placing each expert's weights on its ``ep`` device."""
    from jax.sharding import PartitionSpec as P

    return [
        (prefix + r".*\.(w1|w2)$", P("ep", None, None)),
        (prefix + r".*\.(b1|b2)$", P("ep", None)),
        (prefix + r".*\.router$", P()),
    ]
