"""``mxnet_tpu.parallel`` — SPMD parallelism over device meshes.

The subsystems the reference lacks and SURVEY.md requires designed fresh:
tensor/pipeline/sequence/expert parallelism and ZeRO-style sharding, built
on ``jax.sharding`` + XLA collectives.
"""
from __future__ import annotations

from . import mesh
from .mesh import get_mesh, initialize_distributed, make_mesh, mesh_scope, set_mesh
from . import functional
from .functional import ShardedTrainer, ShardingRules, functionalize
