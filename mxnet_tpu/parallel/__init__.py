"""``mxnet_tpu.parallel`` — SPMD parallelism over device meshes.

The subsystems the reference lacks and SURVEY.md requires designed fresh:
tensor/pipeline/sequence/expert parallelism and ZeRO-style sharding, built
on ``jax.sharding`` + XLA collectives.
"""
from __future__ import annotations

from . import mesh
from .mesh import (get_mesh, initialize_distributed, make_mesh, mesh_scope,
                   rebuild_mesh, set_mesh, shrink_mesh, touched_groups)
from . import functional
from .functional import (ParallelConfig, ShardedTrainer, ShardingRules,
                         functionalize)
from . import pipeline
from .pipeline import PipelinedBlock, pipeline_apply, stack_stage_params
from . import moe
from .moe import MoEBlock, moe_dispatch_combine, moe_sharding_rules
from . import ring_attention
from .ring_attention import ring_attention as ring_attention_fn  # noqa: F401
from .ring_attention import sequence_sharded, ulysses_attention  # noqa: F401
