"""Ring attention: context parallelism over a sequence-sharded mesh axis.

No reference analog (SURVEY.md §5: "long-context / sequence parallelism —
absent... design fresh"). Design follows the blockwise ring schedule (Liu &
Abbeel 2310.01889): Q stays resident per device; K/V blocks rotate around
the ``sp`` ring via ``lax.ppermute`` (ICI neighbor exchange) while a running
online-softmax (m, l, acc) merges each visiting block — the same math as
flash attention, distributed. Peak memory per device is O(T/n · T/n) and
the K/V transfer overlaps with the block matmul, so sequence length scales
linearly with ring size.
"""
from __future__ import annotations

import math

from ..base import MXNetError


def ring_attention(q, k, v, mesh=None, axis="sp", causal=False, scale=None):
    """Attention over (B, H, T, D) arrays whose T axis is sharded on
    ``axis``. Returns the same sharding. Eager-safe: jit/shard_map inside."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import mesh as mesh_mod
    from .mesh import shard_map_compat

    mesh = mesh if mesh is not None else mesh_mod.get_mesh(create=True)
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(f"ring_attention needs a mesh with axis {axis!r}")

    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    n = mesh.shape[axis]
    if q.shape[2] % n != 0:
        raise MXNetError(
            f"sequence length {q.shape[2]} not divisible by {axis}={n}")

    spec = P(None, None, axis, None)

    def _wrap(fn):
        return shard_map_compat(fn, mesh, (spec, spec, spec), spec)

    @_wrap
    def inner(ql, kl, vl):
        # ql/kl/vl: (B, H, Tl, D) local blocks
        b, h, tl, dd = ql.shape
        my = jax.lax.axis_index(axis)
        qf = ql.astype(jnp.float32) * s
        q_pos = my * tl + jax.lax.broadcasted_iota(jnp.int32, (tl, tl), 0)

        def block_update(i, m, l, acc, kb, vb):
            """Merge one visiting K/V block into the online softmax."""
            src = (my - i) % n  # which global block kb currently holds
            sc = jnp.einsum("bhqd,bhkd->bhqk", qf, kb.astype(jnp.float32))
            if causal:
                k_pos = src * tl + jax.lax.broadcasted_iota(
                    jnp.int32, (tl, tl), 1)
                sc = jnp.where(q_pos >= k_pos, sc, -jnp.inf)
            m_cur = jnp.max(sc, axis=-1, keepdims=True)
            m_new = jnp.maximum(m, m_cur)
            # fully-masked rows keep m = -inf; guard the exp shift
            shift = jnp.where(jnp.isinf(m_new), 0.0, m_new)
            p = jnp.exp(sc - shift)
            alpha = jnp.where(jnp.isinf(m), 0.0, jnp.exp(m - shift))
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            acc = acc * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
            return m_new, l, acc

        def step(i, carry):
            m, l, acc, kb, vb = carry
            m, l, acc = block_update(i, m, l, acc, kb, vb)
            # rotate K/V to the next device on the ring (ICI neighbor hop)
            perm = [(j, (j + 1) % n) for j in range(n)]
            kb = jax.lax.ppermute(kb, axis, perm)
            vb = jax.lax.ppermute(vb, axis, perm)
            return m, l, acc, kb, vb

        m0 = jnp.full((b, h, tl, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((b, h, tl, 1), jnp.float32)
        a0 = jnp.zeros((b, h, tl, dd), jnp.float32)
        # n-1 rotating steps, then the final visiting block without the
        # rotation (its ppermute output would be discarded — dead ICI traffic)
        m, l, acc, kb, vb = jax.lax.fori_loop(
            0, n - 1, step, (m0, l0, a0, kl, vl))
        m, l, acc = block_update(n - 1, m, l, acc, kb, vb)
        l = jnp.where(l == 0.0, 1.0, l)
        return (acc / l).astype(ql.dtype)

    return inner(q, k, v)


def sequence_sharded(x, mesh=None, axis="sp", dim=2):
    """Place an array with dimension ``dim`` sharded over the ``axis`` ring."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import mesh as mesh_mod

    mesh = mesh if mesh is not None else mesh_mod.get_mesh(create=True)
    parts = [None] * x.ndim
    parts[dim] = axis
    return jax.device_put(x, NamedSharding(mesh, P(*parts)))


def ulysses_attention(q, k, v, mesh=None, axis="sp", causal=False,
                      scale=None):
    """DeepSpeed-Ulysses-style sequence parallelism: the all-to-all
    alternative to the ring schedule (SURVEY §5 mandates one of the two;
    this stack ships both).

    Inputs (B, H, T, D) with T sharded over ``axis``. Two
    ``lax.all_to_all`` collectives re-partition sequence-sharded
    activations into HEAD-sharded ones (each device holds H/n full-length
    heads), plain attention runs locally at full sequence length, and the
    inverse all-to-all restores sequence sharding. Communication is
    2 all-to-alls of the qkv/out tensors over ICI vs the ring's n-1
    neighbor permutes; compute is a single dense attention — better MXU
    shape than ring blocks at moderate T, while ring wins when T²/n
    scores no longer fit.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from . import mesh as mesh_mod
    from .mesh import shard_map_compat

    mesh = mesh if mesh is not None else mesh_mod.get_mesh(create=True)
    if mesh is None or axis not in mesh.axis_names:
        raise MXNetError(f"ulysses_attention needs a mesh with axis {axis!r}")
    n = mesh.shape[axis]
    if q.shape[1] % n != 0:
        raise MXNetError(
            f"num_heads {q.shape[1]} not divisible by {axis}={n} "
            "(Ulysses shards heads during compute)")
    if q.shape[2] % n != 0:
        raise MXNetError(
            f"sequence length {q.shape[2]} not divisible by {axis}={n}")
    d = q.shape[-1]
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    spec = P(None, None, axis, None)

    def _wrap(fn):
        return shard_map_compat(fn, mesh, (spec, spec, spec), spec)

    @_wrap
    def inner(ql, kl, vl):
        # local blocks (B, H, T/n, D) -> all_to_all -> (B, H/n, T, D)
        def seq2head(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=2,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=2, concat_axis=1,
                                      tiled=True)

        qh = seq2head(ql).astype(jnp.float32)
        kh = seq2head(kl).astype(jnp.float32)
        vh = seq2head(vl).astype(jnp.float32)
        sc = jnp.einsum("bhqd,bhkd->bhqk", qh, kh) * s
        if causal:
            t = sc.shape[-1]
            cm = jnp.tril(jnp.ones((t, t), bool))
            sc = jnp.where(cm, sc, -jnp.inf)
        w = jax.nn.softmax(sc, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", w, vh)
        return head2seq(out).astype(ql.dtype)

    return inner(q, k, v)
