"""Functionalization + SPMD sharded training step.

The reference's distributed step (SURVEY.md §3.4) is imperative: per-param
``kvstore.pushpull`` after backward, optimizer on worker or server. The
TPU-native step is one compiled SPMD program: params/optimizer state laid out
over a ``jax.sharding.Mesh`` by named rules, batch sharded over ``dp``(+``sp``),
gradients reduced by XLA-inserted collectives over ICI, update fused into the
same executable. This module provides:

* :func:`functionalize` — pure ``fn(params, *args)`` view of any Gluon
  ``Block`` (the deferred-compute trace collapsed onto jax tracing).
* sharding rules — regex → ``PartitionSpec`` tables with an fsdp-style
  default, the declarative replacement for ps-lite key sharding
  (``EncodeDefaultKey``, ``src/kvstore/kvstore_dist.h:621``).
* :class:`ShardedTrainer` — the ``gluon.Trainer`` analog whose ``step`` is a
  single pjit'd (loss, grads, allreduce, update) program.
"""
from __future__ import annotations

import re
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError


def _jax():
    import jax

    return jax


def _P():
    from jax.sharding import PartitionSpec

    return PartitionSpec


# ---------------------------------------------------------------------------
# functionalize
# ---------------------------------------------------------------------------


def functionalize(block, train_mode=False):
    """Return ``(apply_fn, params)`` for a Gluon block.

    ``apply_fn(params_dict, *args)`` is pure and jittable: it replays
    ``block.forward`` with the dict's arrays bound to the block's parameters
    (the CachedOp trick, ``mxnet_tpu/cachedop.py``). Outputs are raw jax
    arrays. Parameter shapes must already be materialized (run one eager
    forward first for deferred-shape layers).

    When ``train_mode`` and the block holds mutable state (BatchNorm running
    stats — ``grad_req='null'`` parameters), ``apply_fn`` returns
    ``(outputs, new_state_dict)`` so callers can carry state functionally.
    """
    from .. import autograd
    from .. import random as _rng
    from ..cachedop import _ParamBinding
    from ..ndarray.ndarray import NDArray

    params_od = block.collect_params()
    names = list(params_od)
    arrays = [params_od[n].data() for n in names]
    state_names = [n for n in names if params_od[n].grad_req == "null"]

    def apply_fn(param_datas, *arg_datas, rng_key=None):
        import jax

        tracers = [param_datas[n] for n in names]
        wrapped_args = [NDArray(d) for d in arg_datas]
        with _ParamBinding(arrays, tracers):
            if rng_key is None:
                rng_key = _rng.next_key()
            _rng.push_trace_rng(rng_key)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(train_mode)
            try:
                outs = block.forward(*wrapped_args)
            finally:
                autograd.set_training(prev_train)
                autograd.set_recording(prev_rec)
                _rng.pop_trace_rng()
            new_state = {n: a._data for n, a in zip(names, arrays)
                         if n in state_names}
        flat, tree = jax.tree_util.tree_flatten(
            outs, is_leaf=lambda x: isinstance(x, NDArray))
        datas = [o._data if isinstance(o, NDArray) else o for o in flat]
        out = jax.tree_util.tree_unflatten(tree, datas)
        if train_mode and state_names:
            return out, new_state
        return out

    params = {n: a._data for n, a in zip(names, arrays)}
    return apply_fn, params


def functionalize_abstract(block):
    """``functionalize`` for compile-only flows: parameters are NEVER
    materialized. Returns ``(apply_fn, {name: jax.ShapeDtypeStruct})``.

    Every uninitialized Parameter must carry a complete static shape (the
    model must be built with explicit ``in_units``/``in_channels``) — it
    gets a 0-element placeholder slot whose only job is identity for the
    trace-time rebinding (``_ParamBinding`` swaps ``_data`` for the
    tracer, so the placeholder's shape is never read). This is what makes
    an 8B-parameter AOT memory proof possible on a laptop-sized host
    (VERDICT r3 item 5): nothing but ShapeDtypeStructs ever exists.
    """
    import jax
    from collections import OrderedDict

    import numpy as _np

    from ..device import cpu
    from ..ndarray.ndarray import NDArray

    params_od = block.collect_params()
    structs = {}
    placeholders = []
    for n, p in params_od.items():
        if getattr(p, "_abstract_placeholder", False):
            # idempotent re-functionalization (second abstract trainer on
            # the same block): lift the poison while we re-capture slots
            p._abstract_placeholder = False
            placeholders.append(p)
        elif p._data is None:
            if not _param_shape_complete(p.shape):
                raise MXNetError(
                    f"functionalize_abstract: parameter {n!r} has "
                    f"incomplete shape {p.shape}; build the model with "
                    "explicit in_units/in_channels so shapes are static")
            import jax.numpy as jnp

            slot = NDArray(jnp.zeros((0,), p.dtype or _np.float32))
            p._data = OrderedDict({cpu(): slot})
            placeholders.append(p)
        structs[n] = jax.ShapeDtypeStruct(
            tuple(p.shape), p.dtype or _np.float32)
    apply_fn, _ = functionalize(block, train_mode=True)
    # poison AFTER functionalize captured the slots: the placeholder must
    # never leak into eager use — Parameter.data()/initialize() raise on
    # it outside a trace (inside a trace the slot is rebound to a tracer)
    for p in placeholders:
        p._abstract_placeholder = True
    return apply_fn, structs


def _param_shape_complete(shape):
    return shape is not None and all(
        isinstance(s, int) and s > 0 for s in shape)


def _cost_analysis_of(compiled):
    """Normalize jax Compiled.cost_analysis() across jax versions (older
    ones return a one-element list)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def _collect_aux_losses(block):
    """Sum `aux_loss` values the forward just set on any sub-block (MoE
    router load-balance terms). Values are tracers from THIS trace — read
    immediately inside the loss closure, never cached."""
    total = None
    stack = [block]
    while stack:
        b = stack.pop()
        aux = getattr(b, "aux_loss", None)
        if aux is not None:
            from ..ndarray.ndarray import NDArray

            a = aux._data if isinstance(aux, NDArray) else aux
            total = a if total is None else total + a
        stack.extend(getattr(b, "_children", {}).values())
    return total


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------


class ShardingRules:
    """Ordered ``(regex, PartitionSpec)`` table mapping param names to specs.

    First match wins; no match → fsdp default (if an ``fsdp`` axis exists:
    shard the largest divisible dim) else fully replicated.
    """

    def __init__(self, rules: Sequence[Tuple[str, object]] = (),
                 default_axis: Optional[str] = "fsdp"):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default_axis = default_axis

    def spec_for(self, name, shape, mesh):
        P = _P()
        for pat, spec in self.rules:
            if pat.search(name):
                # rank-dependent rules (pipeline-stacked leaves) are
                # callables shape -> PartitionSpec
                return spec(shape) if callable(spec) else spec
        if self.default_axis and self.default_axis in mesh.axis_names:
            n = mesh.shape[self.default_axis]
            # largest dim divisible by the fsdp axis size, else replicate
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % n == 0 and shape[i] >= n:
                    parts = [None] * len(shape)
                    parts[i] = self.default_axis
                    return P(*parts)
        return P()

    def shard(self, params: Dict[str, object], mesh):
        """Place a param dict onto the mesh per the rules.

        Copies rather than aliasing: device_put can reuse the source buffer
        for the matching shard, and ShardedTrainer donates these arrays —
        donation must never free a buffer the caller's Block still owns.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        out = {}
        for name, arr in params.items():
            spec = self.spec_for(name, arr.shape, mesh)
            out[name] = jax.device_put(jnp.array(arr, copy=True),
                                       NamedSharding(mesh, spec))
        return out


# ---------------------------------------------------------------------------
# declarative parallel composition
# ---------------------------------------------------------------------------


class ParallelConfig:
    """Declarative dp×tp(×pp) composition for :class:`ShardedTrainer`.

    ``ParallelConfig(dp=2, tp=2)`` names the mesh the trainer runs over:
    ``dp`` data-parallel groups (the batch axis; ZeRO flat buckets shard
    over it), ``tp``-way tensor parallelism (explicit ``shard_map``
    collectives following the param rules' layouts), and optionally
    ``pp`` pipeline stages (the ``parallel.pipeline`` path; tp and pp do
    not compose yet). ``resilience.elastic`` rebuilds trainers from these
    three integers after chip loss: dp shrinks to the survivor groups
    while the tp/pp extents stay pinned (``parallel.mesh.rebuild_mesh``).
    """

    def __init__(self, dp, tp=1, pp=0):
        self.dp = int(dp)
        self.tp = int(tp)
        self.pp = int(pp)
        if self.dp < 1 or self.tp < 1 or self.pp < 0:
            raise MXNetError(
                f"ParallelConfig needs dp>=1, tp>=1, pp>=0; got "
                f"dp={dp}, tp={tp}, pp={pp}")

    def mesh_shape(self):
        """Axis-name -> extent dict for ``make_mesh``. ``dp`` is always
        present (the batch spec needs its axis even at extent 1); tp/pp
        appear only when actually used."""
        shape = {"dp": self.dp}
        if self.tp > 1:
            shape["tp"] = self.tp
        if self.pp > 0:
            shape["pp"] = self.pp
        return shape

    def __repr__(self):
        return f"ParallelConfig(dp={self.dp}, tp={self.tp}, pp={self.pp})"


# ---------------------------------------------------------------------------
# sharded training step
# ---------------------------------------------------------------------------


class ShardedTrainer:
    """SPMD trainer: the whole step is one compiled XLA program.

    Replaces the reference's step (forward → backward → per-param
    ``kvstore.pushpull`` → per-param optimizer kernels) with a single pjit:
    data parallelism comes from sharding the batch (``batch_spec``), tensor
    parallelism from the param rules, and gradient reduction from XLA's
    automatic collective insertion — serving the role the `Comm`/ps-lite/NCCL
    stack plays in `src/kvstore/` but riding ICI.

    Usage::

        trainer = ShardedTrainer(net, loss_fn, 'sgd',
                                 {'learning_rate': 0.1}, mesh=mesh,
                                 rules=ShardingRules([(r'dense\\d+.weight',
                                                       P('tp', None))]))
        loss = trainer.step(x, y)          # one fused SPMD step
        trainer.sync_to_block()            # write weights back to the Block
    """

    def __init__(self, block, loss_fn, optimizer, optimizer_params=None,
                 mesh=None, rules: Optional[ShardingRules] = None,
                 batch_spec=None, dtype=None, aux_loss_weight=0.01,
                 abstract=False, zero_bucket_mb=None, parallel=None):
        import jax
        from jax.sharding import NamedSharding

        from ..optimizer import optimizer as opt_mod
        from . import mesh as mesh_mod

        self.block = block
        self._abstract = bool(abstract)
        self.loss_fn = loss_fn
        if isinstance(optimizer, str):
            self.optimizer = opt_mod.create(optimizer,
                                            **(optimizer_params or {}))
        else:
            self.optimizer = optimizer
        self._parallel = parallel
        self._use_shard_map = False
        if parallel is not None:
            if parallel.tp > 1 and parallel.pp:
                raise MXNetError(
                    "ParallelConfig: composed tp×pp is not supported yet — "
                    "run tp (shard_map) or pp (pipeline) but not both")
            if mesh is None:
                mesh = mesh_mod.make_mesh(parallel.mesh_shape())
            else:
                for ax, n in parallel.mesh_shape().items():
                    if int(mesh.shape.get(ax, 0)) != n:
                        raise MXNetError(
                            f"ParallelConfig wants {ax}={n} but the given "
                            f"mesh has {ax}={mesh.shape.get(ax, 'absent')}")
            self._use_shard_map = parallel.tp > 1
        self.mesh = mesh if mesh is not None else mesh_mod.get_mesh(create=True)
        if self.mesh is None:
            raise MXNetError("ShardedTrainer needs a device mesh")
        if rules is None:
            # under a declarative ParallelConfig the ZeRO default axis is
            # dp: unruled params bucket over the dp groups while tp/pp
            # layouts come from explicit rules
            rules = ShardingRules(default_axis="dp") \
                if parallel is not None else ShardingRules()
        self.rules = rules
        # AMP policy (amp.py bf16-first): compute casts float params+inputs
        # to `dtype` inside the step; master weights, grads and the update
        # stay fp32 — the multi-precision layout of optimizer_op-inl.h
        self._dtype = dtype
        # blocks exposing `aux_loss` (MoE router balance) contribute
        # weight * sum(aux) to the objective inside the same trace
        self._aux_weight = aux_loss_weight
        P = _P()
        if batch_spec is None:
            batch_spec = P("dp") if "dp" in self.mesh.axis_names else P()
        self.batch_spec = batch_spec

        self._pp_meta = None
        pp_axis = getattr(block, "_pp_axis", None)
        if hasattr(block, "_pp_functionalize") \
                and pp_axis in self.mesh.axis_names:
            if self._use_shard_map:
                raise MXNetError(
                    "ParallelConfig(tp>1) cannot drive a pipelined block: "
                    "the shard_map tp step and the pp stage schedule do "
                    "not compose yet")
            # pipeline-parallel path (parallel/pipeline.PipelinedBlock):
            # body layers arrive stacked as `pp::<rel>` leaves sharded
            # P(pp) — one stage's params per device along the pp axis
            self._apply_fn, params, self._pp_meta = \
                block._pp_functionalize(self.mesh)
            params_od = block.collect_params()
            # trainer-local copy: the injected pp:: rule must not leak
            # into (or stack up in) the caller's ShardingRules object
            rules_copy = ShardingRules(default_axis=self.rules.default_axis)
            rules_copy.rules = [(
                re.compile(r"^pp::"),
                lambda shape, _a=pp_axis: _P()(
                    _a, *([None] * (len(shape) - 1))))] + list(self.rules.rules)
            self.rules = rules_copy
            # frozen leaves (intentionally grad_req='null' weights,
            # Constants) flow through the step as inputs but are returned
            # un-updated — see the skip in the compiled step
            self._frozen_names = set(
                self._pp_meta.pop("__frozen__", set()))
            self._train_names = list(params)
            self._state_names = []
            self.optimizer.param_dict = {
                i: params_od[n]
                for i, n in enumerate(self._train_names)
                if n in params_od}
        else:
            self._frozen_names = set()
            if self._abstract:
                # compile-only mode (VERDICT r3 item 5): params are
                # ShapeDtypeStructs, never materialized — aot_lower() is
                # the only runnable surface
                self._apply_fn, params = functionalize_abstract(block)
            else:
                self._apply_fn, params = functionalize(block,
                                                       train_mode=True)
            params_od = block.collect_params()
            self._train_names = [n for n in params
                                 if params_od[n].grad_req != "null"]
            self._state_names = [n for n in params
                                 if params_od[n].grad_req == "null"]
            # per-param lr_mult/wd_mult flow through the optimizer's
            # param_dict, same wiring as the eager gluon.Trainer
            # (trainer.py) — frozen layers (lr_mult=0) stay frozen under
            # the SPMD step too
            self.optimizer.param_dict = {
                i: params_od[n] for i, n in enumerate(self._train_names)}
        # ZeRO collective bucketing (kvstore.bucketing): opt-in via
        # MXNET_KVSTORE_BUCKET_MB or the zero_bucket_mb argument. Default
        # -rule fsdp params are stored canonically as flat P(axis)-sharded
        # fusion buffers, so the step gathers ONE buffer per bucket
        # instead of one per param (the 1829-gather lowering collapses to
        # a bucket-proportional count). Pack/unpack only ever happens on
        # the host (init, sync_to_block) or on the replicated post-gather
        # array — never on a sharded array in-trace, which would insert
        # resharding collectives.
        self._zb_specs = None
        self._zb_axis = None
        self._zb_names = set()
        self._zb_by_key = {}
        if zero_bucket_mb is None:
            from .. import config as _cfg

            zero_bucket_mb = _cfg.get("MXNET_KVSTORE_BUCKET_MB")
        if zero_bucket_mb and float(zero_bucket_mb) > 0 \
                and self._pp_meta is None:
            self._setup_zero_buckets(params, params_od,
                                     float(zero_bucket_mb))
        if self._zb_specs:
            # optimizer units follow _train_keys: a bucket takes its
            # (uniform, plan-segregated) lr/wd mults from any member
            self._train_keys = ([s.key for s in self._zb_specs]
                                + [n for n in self._train_names
                                   if n not in self._zb_names])
            self.optimizer.param_dict = {
                i: params_od[self._zb_by_key[k].names[0]
                             if k in self._zb_by_key else k]
                for i, k in enumerate(self._train_keys)
                if (self._zb_by_key[k].names[0]
                    if k in self._zb_by_key else k) in params_od}
        else:
            self._train_keys = list(self._train_names)
        # placement: params + optimizer state onto the mesh by rule
        if self._abstract:
            self.params = {
                n: jax.ShapeDtypeStruct(
                    s.shape, s.dtype,
                    sharding=NamedSharding(
                        self.mesh,
                        self.rules.spec_for(n, s.shape, self.mesh)))
                for n, s in params.items() if n not in self._zb_names}
            if self._zb_specs:
                self.params.update(self._zb_abstract_buckets())
            self._opt_states = self._init_opt_states_abstract()
        else:
            self.params = self.rules.shard(
                {n: a for n, a in params.items()
                 if n not in self._zb_names}, self.mesh)
            if self._zb_specs:
                self.params.update(self._zb_pack_buckets(params))
            self._opt_states = self._init_opt_states()
        self._step_jit = None
        self._compiled = {}   # batch-signature -> AOT executable
        self._last_compiled = None
        self._step_flops = None
        self._step_count = 0
        self._key = jax.random.PRNGKey(0)

    # -- ZeRO bucketing ---------------------------------------------------
    def _setup_zero_buckets(self, params, params_od, bucket_mb):
        """Plan flat fusion buffers over the default-rule (fsdp) float
        params. Explicitly-ruled params (tp/pp layouts) keep their
        per-param sharding — replicating them through a bucket gather
        would undo the layout the rule asked for."""
        import jax.numpy as jnp

        from ..kvstore import bucketing as _bkt

        axis = self.rules.default_axis
        if not axis or axis not in self.mesh.axis_names:
            return
        items = []
        for n in self._train_names:
            if n in self._frozen_names:
                continue
            if any(pat.search(n) for pat, _ in self.rules.rules):
                continue
            s = params[n]
            if not jnp.issubdtype(jnp.dtype(s.dtype), jnp.floating):
                continue
            p = params_od.get(n)
            group = (float(getattr(p, "lr_mult", 1.0)),
                     float(getattr(p, "wd_mult", 1.0)))
            items.append((n, tuple(s.shape), jnp.dtype(s.dtype), group))
        if not items:
            return
        opt = self.optimizer
        if not (getattr(opt, "fused_safe", True)
                and getattr(opt, "elementwise", True)):
            raise MXNetError(
                f"ZeRO bucketing needs an elementwise optimizer: "
                f"{type(opt).__name__} keeps per-tensor norms or python "
                "-side state, so updating a flat fusion buffer would "
                "change its math — unset MXNET_KVSTORE_BUCKET_MB (or "
                "zero_bucket_mb) for this optimizer")
        n_shards = int(self.mesh.shape[axis])
        self._zb_specs = _bkt.GradBucketer(
            bucket_mb, pad_multiple=n_shards).plan(items)
        self._zb_axis = axis
        self._zb_names = {n for s in self._zb_specs for n in s.names}
        self._zb_by_key = {s.key: s for s in self._zb_specs}

    def _spec_of(self, key, shape):
        """PartitionSpec for a ``self.params`` key: flat buckets shard
        P(axis) (their padded totals divide evenly by construction);
        everything else goes through the rule table."""
        if key in self._zb_by_key:
            return _P()(self._zb_axis)
        return self.rules.spec_for(key, shape, self.mesh)

    def _zb_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, _P()(self._zb_axis))

    def _zb_abstract_buckets(self):
        import jax

        sh = self._zb_sharding()
        return {s.key: jax.ShapeDtypeStruct((s.total,), s.dtype,
                                            sharding=sh)
                for s in self._zb_specs}

    def _zb_pack_buckets(self, params):
        """Host-side pack of the block's materialized params into the
        sharded flat buffers (init-time only)."""
        import jax
        import numpy as onp

        sh = self._zb_sharding()
        out = {}
        for spec in self._zb_specs:
            flat = onp.zeros((spec.total,), dtype=spec.dtype)
            for n, off, size, shape in spec.items():
                flat[off:off + size] = onp.asarray(
                    jax.device_get(params[n])).reshape(-1)
            out[spec.key] = jax.device_put(flat, sh)
        return out

    # -- optimizer state --------------------------------------------------
    def _init_opt_states(self):
        import jax
        from jax.sharding import NamedSharding

        from ..gluon.trainer import _flatten_state
        from ..ndarray.ndarray import NDArray

        states = {}
        for i, n in enumerate(self._train_keys):
            if n in self._frozen_names:
                # frozen leaves are never updated: no momentum/variance
                # buffers (they'd waste 2x the frozen size in HBM)
                states[n] = ()
                continue
            w = NDArray(self.params[n])
            st = self.optimizer.create_state_multi_precision(i, w)
            flat = [s._data for s in _flatten_state(st)]
            spec = self._spec_of(n, self.params[n].shape)
            placed = []
            for s in flat:
                sh = (NamedSharding(self.mesh, spec) if s.shape == w.shape
                      else NamedSharding(self.mesh, _P()))
                placed.append(jax.device_put(s, sh))
            states[n] = tuple(placed)
        return states

    def _init_opt_states_abstract(self):
        """Optimizer-state ShapeDtypeStructs via ``jax.eval_shape`` over
        ``create_state_multi_precision`` — same shapes/dtypes the real
        path materializes, zero bytes allocated."""
        import jax
        from jax.sharding import NamedSharding

        from ..gluon.trainer import _flatten_state
        from ..ndarray.ndarray import NDArray

        P = _P()
        states = {}
        for i, n in enumerate(self._train_keys):
            w_struct = self.params[n]

            def mk(i=i, w_struct=w_struct):
                import jax.numpy as jnp

                w = NDArray(jnp.zeros(w_struct.shape, w_struct.dtype))
                st = self.optimizer.create_state_multi_precision(i, w)
                return tuple(s._data for s in _flatten_state(st))

            flat = jax.eval_shape(mk)
            spec = self._spec_of(n, w_struct.shape)
            states[n] = tuple(
                jax.ShapeDtypeStruct(
                    s.shape, s.dtype,
                    sharding=NamedSharding(
                        self.mesh,
                        spec if tuple(s.shape) == tuple(w_struct.shape)
                        else P()))
                for s in flat)
        return states

    def aot_lowered(self, batch_struct, labels_struct):
        """Lowered-but-NOT-compiled step (StableHLO) from
        ShapeDtypeStructs — pre-optimization inspection (tests check
        e.g. that ``layer_barrier`` threaded its optimization_barriers
        into the trace; backends may fold them after scheduling, so the
        compiled text cannot pin them)."""
        import jax
        import jax.numpy as jnp

        if self._step_jit is None:
            self._build_step()
        n_train = len(self._train_keys)
        lrs = tuple(self.optimizer._get_lr(i) for i in range(n_train))
        wds = tuple(self.optimizer._get_wd(i) for i in range(n_train))
        key_struct = jax.ShapeDtypeStruct((2,), jnp.uint32)
        train = {n: self.params[n] for n in self._train_keys}
        state = {n: self.params[n] for n in self._state_names}
        args = (train, state, self._opt_states, batch_struct, labels_struct,
                key_struct, lrs, wds, 1)
        return self._step_jit.lower(*args)

    def aot_lower(self, batch_struct, labels_struct):
        """AOT-compile ONE SPMD training step from ShapeDtypeStructs —
        the compile/memory-plan-only proof path for configs too big to
        materialize on the host (``abstract=True`` trainers; Llama-3-8B
        on a virtual v5e-8 mesh). Returns the jax ``Compiled`` object:
        ``.memory_analysis()`` has the per-device argument/temp bytes the
        fit assertion reads, ``.as_text()`` the HLO.
        """
        compiled = self.aot_lowered(batch_struct, labels_struct).compile()
        self._last_compiled = compiled
        self._step_flops = _cost_analysis_of(compiled).get("flops")
        return compiled

    # -- the compiled step ------------------------------------------------
    def _build_step(self):
        if self._use_shard_map:
            return self._build_step_shard_map()
        return self._build_step_pjit()

    def _build_step_shard_map(self):
        """Explicit-collective step for composed dp×tp meshes: the whole
        step runs under ``shard_map``, so every array is its per-device
        block and every cross-device exchange is written out instead of
        left to the SPMD partitioner.

        The math mirrors the pjit path exactly:

        * the local loss is ``pmean``-ed over ALL mesh axes — over dp
          that is the global batch mean; over tp it is value-identical
          (every tp peer sees the same gathered params and the same
          batch block) but it is what makes the tiled ``all_gather``
          transpose (a psum_scatter over tp) come out unscaled;
        * each param's grad is then ``psum``-ed over exactly the axes
          its PartitionSpec does NOT mention — dp for tp layouts, tp for
          dp-sharded ZeRO buckets, both for replicated params — and
          divided by ``mesh.size`` (every device seeds cotangent 1 on
          its replicated loss), after which the local grad IS the exact
          global-batch-mean grad of that slice;
        * optimizer updates run on the local slices (elementwise
          optimizers only), so sharded state never materializes whole.
        """
        import jax
        import jax.numpy as jnp
        from jax.experimental.shard_map import shard_map
        from jax.sharding import NamedSharding

        opt = self.optimizer
        if not (getattr(opt, "fused_safe", True)
                and getattr(opt, "elementwise", True)):
            raise MXNetError(
                "ParallelConfig(tp>1) runs optimizer updates on local "
                f"shards, which needs an elementwise optimizer: "
                f"{type(opt).__name__} keeps per-tensor norms or python"
                "-side state, so updating slices would change its math")
        mesh = self.mesh
        P = _P()
        all_axes = tuple(mesh.axis_names)
        mesh_n = int(mesh.size)
        apply_fn = self._apply_fn
        loss_fn = self.loss_fn
        train_names = self._train_keys
        state_names = self._state_names
        has_state = bool(state_names)
        zb_specs = self._zb_specs
        zb_keys = frozenset(self._zb_by_key)
        spec_of = {n: self._spec_of(n, self.params[n].shape)
                   for n in self.params}
        amp_dtype = self._dtype

        def cast_amp(x):
            if amp_dtype is not None and jnp.issubdtype(x.dtype,
                                                        jnp.floating):
                return x.astype(amp_dtype)
            return x

        def axes_of(spec):
            out = []
            for entry in spec:
                if entry is None:
                    continue
                out.extend(entry if isinstance(entry, (tuple, list))
                           else (entry,))
            return tuple(out)

        def gather_full(x, spec):
            # local block -> full tensor; tiled all_gather per sharded
            # dim is differentiable (its transpose is psum_scatter)
            for dim, entry in enumerate(spec):
                if entry is None:
                    continue
                for ax in (entry if isinstance(entry, (tuple, list))
                           else (entry,)):
                    x = jax.lax.all_gather(x, ax, axis=dim, tiled=True)
            return x

        def scatter_local(x, spec):
            for dim, entry in enumerate(spec):
                if entry is None:
                    continue
                for ax in (entry if isinstance(entry, (tuple, list))
                           else (entry,)):
                    size = x.shape[dim] // int(mesh.shape[ax])
                    x = jax.lax.dynamic_slice_in_dim(
                        x, jax.lax.axis_index(ax) * size, size, axis=dim)
            return x

        def local_loss(train_params, state_params, batch, labels, key):
            full = {}
            if zb_specs:
                # ZeRO per dp-group: ONE all_gather per flat bucket
                # rebuilds the replicated buffer; per-param views are
                # static slices of it — the pjit path's bucket
                # discipline with the collective written out
                for spec in zb_specs:
                    flat = gather_full(train_params[spec.key],
                                       spec_of[spec.key])
                    for pn, off, size, shape in spec.items():
                        full[pn] = jax.lax.slice_in_dim(
                            flat, off, off + size).reshape(shape)
            for pn, a in train_params.items():
                if pn not in zb_keys:
                    full[pn] = gather_full(a, spec_of[pn])
            params = dict(full)
            for sn, a in state_params.items():
                params[sn] = gather_full(a, spec_of[sn])
            if amp_dtype is not None:
                params = {n: cast_amp(a) for n, a in params.items()}
                batch = jax.tree_util.tree_map(cast_amp, batch)
            batch = batch if isinstance(batch, tuple) else (batch,)
            r = apply_fn(params, *batch, rng_key=key)
            if has_state:
                out, new_state = r
            else:
                out, new_state = r, {}
            from ..ndarray.ndarray import NDArray

            out_nd = jax.tree_util.tree_map(
                lambda x: x if isinstance(x, NDArray) else NDArray(x), out,
                is_leaf=lambda x: isinstance(x, NDArray))
            lbl_nd = jax.tree_util.tree_map(NDArray, labels)
            loss = loss_fn(out_nd, lbl_nd)
            ldata = loss._data if isinstance(loss, NDArray) else loss
            aux = _collect_aux_losses(self.block)
            if aux is not None:
                ldata = ldata + self._aux_weight * aux
            if amp_dtype is not None:
                new_state = {n: v.astype(state_params[n].dtype)
                             for n, v in new_state.items()}
            return jax.lax.pmean(
                jnp.mean(ldata.astype(jnp.float32)), all_axes), new_state

        def step(train_params, state_params, opt_states, batch, labels,
                 key, lrs, wds, t):
            (loss, new_state), grads = jax.value_and_grad(
                local_loss, has_aux=True)(train_params, state_params,
                                          batch, labels, key)
            new_train = {}
            new_opt = {}
            frozen = self._frozen_names
            for i, n in enumerate(train_names):
                if n in frozen:
                    new_train[n] = train_params[n]
                    new_opt[n] = opt_states[n]
                    continue
                g = grads[n].astype(train_params[n].dtype)
                missing = tuple(a for a in all_axes
                                if a not in axes_of(spec_of[n]))
                if missing:
                    g = jax.lax.psum(g, missing)
                # every device seeds cotangent 1 on its own (replicated)
                # pmean'd loss, so after the psum the grad is mesh.size×
                # the global-batch-mean grad — one normalization for all
                # layouts (sharded axes already collapse in the backward,
                # missing axes in the psum above)
                g = g / float(mesh_n)
                g = opt._prep_grad(g)
                p_new, s_new = opt._update_raw(
                    train_params[n], g, opt_states[n], lrs[i], wds[i], t)
                new_train[n] = p_new
                new_opt[n] = tuple(s_new) \
                    if isinstance(s_new, (list, tuple)) else (s_new,)
            # mutable block state (BN running stats): average the
            # per-shard updates, keep only the local block of the result
            new_state = {n: scatter_local(jax.lax.pmean(v, all_axes),
                                          spec_of[n])
                         for n, v in new_state.items()}
            return new_train, new_state, new_opt, loss

        train_in = {n: spec_of[n] for n in train_names}
        state_in = {n: spec_of[n] for n in state_names}
        opt_in = {n: tuple(s.sharding.spec for s in self._opt_states[n])
                  for n in train_names}
        sm = shard_map(
            step, mesh=mesh,
            in_specs=(train_in, state_in, opt_in, self.batch_spec,
                      self.batch_spec, P(), P(), P(), P()),
            out_specs=(train_in, state_in, opt_in, P()),
            check_rep=False)
        train_shard = {n: NamedSharding(mesh, spec_of[n])
                       for n in train_names}
        state_shard = {n: NamedSharding(mesh, spec_of[n])
                       for n in state_names}
        opt_shard = {
            n: tuple(NamedSharding(mesh, s.sharding.spec)
                     for s in self._opt_states[n])
            for n in train_names}
        batch_shard = NamedSharding(mesh, self.batch_spec)
        repl = NamedSharding(mesh, P())
        self._step_jit = jax.jit(
            sm,
            in_shardings=(train_shard, state_shard, opt_shard, batch_shard,
                          batch_shard, repl, None, None, None),
            out_shardings=(train_shard, state_shard, opt_shard, repl),
            donate_argnums=(0, 1, 2),
        )
        stacked_spec = P(None, *self.batch_spec)
        stacked_shard = NamedSharding(mesh, stacked_spec)

        def step_n_fn(train_params, state_params, opt_states, d_all, l_all,
                      key, lrs, wds, t0):
            def body(carry, xs):
                tr, st, op, t, k = carry
                k, sub = jax.random.split(k)
                d, l = xs
                ntr, nst, nop, loss = sm(tr, st, op, d, l, sub, lrs, wds,
                                         t)
                return (ntr, nst, nop, t + 1, k), loss

            (tr, st, op, _, _), losses = jax.lax.scan(
                body, (train_params, state_params, opt_states, t0, key),
                (d_all, l_all))
            return tr, st, op, losses

        self._stepn_fn = step_n_fn
        self._stepn_jit = jax.jit(
            step_n_fn,
            in_shardings=(train_shard, state_shard, opt_shard,
                          stacked_shard, stacked_shard, repl, None, None,
                          None),
            out_shardings=(train_shard, state_shard, opt_shard, repl),
            donate_argnums=(0, 1, 2),
        )

    def _build_step_pjit(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        apply_fn = self._apply_fn
        loss_fn = self.loss_fn
        opt = self.optimizer
        train_names = self._train_keys
        state_names = self._state_names
        has_state = bool(state_names)
        zb_specs = self._zb_specs
        zb_keys = frozenset(self._zb_by_key)
        repl_shard = NamedSharding(self.mesh, _P()())

        amp_dtype = self._dtype
        # inner-AMP protocol: the block casts params at use inside its own
        # remat boundary (LlamaModel.supports_inner_amp) — the trainer
        # must NOT pre-cast the tree, or a full extra low-precision param
        # copy stays live across the step
        inner_amp = (amp_dtype is not None
                     and getattr(self.block, "supports_inner_amp", False)
                     and getattr(self.block, "_remat", False))
        inner_protocol = getattr(self.block, "supports_inner_amp", False)

        def cast_amp(x):
            if amp_dtype is not None and jnp.issubdtype(x.dtype, jnp.floating):
                return x.astype(amp_dtype)
            return x

        def loss_of(train_params, state_params, batch, labels, key):
            if zb_specs:
                # flat-bucket ZeRO: ONE all-gather per bucket (the
                # constraint to replicated), then static slices of the
                # replicated buffer rebuild the per-param views. Buckets
                # are gathered front-first (plan order); XLA's latency
                # -hiding scheduler prefetches bucket k+1 behind the
                # layers consuming bucket k. The per-param gathers the
                # partitioner would otherwise insert at every use site
                # collapse to len(zb_specs) collectives.
                full = {}
                for spec in zb_specs:
                    flat = jax.lax.with_sharding_constraint(
                        train_params[spec.key], repl_shard)
                    for pn, off, size, shape in spec.items():
                        full[pn] = jax.lax.slice_in_dim(
                            flat, off, off + size).reshape(shape)
                for pn, a in train_params.items():
                    if pn not in zb_keys:
                        full[pn] = a
                train_params = full
            params = dict(train_params)
            params.update(state_params)
            if amp_dtype is not None and not inner_amp:
                # cast-for-compute: autodiff through the cast hands back
                # fp32 grads against the fp32 master params
                params = {n: cast_amp(a) for n, a in params.items()}
                batch = jax.tree_util.tree_map(cast_amp, batch)
            elif inner_amp:
                batch = jax.tree_util.tree_map(cast_amp, batch)
            batch = batch if isinstance(batch, tuple) else (batch,)
            if inner_protocol:
                # set for THIS trace only (block.forward reads it at
                # trace time) and restore after: a persistent write
                # would leak this trainer's dtype into a sibling
                # trainer's later re-trace on the same block
                prev_amp = getattr(self.block, "_amp_dtype", None)
                self.block._amp_dtype = amp_dtype if inner_amp else None
            try:
                r = apply_fn(params, *batch, rng_key=key)
            finally:
                if inner_protocol:
                    self.block._amp_dtype = prev_amp
            if has_state:
                out, new_state = r
            else:
                out, new_state = r, {}
            from ..ndarray.ndarray import NDArray

            # outputs may be a pytree (e.g. BERT's (mlm_scores, nsp_scores));
            # hand the loss_fn NDArray leaves with the structure intact
            out_nd = jax.tree_util.tree_map(
                lambda x: x if isinstance(x, NDArray) else NDArray(x), out,
                is_leaf=lambda x: isinstance(x, NDArray))
            lbl_nd = jax.tree_util.tree_map(NDArray, labels)
            loss = loss_fn(out_nd, lbl_nd)
            ldata = loss._data if isinstance(loss, NDArray) else loss
            aux = _collect_aux_losses(self.block)
            if aux is not None:
                ldata = ldata + self._aux_weight * aux
            if amp_dtype is not None:
                # mutable state (BN running stats) flows back at the master
                # dtype so the AOT-compiled step signature stays stable
                new_state = {
                    n: v.astype(state_params[n].dtype)
                    for n, v in new_state.items()}
            return jnp.mean(ldata.astype(jnp.float32)), new_state

        mesh = self.mesh
        p_shard = {
            n: NamedSharding(mesh, self._spec_of(n, self.params[n].shape))
            for n in self.params
        }
        train_shard = {n: p_shard[n] for n in train_names}
        state_shard = {n: p_shard[n] for n in state_names}

        def step(train_params, state_params, opt_states, batch, labels, key,
                 lrs, wds, t):
            (loss, new_state), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_params, state_params, batch,
                                       labels, key)
            new_train = {}
            new_opt = {}
            frozen = self._frozen_names
            for i, n in enumerate(train_names):
                if n in frozen:
                    # frozen leaf: participates in forward/backward but
                    # the optimizer never moves it
                    new_train[n] = train_params[n]
                    new_opt[n] = opt_states[n]
                    continue
                g = grads[n].astype(train_params[n].dtype)
                # ZeRO discipline: pin the grad to the PARAM's sharding
                # before the update. For fsdp-sharded params this makes the
                # SPMD partitioner emit a reduce-scatter (each device gets
                # only its shard's summed grad) and run the optimizer on
                # 1/N of the state — gather-for-compute (XLA all-gathers
                # the weight at its use sites) / scatter-for-update.
                g = jax.lax.with_sharding_constraint(g, train_shard[n])
                g = opt._prep_grad(g)
                p_new, s_new = opt._update_raw(train_params[n], g,
                                               opt_states[n], lrs[i], wds[i],
                                               t)
                new_train[n] = p_new
                new_opt[n] = tuple(s_new) if isinstance(s_new, (list, tuple)) \
                    else (s_new,)
            return new_train, new_state, new_opt, loss
        opt_shard = {
            n: tuple(
                NamedSharding(mesh, s.sharding.spec)
                for s in self._opt_states[n])
            for n in train_names
        }
        # a single NamedSharding acts as a pytree prefix: it applies to every
        # leaf of the batch/labels trees (tuple inputs shard dim 0 over dp)
        batch_shard = NamedSharding(mesh, self.batch_spec)
        repl = NamedSharding(mesh, _P()())
        self._step_jit = jax.jit(
            step,
            in_shardings=(train_shard, state_shard, opt_shard, batch_shard,
                          batch_shard, repl, None, None, None),
            out_shardings=(train_shard, state_shard, opt_shard, repl),
            donate_argnums=(0, 1, 2),
        )
        # fused multi-step (step_n): lax.scan over stacked microbatches —
        # the reference's bulk-exec segments (engine.h:311-317) done the
        # trace-once way: one dispatch runs N whole training steps
        stacked_spec = _P()(None, *self.batch_spec)
        stacked_shard = NamedSharding(mesh, stacked_spec)

        def step_n_fn(train_params, state_params, opt_states, d_all, l_all,
                      key, lrs, wds, t0):
            def body(carry, xs):
                tr, st, op, t, k = carry
                k, sub = jax.random.split(k)
                d, l = xs
                ntr, nst, nop, loss = step(tr, st, op, d, l, sub, lrs, wds,
                                           t)
                return (ntr, nst, nop, t + 1, k), loss

            (tr, st, op, _, _), losses = jax.lax.scan(
                body, (train_params, state_params, opt_states, t0, key),
                (d_all, l_all))
            return tr, st, op, losses

        self._stepn_fn = step_n_fn
        self._stepn_jit = jax.jit(
            step_n_fn,
            in_shardings=(train_shard, state_shard, opt_shard,
                          stacked_shard, stacked_shard, repl, None, None,
                          None),
            out_shardings=(train_shard, state_shard, opt_shard, repl),
            donate_argnums=(0, 1, 2),
        )

    @property
    def step_flops(self):
        """XLA cost-analysis FLOPs of one compiled step (None before the
        first step). The MFU numerator bench.py divides by chip peak."""
        return self._step_flops

    @property
    def step_hlo(self):
        """Compiled HLO text of the step (None before the first step);
        tests assert collective choice (all-gather/reduce-scatter) on it."""
        return self._last_compiled.as_text() \
            if self._last_compiled is not None else None

    @property
    def step_cost_analysis(self):
        """XLA cost analysis dict of the last executed step ({} before the
        first step): 'flops', 'bytes accessed', ... — the roofline inputs
        bench.py reads (flops/bytes = arithmetic intensity)."""
        if self._last_compiled is None:
            return {}
        return _cost_analysis_of(self._last_compiled)

    def device_memory_bytes(self):
        """Per-device bytes held by params + optimizer state (shard 0):
        the ZeRO memory claim tests assert this drops ~N× under fsdp."""
        total = 0
        for arr in list(self.params.values()) + [
                s for st in self._opt_states.values() for s in st]:
            total += arr.addressable_shards[0].data.nbytes
        return total

    # -- shared host-side step machinery ----------------------------------
    def _unwrap_batch(self, data, labels, spec=None):
        import jax
        from jax.sharding import NamedSharding

        from ..ndarray.ndarray import NDArray

        sh = NamedSharding(self.mesh,
                           spec if spec is not None else self.batch_spec)

        def raw(x):
            d = x._data if isinstance(x, NDArray) else x
            if isinstance(d, jax.Array) and getattr(d, "_committed", False):
                # eager NDArrays sit committed on their ctx device; the
                # step's in_shardings contract wants mesh-laid-out (or
                # uncommitted) inputs — re-place instead of erroring
                d = jax.device_put(d, sh)
            return d

        d = tuple(raw(x) for x in data) if isinstance(data, (list, tuple)) \
            else raw(data)
        l = jax.tree_util.tree_map(raw, labels,
                                   is_leaf=lambda x: isinstance(x, NDArray))
        return d, l

    def _advance_optimizer(self, n):
        """Advance step/update counts by n; return (lrs, wds, t_first)."""
        t_first = self._step_count + 1
        self._step_count += n
        n_train = len(self._train_keys)
        for i in range(n_train):
            self.optimizer._index_update_count[i] = self._step_count
        lrs = tuple(self.optimizer._get_lr(i) for i in range(n_train))
        wds = tuple(self.optimizer._get_wd(i) for i in range(n_train))
        return lrs, wds, t_first

    def _run_compiled(self, sig, jit_fn, args):
        """AOT-compile once per signature (a partial final batch gets its
        own executable): the compiled callable skips per-call signature
        matching and exposes XLA's cost analysis — the exact per-step
        FLOPs source for MFU reporting. Returns the executable's outputs;
        updates params/opt state from the first three."""
        if self._abstract:
            raise MXNetError(
                "this ShardedTrainer was built with abstract=True "
                "(compile-only): params were never materialized — use "
                "aot_lower() for the memory proof, or rebuild without "
                "abstract to train")
        hit = self._compiled.get(sig)
        if hit is None:
            compiled = jit_fn.lower(*args).compile()
            flops = _cost_analysis_of(compiled).get("flops")
            self._compiled[sig] = (compiled, flops)
        else:
            compiled, flops = hit
        # refresh per call so the property tracks the LAST executed
        # program (scan bodies are counted once by XLA, so this stays a
        # per-step figure even for step_n windows)
        self._step_flops = flops
        self._last_compiled = compiled
        new_train, new_state, new_opt, out = compiled(*args)
        self.params.update(new_train)
        self.params.update(new_state)
        self._opt_states = new_opt
        return out

    def step(self, data, labels):
        """Run one SPMD training step; returns the scalar loss as an
        NDArray (async — reading/printing it syncs, dispatch does not).

        ``data`` may be a single array or a tuple of arrays (multi-input
        models, e.g. (tokens, segments) for BERT)."""
        import jax

        from ..ndarray.ndarray import NDArray
        from ..resilience import faults as _faults

        # chip-loss injection surface for composed-mesh elasticity: a
        # `chip_loss` rule here (optionally device-addressed) raises
        # BEFORE the compiled SPMD step dispatches, exactly where a real
        # ICI/chip failure would surface as a poisoned dispatch
        _faults.fault_point("trainer:sharded_step",
                            {"step": self._step_count})
        if self._step_jit is None:
            self._build_step()
        d, l = self._unwrap_batch(data, labels)
        lrs, wds, t = self._advance_optimizer(1)
        self._key, sub = jax.random.split(self._key)
        train = {n: self.params[n] for n in self._train_keys}
        state = {n: self.params[n] for n in self._state_names}
        args = (train, state, self._opt_states, d, l, sub, lrs, wds, t)
        sig = tuple(
            (x.shape, str(x.dtype))
            for x in jax.tree_util.tree_leaves((d, l)))
        loss = self._run_compiled(sig, self._step_jit, args)
        return NDArray(loss)

    def step_n(self, data, labels, num_steps=None):
        """Run MANY SPMD training steps in ONE compiled dispatch.

        ``data``/``labels`` leaves are stacked per-step on a leading axis:
        shape ``(num_steps, B, ...)``. Returns the per-step losses as an
        NDArray of shape (num_steps,). The learning rate and weight decay
        are held constant across the fused window (schedulers advance
        between calls); ``lax.scan`` carries params/optimizer state, so
        host dispatch cost is paid once per window instead of per step.
        """
        import jax

        from ..ndarray.ndarray import NDArray

        if self._step_jit is None:
            self._build_step()
        d, l = self._unwrap_batch(data, labels,
                                  spec=_P()(None, *self.batch_spec))
        avail = jax.tree_util.tree_leaves(d)[0].shape[0]
        n = avail if num_steps is None else int(num_steps)
        if n < 1 or n > avail:
            raise MXNetError(
                f"step_n: num_steps={num_steps} but the stacked leading "
                f"axis holds {avail} step batches")
        if avail != n:
            # scan runs the whole leading axis: slice so bookkeeping
            # (update counts, lr schedule, FLOPs) matches execution
            d = jax.tree_util.tree_map(lambda x: x[:n], d)
            l = jax.tree_util.tree_map(lambda x: x[:n], l)
        lrs, wds, t0 = self._advance_optimizer(n)
        self._key, sub = jax.random.split(self._key)
        train = {k: self.params[k] for k in self._train_keys}
        state = {k: self.params[k] for k in self._state_names}
        args = (train, state, self._opt_states, d, l, sub, lrs, wds, t0)
        sig = ("step_n", n, tuple(
            (x.shape, str(x.dtype))
            for x in jax.tree_util.tree_leaves((d, l))))
        losses = self._run_compiled(sig, self._stepn_jit, args)
        return NDArray(losses)

    def save_checkpoint(self, path):
        """Checkpoint the FULL training state — params, optimizer state,
        step count — for exact resume (the SPMD analog of
        ``Trainer.save_states`` + ``save_parameters``; reference
        ``gluon/trainer.py:482``). Sharded arrays are gathered to host;
        ``load_checkpoint`` re-places them with the live shardings."""
        import pickle

        import jax

        blob = {
            "params": {n: jax.device_get(a)
                       for n, a in self.params.items()},
            "opt_states": {n: tuple(jax.device_get(s) for s in st)
                           for n, st in self._opt_states.items()},
            "step_count": self._step_count,
            # the dropout/RNG stream position: without it a resumed run
            # would replay earlier steps' masks
            "rng_key": jax.device_get(self._key),
        }
        with open(path, "wb") as f:
            pickle.dump(blob, f)

    def load_checkpoint(self, path):
        """Restore a ``save_checkpoint`` blob onto the CURRENT mesh: each
        array is device_put with the trainer's live sharding, so resume
        works across process restarts (and across mesh shapes, as long as
        the rules still divide the shapes)."""
        import pickle

        import jax

        with open(path, "rb") as f:
            blob = pickle.load(f)
        if set(blob["params"]) != set(self.params):
            raise MXNetError(
                "checkpoint params do not match this trainer's params: "
                f"missing {set(self.params) - set(blob['params'])}, "
                f"unexpected {set(blob['params']) - set(self.params)}")
        # optimizer-state structure must line up with THIS trainer's
        # optimizer (same names, same per-param arity/shapes) — a
        # mismatched load (adam ckpt into sgd trainer) must fail here
        # with a clear error, not as a tracing failure steps later
        if set(blob["opt_states"]) != set(self._opt_states):
            raise MXNetError(
                "checkpoint optimizer state does not match this trainer: "
                f"missing {set(self._opt_states) - set(blob['opt_states'])}, "
                f"unexpected {set(blob['opt_states']) - set(self._opt_states)}")
        for n, st in blob["opt_states"].items():
            live = self._opt_states[n]
            if len(st) != len(live) or any(
                    tuple(h.shape) != tuple(s.shape)
                    for h, s in zip(st, live)):
                raise MXNetError(
                    f"checkpoint optimizer state for {n!r} has structure "
                    f"{[tuple(h.shape) for h in st]} but this trainer's "
                    f"optimizer ({type(self.optimizer).__name__}) expects "
                    f"{[tuple(s.shape) for s in live]}")
        for n, host in blob["params"].items():
            self.params[n] = jax.device_put(host, self.params[n].sharding)
        self._opt_states = {
            n: tuple(jax.device_put(h, live_s.sharding)
                     for h, live_s in zip(st, self._opt_states[n]))
            for n, st in blob["opt_states"].items()}
        self._step_count = int(blob["step_count"])
        if "rng_key" in blob:
            self._key = jax.device_put(blob["rng_key"])
        for i in range(len(self._train_keys)):
            self.optimizer._index_update_count[i] = self._step_count

    # -- portable state (elastic rebuild-and-reshard) ---------------------
    def checkpoint_layouts(self):
        """Tensor-split layout of every explicitly tp/pp-sharded param:
        ``{name: {"axis", "dim", "parts"}}`` — what
        ``resilience.checkpoint.save_sharded_checkpoint(layouts=...)``
        records in its manifest so a resume under ANY mesh reassembles
        the full tensor before re-laying it out. dp/fsdp sharding is
        ownership, not layout, and is not recorded."""
        out = {}
        for n in self.params:
            if n in self._zb_by_key:
                continue
            spec = self._spec_of(n, self.params[n].shape)
            for dim, entry in enumerate(spec):
                if entry is None:
                    continue
                for ax in (entry if isinstance(entry, (tuple, list))
                           else (entry,)):
                    if ax in ("dp", "fsdp"):
                        continue
                    if n in out:
                        raise MXNetError(
                            f"checkpoint_layouts: {n!r} is sharded over "
                            "more than one non-dp axis/dim — multi-axis "
                            "tensor layouts cannot be checkpointed yet")
                    out[n] = {"axis": ax, "dim": dim,
                              "parts": int(self.mesh.shape[ax])}
        return out

    def export_state(self):
        """Gather the FULL training state to host, bucket-free: whole
        numpy tensors per param (flat ZeRO buckets unpacked back into
        their member views, padding dropped), optimizer state re-keyed
        per param the same way, plus step count and RNG position. The
        result is mesh-independent: :meth:`import_state` repacks it under
        the destination trainer's own bucket plan and shardings — what
        lets an elastic resume cross dp extents."""
        import jax
        import numpy as onp

        params = {}
        opt_states = {}
        for n, a in self.params.items():
            if n not in self._zb_by_key:
                params[n] = onp.asarray(jax.device_get(a))
        for n in self._train_keys:
            st = tuple(onp.asarray(jax.device_get(s))
                       for s in self._opt_states[n])
            spec = self._zb_by_key.get(n)
            if spec is None:
                opt_states[n] = st
                continue
            flat = onp.asarray(jax.device_get(self.params[n]))
            for pn, off, size, shape in spec.items():
                params[pn] = flat[off:off + size].reshape(shape).copy()
                # per-element state (momentum) slices like the weight;
                # anything else (scalars) replicates per member
                opt_states[pn] = tuple(
                    s[off:off + size].reshape(shape).copy()
                    if s.shape == flat.shape else s.copy() for s in st)
        return {"params": params, "opt_states": opt_states,
                "step_count": self._step_count,
                "rng_key": onp.asarray(jax.device_get(self._key))}

    def _zb_repack(self, spec, values, dtype, what):
        """Zero-padded flat repack of per-member host arrays into one
        bucket buffer. Zero-filling the padding is exact for elementwise
        optimizers: a padding slot's grad is identically zero and decay
        multiplies zero, so its momentum never leaves zero."""
        import numpy as onp

        flat = onp.zeros((spec.total,), dtype=dtype)
        for pn, off, size, shape in spec.items():
            v = values.get(pn)
            if v is None:
                raise MXNetError(
                    f"{what} for bucket member {pn!r} is missing from "
                    "the imported state")
            v = onp.asarray(v)
            if int(v.size) != size:
                raise MXNetError(
                    f"{what} for bucket member {pn!r} has {v.size} "
                    f"elements, expected {size}")
            flat[off:off + size] = v.reshape(-1)
        return flat

    def import_params(self, params):
        """Place a dict of FULL host tensors (numpy or NDArray) into this
        trainer — repacking flat ZeRO buckets and resharding every array
        to the live mesh layout. Accepts ``export_state()['params']`` or
        ``resilience.checkpoint.load_checkpoint``'s reassembled output
        (extra entries are ignored; missing ones raise)."""
        import jax
        import numpy as onp

        def host(v):
            return v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v)

        for n, live in self.params.items():
            spec = self._zb_by_key.get(n)
            if spec is not None:
                flat = self._zb_repack(
                    spec,
                    {pn: host(params[pn]) for pn, _, _, _ in spec.items()
                     if pn in params},
                    live.dtype, "parameter")
                self.params[n] = jax.device_put(flat, live.sharding)
                continue
            if n not in params:
                raise MXNetError(
                    f"import_params: parameter {n!r} missing from the "
                    "imported dict")
            h = host(params[n])
            if tuple(h.shape) != tuple(live.shape):
                raise MXNetError(
                    f"import_params: {n!r} has shape {tuple(h.shape)} "
                    f"but this trainer expects {tuple(live.shape)}")
            self.params[n] = jax.device_put(
                onp.asarray(h, dtype=live.dtype), live.sharding)

    def _import_opt_states(self, opt_states):
        import jax
        import numpy as onp

        def host(v):
            return v.asnumpy() if hasattr(v, "asnumpy") else onp.asarray(v)

        new = {}
        for n in self._train_keys:
            live = self._opt_states[n]
            spec = self._zb_by_key.get(n)
            if spec is None:
                if n not in opt_states:
                    raise MXNetError(
                        f"optimizer state for {n!r} is missing from the "
                        "imported state")
                st = tuple(host(s) for s in opt_states[n])
                if len(st) != len(live):
                    raise MXNetError(
                        f"optimizer state for {n!r} has arity {len(st)} "
                        f"but this trainer expects {len(live)}")
                new[n] = tuple(
                    jax.device_put(onp.asarray(h, dtype=l.dtype),
                                   l.sharding)
                    for h, l in zip(st, live))
                continue
            placed = []
            first = spec.names[0]
            for i, l in enumerate(live):
                if tuple(l.shape) == (spec.total,):
                    members = {}
                    for pn, off, size, shape in spec.items():
                        sts = opt_states.get(pn)
                        if sts is None or len(sts) <= i:
                            raise MXNetError(
                                f"optimizer state for bucket member "
                                f"{pn!r} is missing from the imported "
                                "state")
                        members[pn] = host(sts[i])
                    flat = self._zb_repack(spec, members, l.dtype,
                                           "optimizer state")
                    placed.append(jax.device_put(flat, l.sharding))
                else:
                    sts = opt_states.get(first)
                    if sts is None or len(sts) <= i:
                        raise MXNetError(
                            f"optimizer state for bucket member {first!r} "
                            "is missing from the imported state")
                    placed.append(jax.device_put(
                        onp.asarray(host(sts[i]), dtype=l.dtype),
                        l.sharding))
            new[n] = tuple(placed)
        self._opt_states = new

    def import_state(self, blob):
        """Inverse of :meth:`export_state` onto THIS trainer's mesh and
        bucket plan — params, optimizer state, step count, RNG
        position."""
        self.import_params(blob["params"])
        self._restore_scalars(blob)

    def _restore_scalars(self, blob):
        import jax

        self._import_opt_states(blob["opt_states"])
        self._step_count = int(blob["step_count"])
        if blob.get("rng_key") is not None:
            self._key = jax.device_put(blob["rng_key"])
        for i in range(len(self._train_keys)):
            self.optimizer._index_update_count[i] = self._step_count

    def states_to_bytes(self):
        """Trainer blob for ``resilience.checkpoint`` (the duck-typed
        ``trainer=`` hook): optimizer state + step count + RNG position,
        bucket-free — params travel separately through the checkpoint's
        own (layout-aware) params path."""
        import pickle

        st = self.export_state()
        st.pop("params")
        return pickle.dumps(st)

    def load_states_from_bytes(self, raw):
        """Restore a :meth:`states_to_bytes` blob onto THIS trainer —
        which may sit on a different mesh than the saver; the per-param
        repack is the reshard an elastic resume relies on."""
        import pickle

        self._restore_scalars(pickle.loads(raw))

    def sync_to_block(self):
        """Copy trained weights back into the Block's Parameters (a copy —
        the trainer's own arrays get donated on the next step). Pipeline
        runs unstack the ``pp::`` leaves back into the per-layer params."""
        import jax.numpy as jnp

        params_od = self.block.collect_params()
        if self._zb_specs:
            import jax
            import numpy as onp

            # bucketed params live only inside the flat buffers: gather
            # each to host once and slice the members back out
            for spec in self._zb_specs:
                host = onp.asarray(jax.device_get(self.params[spec.key]))
                for n, off, size, shape in spec.items():
                    params_od[n].data()._set_data_internal(
                        jnp.asarray(host[off:off + size].reshape(shape)))
        for n, arr in self.params.items():
            if n.startswith("__"):
                continue
            if self._pp_meta is not None and n.startswith("pp::"):
                import jax

                # device_get: the stacked leaf is sharded over pp — the
                # unstacked per-layer weights must land whole on the
                # default device for eager use
                flat = jnp.asarray(jax.device_get(arr)).reshape(
                    (-1,) + arr.shape[2:])  # (S, per_stage, ...) -> (L, ...)
                for li, pname in enumerate(self._pp_meta[n]):
                    params_od[pname].data()._set_data_internal(flat[li])
            else:
                params_od[n].data()._set_data_internal(
                    jnp.array(arr, copy=True))
