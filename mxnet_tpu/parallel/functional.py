"""Functionalization + SPMD sharded training step.

The reference's distributed step (SURVEY.md §3.4) is imperative: per-param
``kvstore.pushpull`` after backward, optimizer on worker or server. The
TPU-native step is one compiled SPMD program: params/optimizer state laid out
over a ``jax.sharding.Mesh`` by named rules, batch sharded over ``dp``(+``sp``),
gradients reduced by XLA-inserted collectives over ICI, update fused into the
same executable. This module provides:

* :func:`functionalize` — pure ``fn(params, *args)`` view of any Gluon
  ``Block`` (the deferred-compute trace collapsed onto jax tracing).
* sharding rules — regex → ``PartitionSpec`` tables with an fsdp-style
  default, the declarative replacement for ps-lite key sharding
  (``EncodeDefaultKey``, ``src/kvstore/kvstore_dist.h:621``).
* :class:`ShardedTrainer` — the ``gluon.Trainer`` analog whose ``step`` is a
  single pjit'd (loss, grads, allreduce, update) program.
"""
from __future__ import annotations

import re
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError


def _jax():
    import jax

    return jax


def _P():
    from jax.sharding import PartitionSpec

    return PartitionSpec


# ---------------------------------------------------------------------------
# functionalize
# ---------------------------------------------------------------------------


def functionalize(block, train_mode=False):
    """Return ``(apply_fn, params)`` for a Gluon block.

    ``apply_fn(params_dict, *args)`` is pure and jittable: it replays
    ``block.forward`` with the dict's arrays bound to the block's parameters
    (the CachedOp trick, ``mxnet_tpu/cachedop.py``). Outputs are raw jax
    arrays. Parameter shapes must already be materialized (run one eager
    forward first for deferred-shape layers).

    When ``train_mode`` and the block holds mutable state (BatchNorm running
    stats — ``grad_req='null'`` parameters), ``apply_fn`` returns
    ``(outputs, new_state_dict)`` so callers can carry state functionally.
    """
    from .. import autograd
    from .. import random as _rng
    from ..cachedop import _ParamBinding
    from ..ndarray.ndarray import NDArray

    params_od = block.collect_params()
    names = list(params_od)
    arrays = [params_od[n].data() for n in names]
    state_names = [n for n in names if params_od[n].grad_req == "null"]

    def apply_fn(param_datas, *arg_datas, rng_key=None):
        import jax

        tracers = [param_datas[n] for n in names]
        wrapped_args = [NDArray(d) for d in arg_datas]
        with _ParamBinding(arrays, tracers):
            if rng_key is None:
                rng_key = _rng.next_key()
            _rng.push_trace_rng(rng_key)
            prev_rec = autograd.set_recording(False)
            prev_train = autograd.set_training(train_mode)
            try:
                outs = block.forward(*wrapped_args)
            finally:
                autograd.set_training(prev_train)
                autograd.set_recording(prev_rec)
                _rng.pop_trace_rng()
            new_state = {n: a._data for n, a in zip(names, arrays)
                         if n in state_names}
        flat, tree = jax.tree_util.tree_flatten(
            outs, is_leaf=lambda x: isinstance(x, NDArray))
        datas = [o._data if isinstance(o, NDArray) else o for o in flat]
        out = jax.tree_util.tree_unflatten(tree, datas)
        if train_mode and state_names:
            return out, new_state
        return out

    params = {n: a._data for n, a in zip(names, arrays)}
    return apply_fn, params


# ---------------------------------------------------------------------------
# sharding rules
# ---------------------------------------------------------------------------


class ShardingRules:
    """Ordered ``(regex, PartitionSpec)`` table mapping param names to specs.

    First match wins; no match → fsdp default (if an ``fsdp`` axis exists:
    shard the largest divisible dim) else fully replicated.
    """

    def __init__(self, rules: Sequence[Tuple[str, object]] = (),
                 default_axis: Optional[str] = "fsdp"):
        self.rules = [(re.compile(pat), spec) for pat, spec in rules]
        self.default_axis = default_axis

    def spec_for(self, name, shape, mesh):
        P = _P()
        for pat, spec in self.rules:
            if pat.search(name):
                return spec
        if self.default_axis and self.default_axis in mesh.axis_names:
            n = mesh.shape[self.default_axis]
            # largest dim divisible by the fsdp axis size, else replicate
            order = sorted(range(len(shape)), key=lambda i: -shape[i])
            for i in order:
                if shape[i] % n == 0 and shape[i] >= n:
                    parts = [None] * len(shape)
                    parts[i] = self.default_axis
                    return P(*parts)
        return P()

    def shard(self, params: Dict[str, object], mesh):
        """Place a param dict onto the mesh per the rules.

        Copies rather than aliasing: device_put can reuse the source buffer
        for the matching shard, and ShardedTrainer donates these arrays —
        donation must never free a buffer the caller's Block still owns.
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        out = {}
        for name, arr in params.items():
            spec = self.spec_for(name, arr.shape, mesh)
            out[name] = jax.device_put(jnp.array(arr, copy=True),
                                       NamedSharding(mesh, spec))
        return out


# ---------------------------------------------------------------------------
# sharded training step
# ---------------------------------------------------------------------------


class ShardedTrainer:
    """SPMD trainer: the whole step is one compiled XLA program.

    Replaces the reference's step (forward → backward → per-param
    ``kvstore.pushpull`` → per-param optimizer kernels) with a single pjit:
    data parallelism comes from sharding the batch (``batch_spec``), tensor
    parallelism from the param rules, and gradient reduction from XLA's
    automatic collective insertion — serving the role the `Comm`/ps-lite/NCCL
    stack plays in `src/kvstore/` but riding ICI.

    Usage::

        trainer = ShardedTrainer(net, loss_fn, 'sgd',
                                 {'learning_rate': 0.1}, mesh=mesh,
                                 rules=ShardingRules([(r'dense\\d+.weight',
                                                       P('tp', None))]))
        loss = trainer.step(x, y)          # one fused SPMD step
        trainer.sync_to_block()            # write weights back to the Block
    """

    def __init__(self, block, loss_fn, optimizer, optimizer_params=None,
                 mesh=None, rules: Optional[ShardingRules] = None,
                 batch_spec=None):
        import jax
        from jax.sharding import NamedSharding

        from ..optimizer import optimizer as opt_mod
        from . import mesh as mesh_mod

        self.block = block
        self.loss_fn = loss_fn
        if isinstance(optimizer, str):
            self.optimizer = opt_mod.create(optimizer,
                                            **(optimizer_params or {}))
        else:
            self.optimizer = optimizer
        self.mesh = mesh if mesh is not None else mesh_mod.get_mesh(create=True)
        if self.mesh is None:
            raise MXNetError("ShardedTrainer needs a device mesh")
        self.rules = rules or ShardingRules()
        P = _P()
        if batch_spec is None:
            batch_spec = P("dp") if "dp" in self.mesh.axis_names else P()
        self.batch_spec = batch_spec

        self._apply_fn, params = functionalize(block, train_mode=True)
        params_od = block.collect_params()
        self._train_names = [n for n in params
                             if params_od[n].grad_req != "null"]
        self._state_names = [n for n in params
                             if params_od[n].grad_req == "null"]
        # per-param lr_mult/wd_mult flow through the optimizer's param_dict,
        # same wiring as the eager gluon.Trainer (trainer.py) — frozen layers
        # (lr_mult=0) stay frozen under the SPMD step too
        self.optimizer.param_dict = {
            i: params_od[n] for i, n in enumerate(self._train_names)}
        # placement: params + optimizer state onto the mesh by rule
        self.params = self.rules.shard(params, self.mesh)
        self._opt_states = self._init_opt_states()
        self._step_jit = None
        self._step_count = 0
        self._key = jax.random.PRNGKey(0)

    # -- optimizer state --------------------------------------------------
    def _init_opt_states(self):
        import jax
        from jax.sharding import NamedSharding

        from ..gluon.trainer import _flatten_state
        from ..ndarray.ndarray import NDArray

        states = {}
        for i, n in enumerate(self._train_names):
            w = NDArray(self.params[n])
            st = self.optimizer.create_state_multi_precision(i, w)
            flat = [s._data for s in _flatten_state(st)]
            spec = self.rules.spec_for(n, self.params[n].shape, self.mesh)
            placed = []
            for s in flat:
                sh = (NamedSharding(self.mesh, spec) if s.shape == w.shape
                      else NamedSharding(self.mesh, _P()))
                placed.append(jax.device_put(s, sh))
            states[n] = tuple(placed)
        return states

    # -- the compiled step ------------------------------------------------
    def _build_step(self):
        import jax
        import jax.numpy as jnp

        apply_fn = self._apply_fn
        loss_fn = self.loss_fn
        opt = self.optimizer
        train_names = self._train_names
        state_names = self._state_names
        has_state = bool(state_names)

        def loss_of(train_params, state_params, batch, labels, key):
            params = dict(train_params)
            params.update(state_params)
            r = apply_fn(params, batch, rng_key=key)
            if has_state:
                out, new_state = r
            else:
                out, new_state = r, {}
            from ..ndarray.ndarray import NDArray

            out_nd = NDArray(out) if not isinstance(out, NDArray) else out
            lbl_nd = NDArray(labels)
            loss = loss_fn(out_nd, lbl_nd)
            ldata = loss._data if isinstance(loss, NDArray) else loss
            return jnp.mean(ldata), new_state

        def step(train_params, state_params, opt_states, batch, labels, key,
                 lrs, wds, t):
            (loss, new_state), grads = jax.value_and_grad(
                loss_of, has_aux=True)(train_params, state_params, batch,
                                       labels, key)
            new_train = {}
            new_opt = {}
            for i, n in enumerate(train_names):
                g = opt._prep_grad(grads[n].astype(train_params[n].dtype))
                p_new, s_new = opt._update_raw(train_params[n], g,
                                               opt_states[n], lrs[i], wds[i],
                                               t)
                new_train[n] = p_new
                new_opt[n] = tuple(s_new) if isinstance(s_new, (list, tuple)) \
                    else (s_new,)
            return new_train, new_state, new_opt, loss

        from jax.sharding import NamedSharding

        mesh = self.mesh
        p_shard = {
            n: NamedSharding(mesh,
                             self.rules.spec_for(n, self.params[n].shape,
                                                 mesh))
            for n in self.params
        }
        train_shard = {n: p_shard[n] for n in train_names}
        state_shard = {n: p_shard[n] for n in state_names}
        opt_shard = {
            n: tuple(
                NamedSharding(mesh, s.sharding.spec)
                for s in self._opt_states[n])
            for n in train_names
        }
        batch_shard = NamedSharding(mesh, self.batch_spec)
        repl = NamedSharding(mesh, _P()())
        self._step_jit = jax.jit(
            step,
            in_shardings=(train_shard, state_shard, opt_shard, batch_shard,
                          batch_shard, repl, None, None, None),
            out_shardings=(train_shard, state_shard, opt_shard, repl),
            donate_argnums=(0, 1, 2),
        )

    def step(self, data, labels):
        """Run one SPMD training step; returns the scalar loss as an
        NDArray (async — reading/printing it syncs, dispatch does not)."""
        import jax

        from ..ndarray.ndarray import NDArray

        if self._step_jit is None:
            self._build_step()
        d = data._data if isinstance(data, NDArray) else data
        l = labels._data if isinstance(labels, NDArray) else labels
        self._step_count += 1
        t = self._step_count
        n_train = len(self._train_names)
        for i in range(n_train):
            self.optimizer._index_update_count[i] = t
        lrs = tuple(self.optimizer._get_lr(i) for i in range(n_train))
        wds = tuple(self.optimizer._get_wd(i) for i in range(n_train))
        self._key, sub = jax.random.split(self._key)
        train = {n: self.params[n] for n in self._train_names}
        state = {n: self.params[n] for n in self._state_names}
        new_train, new_state, new_opt, loss = self._step_jit(
            train, state, self._opt_states, d, l, sub, lrs, wds, t)
        self.params.update(new_train)
        self.params.update(new_state)
        self._opt_states = new_opt
        return NDArray(loss)

    def sync_to_block(self):
        """Copy trained weights back into the Block's Parameters (a copy —
        the trainer's own arrays get donated on the next step)."""
        import jax.numpy as jnp

        params_od = self.block.collect_params()
        for n, arr in self.params.items():
            params_od[n].data()._set_data_internal(jnp.array(arr, copy=True))
