"""`DynamicBatcher`: request admission + deadline-bounded batch assembly.

Single requests enter through :meth:`DynamicBatcher.submit` and come back
as futures; a background flusher thread assembles batches and hands them
to a ``runner`` callable. A batch dispatches when either trigger fires:

* **flush-on-full** — ``max_batch_size`` requests are waiting, or
* **flush-on-deadline** — the *oldest* admitted request has waited
  ``MXNET_SERVE_BATCH_TIMEOUT_MS``; latecomers never extend the deadline
  (no unbounded batch-coalescing tail latency).

Overload safety (all off by default — a priority-free, deadline-free
deployment behaves exactly like the original FIFO batcher):

* **request deadlines** — ``submit(deadline_ms=...)`` (or the
  ``MXNET_SERVE_DEADLINE_MS`` default) attaches an absolute deadline.
  Expired requests are cancelled at every stage boundary — rejected at
  admission, swept out of the queue before each flush, and re-checked at
  settle time so a completion past deadline + ``MXNET_SERVE_DEADLINE_
  GRACE_MS`` becomes a :class:`DeadlineExceeded` (504) instead of a
  silent late delivery the client already gave up on.
* **two-class priority** — ``submit(priority="interactive"|"batch")``.
  Batches assemble interactive-first; under queue pressure the shedding
  is lowest-first: an interactive arrival displaces the *newest* queued
  batch-class request (its future settles with a 503 shed) before the
  interactive class ever sees a reject. ``MXNET_SERVE_BATCH_QUEUE_SHARE``
  caps the queue fraction the batch class may occupy, and
  ``MXNET_SERVE_RATE_LIMIT`` / ``MXNET_SERVE_RATE_BURST`` put a token
  bucket in front of batch-class admission.
* **graceful drain** — :meth:`drain` stops admission and waits for the
  queue and the in-flight batch to settle; :meth:`resume` reopens.
  :meth:`close` with a wedged runner fails every still-queued AND
  in-flight future with 503 instead of leaking them.

Admission control is a hard queue-depth cap (``MXNET_SERVE_MAX_QUEUE``):
beyond it :meth:`submit` fast-rejects with
:class:`~mxnet_tpu.serve.engine.ServiceUnavailable` *synchronously* — the
overloaded server sheds load in O(1) instead of growing a backlog whose
every entry will miss its SLO anyway. Overload-shaped 503s (full queue,
batch share, rate limit, drain, shed) carry a ``retry_after_ms`` hint
derived from the queue drain rate (depth x per-request service-time
EWMA); structural 503s (shutdown) carry ``None`` so callers can tell
"busy, come back" from "gone, fail over".

Exactly-once admission: ``submit(key=...)`` attaches an idempotency key.
A duplicate submit — same key while the original is queued, in flight,
or recently settled — returns the ORIGINAL future instead of enqueuing
a second copy, so a client (or the fleet Router's failover path)
retrying an ambiguous failure can never double-execute a request.

Failure isolation: a runner exception fails the *requests of that batch*
(each future carries the error) and the flusher thread keeps serving —
an injected ``op:dispatch`` fault is a per-request 5xx, not a dead server.
A runner may also return an ``Exception`` instance in a result slot to
fail that single request (the Generator runner uses this for per-row
deadline retirement). The ``serve:queue`` fault site fires inside
``submit`` so the chaos harness can fail admission deterministically.
"""
from __future__ import annotations

import collections
import threading
import time
import warnings
from concurrent.futures import Future, InvalidStateError

from ..profiler import core as _prof
from ..profiler import trace as _trace
from ..resilience import faults as _faults
from .engine import DeadlineExceeded, ServeError, ServiceUnavailable
from .metrics import ServeMetrics

PRIORITIES = ("interactive", "batch")
#: admission order = shed order reversed: the batch class sheds first.
_PRIORITY_RANK = {p: i for i, p in enumerate(PRIORITIES)}


class TokenBucket:
    """Classic token bucket: ``rate`` tokens/s refill, ``burst`` capacity.
    ``rate <= 0`` means unlimited (every :meth:`take` succeeds)."""

    def __init__(self, rate, burst):
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._t_last = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n=1.0):
        if self.rate <= 0:
            return True
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t_last) * self.rate)
            self._t_last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class _Pending:
    __slots__ = ("payload", "future", "t_enq", "t_dispatch", "priority",
                 "deadline", "key", "trace", "flow", "t_enq_ns",
                 "t_dispatch_ns")

    def __init__(self, payload, priority="interactive", deadline=None,
                 key=None):
        self.payload = payload
        self.future = Future()
        self.t_enq = time.monotonic()
        self.t_dispatch = None
        self.priority = priority
        self.deadline = deadline  # absolute time.monotonic() or None
        self.key = key            # idempotency key or None
        # request-scoped tracing (profiler.trace); None when tracing is
        # off. t_*_ns are perf_counter_ns stamps for retro span emission
        # (t_enq/t_dispatch above are monotonic() — a different clock).
        self.trace = None
        self.flow = None
        self.t_enq_ns = None
        self.t_dispatch_ns = None


def _retire_traced(p, stage, error=None):
    """Close out a pending entry's trace on a non-settle exit path (shed
    / expired / shutdown): the enqueue flow arrow must land somewhere
    (no orphan 's' events) and the trace must read as finished. An entry
    that already dispatched (``t_dispatch_ns`` set) had its arrow and
    queue span emitted by the flusher — only the finish applies."""
    if p.trace is None:
        return
    if p.t_dispatch_ns is None:
        p.trace.flow_in(p.flow, "serve::enqueue")
        p.trace.span_at("serve::queue", p.t_enq_ns,
                        time.perf_counter_ns(), {"outcome": stage})
    p.trace.finish(error=error or stage)


def _settle_future(fut, result=None, error=None):
    """Settle exactly once: a future that already carries an outcome (the
    close-timeout path racing a runner that eventually returned) is left
    untouched. Returns True if this call settled it."""
    try:
        if error is not None:
            fut.set_exception(error)
        else:
            fut.set_result(result)
        return True
    except InvalidStateError:
        return False


class DynamicBatcher:
    """Deadline/size-triggered dynamic batching queue.

    Parameters
    ----------
    runner : callable(list) -> list
        Executes one assembled batch of payloads; must return one result
        per payload (an :class:`InferenceSession`-backed closure in the
        serving stack, but any callable works). A result slot holding an
        ``Exception`` instance fails that request alone.
    max_batch_size, timeout_ms, max_queue : optional overrides of the
        ``MXNET_SERVE_*`` config flags.
    """

    def __init__(self, runner, max_batch_size=None, timeout_ms=None,
                 max_queue=None, name="batcher", metrics=None, start=True):
        from .. import config

        self.runner = runner
        self.max_batch_size = int(max_batch_size if max_batch_size is not None
                                  else config.get("MXNET_SERVE_MAX_BATCH"))
        if self.max_batch_size < 1:
            raise ServeError(
                f"max_batch_size must be >= 1, got {self.max_batch_size}")
        self.timeout_s = (timeout_ms if timeout_ms is not None
                          else config.get("MXNET_SERVE_BATCH_TIMEOUT_MS")) / 1e3
        self.max_queue = int(max_queue if max_queue is not None
                             else config.get("MXNET_SERVE_MAX_QUEUE"))
        if self.max_queue < 0:
            raise ServeError(
                f"max_queue must be >= 0, got {self.max_queue}")
        # overload knobs, resolved once (submit runs per request and must
        # not re-read the environment)
        self.default_deadline_s = (
            config.get("MXNET_SERVE_DEADLINE_MS") or 0.0) / 1e3
        self.deadline_grace_s = (
            config.get("MXNET_SERVE_DEADLINE_GRACE_MS") or 0.0) / 1e3
        share = float(config.get("MXNET_SERVE_BATCH_QUEUE_SHARE"))
        if not 0.0 <= share <= 1.0:
            raise ServeError(
                f"MXNET_SERVE_BATCH_QUEUE_SHARE must be in [0, 1], "
                f"got {share}")
        self.batch_queue_cap = int(self.max_queue * share)
        self.rate_limiter = TokenBucket(
            config.get("MXNET_SERVE_RATE_LIMIT"),
            config.get("MXNET_SERVE_RATE_BURST"))
        self.name = name
        self.metrics = metrics or ServeMetrics(name)
        self._queue = []               # FIFO of _Pending (guarded by _cond)
        self._inflight = []            # batch currently inside the runner
        self._cond = threading.Condition()
        self._closed = False
        self._draining = False
        self._thread = None
        # idempotency keys (exactly-once admission): key -> live future
        # while unsettled, then retained in a bounded settled map so a
        # duplicate submit AFTER settlement returns the same outcome
        # instead of recomputing it (the Router's failover/hedge paths
        # depend on duplicate-submits never double-executing)
        self._keyed = {}
        self._settled_keys = collections.OrderedDict()
        self._settled_cap = 2048
        self.duplicate_submits = 0
        # per-request amortized service time EWMA (ms) — the drain-rate
        # estimate behind the retry_after_ms hint on overload 503s
        self._svc_ms = None
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name=f"mxtpu-serve-batcher[{self.name}]")
        self._thread.start()

    def close(self, timeout=5.0):
        """Stop the flusher. Already-admitted requests are drained first;
        anything still queued after the drain fails with 503. If the
        flusher misses the join deadline (a runner wedged mid-batch),
        every still-queued future AND the wedged batch's futures fail
        with 503 — nothing is left to hang forever. Should the wedged
        runner later return, its settle attempt finds the futures already
        carrying the 503 and is dropped (exactly-once settle)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        stuck = []
        wedged = False
        if self._thread is not None:
            self._thread.join(timeout)
            wedged = self._thread.is_alive()
        with self._cond:
            # a wedged flusher (runner hung) OR a dead one (a `die` fault
            # is a BaseException — it kills the thread without running
            # _settle) both strand the in-flight batch; rescue it either
            # way. A cleanly-exited flusher left _inflight empty.
            stuck, self._inflight = self._inflight, []
            leftovers, self._queue = self._queue, []
            self.metrics.set_queue_depth(0)
        if stuck and wedged:
            warnings.warn(
                f"batcher {self.name!r}: flusher did not join within "
                f"{timeout}s (runner wedged mid-batch); failing its "
                f"{len(stuck)} in-flight and {len(leftovers)} queued "
                "request(s) with 503 instead of leaking them",
                RuntimeWarning, stacklevel=2)
        for p in stuck + leftovers:
            err = ServiceUnavailable(
                f"batcher {self.name!r} shut down before dispatch")
            _retire_traced(p, "shutdown", err)
            _settle_future(p.future, error=err)
            self._key_done(p)

    def drain(self, timeout=30.0):
        """Stop admission and wait until the queue AND the in-flight batch
        are empty. Returns True once quiesced (every admitted future has
        settled), False on timeout. Admission stays stopped either way;
        :meth:`resume` reopens it."""
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            self._draining = True
            self._cond.notify_all()   # wake the flusher: flush NOW
            while self._queue or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
        return True

    def resume(self):
        """Reopen admission after :meth:`drain`."""
        with self._cond:
            self._draining = False
            self._cond.notify_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- admission ----------------------------------------------------------
    def _resolve_deadline(self, deadline_ms):
        if deadline_ms is not None:
            return (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms > 0 else None)
        if self.default_deadline_s > 0:
            return time.monotonic() + self.default_deadline_s
        return None

    def _dedupe_locked(self, key):
        """Return the existing future for ``key`` (live or settled) or
        None. Caller holds ``_cond``."""
        fut = self._keyed.get(key)
        if fut is None:
            fut = self._settled_keys.get(key)
        return fut

    def submit(self, payload, priority="interactive", deadline_ms=None,
               key=None):
        """Admit one request; returns a :class:`concurrent.futures.Future`.

        ``priority`` is ``"interactive"`` (default — never shed in favor
        of batch work) or ``"batch"`` (sheds first under pressure).
        ``deadline_ms`` attaches a relative deadline (<= 0 disables even
        when ``MXNET_SERVE_DEADLINE_MS`` sets a default).
        ``key`` is an optional idempotency key: a resubmit of a key that
        is already queued, in flight, or recently settled returns the
        ORIGINAL request's future — it never enqueues a second copy, so a
        retry after an ambiguous failure cannot double-execute.

        Raises synchronously: :class:`ServiceUnavailable` when the queue
        is full of equal-or-higher-priority work, the batch-class share or
        token bucket rejects, or the batcher is closed/draining;
        :class:`DeadlineExceeded` when the deadline is already in the
        past at admission."""
        if priority not in _PRIORITY_RANK:
            raise ServeError(
                f"unknown priority {priority!r}; use one of {PRIORITIES}")
        if key is not None:
            # dedupe BEFORE the fault site and deadline check: a duplicate
            # must resolve to the original outcome, not inject a second
            # fault or 504 against a deadline the first copy already beat
            with self._cond:
                fut = self._dedupe_locked(key)
                if fut is not None:
                    self.duplicate_submits += 1
                    return fut
        t_sub_ns = time.perf_counter_ns() if _trace.ENABLED else 0
        # admission fault site OUTSIDE the lock: an injected delay models
        # a slow admission path, not a queue-lock convoy
        _faults.fault_point("serve:queue", {"batcher": self.name,
                                            "priority": priority})
        deadline = self._resolve_deadline(deadline_ms)
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            self.metrics.observe_deadline("admit", priority)
            raise DeadlineExceeded(
                f"batcher {self.name!r}: request deadline expired "
                "before admission")
        shed = None
        shed_hint = None
        with self._cond:
            if key is not None:
                # authoritative re-check under the admission lock (two
                # racing duplicates may both pass the pre-check above)
                fut = self._dedupe_locked(key)
                if fut is not None:
                    self.duplicate_submits += 1
                    return fut
            if self._closed:
                # structural: no retry_after_ms — waiting won't help
                raise ServiceUnavailable(
                    f"batcher {self.name!r} is shut down")
            if self._draining:
                self.metrics.observe_reject()
                raise self._shed_503(
                    f"batcher {self.name!r} is draining; no new work "
                    "admitted until resume()", self._drain_eta_ms_locked())
            if priority == "batch" and self.batch_queue_cap < self.max_queue:
                n_batch = sum(1 for p in self._queue
                              if p.priority == "batch")
                if n_batch >= self.batch_queue_cap:
                    self.metrics.observe_shed("batch", reason="share")
                    raise self._shed_503(
                        f"batcher {self.name!r}: batch-class queue share "
                        f"({self.batch_queue_cap} of {self.max_queue}) is "
                        "full; shed", self._drain_eta_ms_locked())
            if len(self._queue) >= self.max_queue:
                # shed-lowest-first: an interactive arrival displaces the
                # NEWEST queued lower-priority request (newest: it has
                # waited least, so killing it wastes the least invested
                # queue time) instead of being rejected
                victim_idx = None
                if priority == "interactive":
                    for i in range(len(self._queue) - 1, -1, -1):
                        if _PRIORITY_RANK[self._queue[i].priority] \
                                > _PRIORITY_RANK[priority]:
                            victim_idx = i
                            break
                if victim_idx is None:
                    self.metrics.observe_reject()
                    if priority == "batch":
                        self.metrics.observe_shed("batch",
                                                  reason="pressure")
                    raise self._shed_503(
                        f"batcher {self.name!r} queue is full "
                        f"({self.max_queue} waiting); shed load upstream",
                        self._drain_eta_ms_locked())
                shed = self._queue.pop(victim_idx)
                shed_hint = self._drain_eta_ms_locked()
            # rate-limit LAST, after every other reject: a token must only
            # be spent on a request that is actually admitted — otherwise
            # retries against a full/draining batcher drain the bucket and
            # the effective rate becomes attempts, not admissions
            if priority == "batch" and not self.rate_limiter.take():
                if shed is not None:
                    # can't happen (only interactive displaces), but never
                    # lose a popped victim
                    self._queue.append(shed)
                self.metrics.observe_shed("batch", reason="rate")
                raise self._shed_503(
                    f"batcher {self.name!r}: batch-class token bucket "
                    f"empty (MXNET_SERVE_RATE_LIMIT="
                    f"{self.rate_limiter.rate:g}/s); shed",
                    1e3 / self.rate_limiter.rate)
            p = _Pending(payload, priority=priority, deadline=deadline,
                         key=key)
            if t_sub_ns:
                # trace set up BEFORE the entry is visible to the flusher
                # (a half-traced entry would leak an unclosed flow arrow)
                tr = _trace.start_trace(f"serve.request[{self.name}]",
                                        args={"priority": priority})
                if tr is not None:
                    p.trace = tr
                    p.t_enq_ns = time.perf_counter_ns()
                    tr.span_at("serve::admit", t_sub_ns, p.t_enq_ns,
                               {"priority": priority})
                    p.flow = tr.flow_out("serve::enqueue")
            self._queue.append(p)
            if key is not None:
                self._keyed[key] = p.future
            self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify()
        if shed is not None:
            self.metrics.observe_shed(shed.priority, reason="pressure")
            err = self._shed_503(
                f"batcher {self.name!r}: shed under queue pressure to "
                "admit higher-priority work", shed_hint)
            _retire_traced(shed, "shed", err)
            _settle_future(shed.future, error=err)
            self._key_done(shed)
        return p.future

    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    def _drain_eta_ms_locked(self):
        """Estimate (ms) how long the current backlog takes to drain:
        queue depth x amortized per-request service time (EWMA from real
        settles), plus one batch-assembly window. Before the first settle
        the timeout alone stands in. Caller holds ``_cond``."""
        svc = self._svc_ms if self._svc_ms is not None \
            else self.timeout_s * 1e3
        return len(self._queue) * svc + self.timeout_s * 1e3

    @staticmethod
    def _shed_503(msg, retry_after_ms):
        """An overload-shaped 503: carries a ``retry_after_ms`` hint
        derived from the queue drain rate, so a client (or the fleet
        Router) backs off just long enough instead of guessing.
        Structural 503s — shutdown — deliberately carry None."""
        err = ServiceUnavailable(msg)
        err.retry_after_ms = max(1.0, float(retry_after_ms))
        return err

    def _key_done(self, p):
        """Retire a settled entry's idempotency key: drop the live
        mapping and retain the settled future in a bounded LRU so a
        late duplicate still gets the original outcome."""
        if p.key is None:
            return
        with self._cond:
            self._keyed.pop(p.key, None)
            self._settled_keys[p.key] = p.future
            self._settled_keys.move_to_end(p.key)
            while len(self._settled_keys) > self._settled_cap:
                self._settled_keys.popitem(last=False)

    # -- flusher ------------------------------------------------------------
    def _sweep_expired_locked(self, now):
        """Remove queue entries whose deadline has passed (caller holds
        ``_cond``); returns them for settling outside the lock."""
        expired = [p for p in self._queue
                   if p.deadline is not None and now >= p.deadline]
        if expired:
            dead = set(id(p) for p in expired)
            self._queue = [p for p in self._queue if id(p) not in dead]
        return expired

    def _take_batch(self):
        """Block until a batch is due; returns (batch, expired) — expired
        entries are settled by the caller with DeadlineExceeded. Flush
        triggers: size >= max_batch_size, the oldest entry older than
        timeout_s, or drain/close (dispatch NOW). Batches assemble
        interactive-first (stable within each class)."""
        with self._cond:
            while True:
                now = time.monotonic()
                expired = self._sweep_expired_locked(now)
                if expired:
                    self.metrics.set_queue_depth(len(self._queue))
                    return [], expired
                if self._queue:
                    # sort only on the dispatch branches — a wakeup that
                    # goes back to waiting must not pay O(n log n) under
                    # the lock submitters contend for
                    if len(self._queue) >= self.max_batch_size:
                        ordered = sorted(
                            self._queue,
                            key=lambda p: _PRIORITY_RANK[p.priority])
                        batch = ordered[:self.max_batch_size]
                        taken = set(id(p) for p in batch)
                        self._queue = [p for p in self._queue
                                       if id(p) not in taken]
                        self._inflight = list(batch)
                        self.metrics.set_queue_depth(len(self._queue))
                        return batch, []
                    age = now - self._queue[0].t_enq
                    remaining = self.timeout_s - age
                    if remaining <= 0 or self._closed or self._draining:
                        # flush deadline hit — or drain/shutdown: dispatch
                        # what's queued NOW instead of sitting it out
                        batch = sorted(
                            self._queue,
                            key=lambda p: _PRIORITY_RANK[p.priority])
                        self._queue = []
                        self._inflight = list(batch)
                        self.metrics.set_queue_depth(0)
                        return batch, []
                    # wake early enough to expire the nearest deadline
                    nearest = min((p.deadline - now for p in self._queue
                                   if p.deadline is not None),
                                  default=remaining)
                    self._cond.wait(max(1e-4, min(remaining, nearest)))
                elif self._closed:
                    return [], []
                else:
                    self._cond.wait(0.5)

    def _flush_loop(self):
        _prof.register_thread_name()
        while True:
            batch, expired = self._take_batch()
            if expired:
                self.settle_expired(expired)
                continue
            if not batch:
                if self._closed:
                    return
                continue
            now = time.monotonic()
            rep = None  # one traced request represents the batch downstream
            for p in batch:
                p.t_dispatch = now
                if p.trace is not None:
                    # land the enqueue arrow on THIS thread + emit the
                    # queue span retroactively from the stored ns stamps
                    p.t_dispatch_ns = time.perf_counter_ns()
                    p.trace.flow_in(p.flow, "serve::enqueue")
                    p.trace.span_at("serve::queue", p.t_enq_ns,
                                    p.t_dispatch_ns,
                                    {"batch_size": len(batch)})
                    if rep is None:
                        rep = p.trace
            self.metrics.observe_batch(len(batch), self.max_batch_size)
            try:
                with _trace.activate(rep):
                    results = self.runner([p.payload for p in batch])
                if len(results) != len(batch):
                    raise ServiceUnavailable(
                        f"batcher runner returned {len(results)} results "
                        f"for a {len(batch)}-request batch")
            except Exception as exc:  # pylint: disable=broad-except
                # (BaseException — e.g. an injected SimulatedWorkerDeath —
                # still kills the flusher: worker-death semantics belong
                # to the resilience harness, not per-request errors.)
                # the batch fails, the SERVER does not: every affected
                # request gets the error on its future and the loop
                # continues (the test for an injected op:dispatch fault)
                self._settle(batch, error=exc)
                continue
            self._settle(batch, results=results)

    def _settle(self, batch, results=None, error=None):
        done = time.monotonic()
        done_ns = time.perf_counter_ns()
        if error is None and batch:
            # feed the drain-rate estimator only from real completions:
            # failed batches say nothing about healthy service time
            per_req = (done - batch[0].t_dispatch) * 1e3 / len(batch)
            self._svc_ms = per_req if self._svc_ms is None \
                else 0.7 * self._svc_ms + 0.3 * per_req
        for i, p in enumerate(batch):
            queue_ms = (p.t_dispatch - p.t_enq) * 1e3
            exec_ms = (done - p.t_dispatch) * 1e3
            out, exc = None, error
            if exc is None:
                out = results[i]
                if isinstance(out, BaseException):
                    # per-request failure returned in a result slot
                    out, exc = None, out
            deadline_ok = True
            if exc is None and p.deadline is not None and done > p.deadline:
                if done > p.deadline + self.deadline_grace_s:
                    # the client's budget ran out mid-execution: a 504,
                    # never a silent late delivery
                    self.metrics.observe_deadline("execute", p.priority)
                    exc = DeadlineExceeded(
                        f"batcher {self.name!r}: completed "
                        f"{(done - p.deadline) * 1e3:.1f}ms past deadline "
                        f"(grace {self.deadline_grace_s * 1e3:.0f}ms)")
                else:
                    deadline_ok = False  # delivered, but counted late
            self.metrics.observe_request(queue_ms, exec_ms,
                                         ok=exc is None,
                                         priority=p.priority,
                                         deadline_ok=deadline_ok)
            if p.trace is not None:
                p.trace.span_at("serve::execute", p.t_dispatch_ns, done_ns,
                                {"exec_ms": round(exec_ms, 3),
                                 "ok": exc is None})
                p.trace.finish(error=exc)
            _settle_future(p.future, result=out, error=exc)
            self._key_done(p)
        with self._cond:
            self._inflight = []
            self._cond.notify_all()

    # -- iteration-level consumer API ---------------------------------------
    # The continuous-batching scheduler (serve.scheduler.ContinuousEngine)
    # consumes the queue directly between decode steps instead of through
    # the flusher thread: construct with ``start=False`` and drive
    # take() / settle_one() / settle_expired() / requeue(). Admission
    # semantics (priority classes, deadlines, shedding, idempotency keys,
    # retry_after_ms taxonomy) are byte-for-byte the same — only batch
    # *assembly* moves from the flusher's size/timeout triggers to the
    # scheduler's free-slot capacity between decode iterations.

    def take(self, max_n, wait_s=0.0):
        """Pop up to ``max_n`` queued requests, interactive-first (stable
        within each class — identical ordering to :meth:`_take_batch`).
        Returns ``(batch, expired)``: expired entries swept from the
        queue must be settled by the caller via :meth:`settle_expired`
        (and when any are returned the batch is empty — one concern per
        call). Blocks up to ``wait_s`` for work; ``([], [])`` means
        nothing was due or the batcher closed. Taken entries sit in the
        in-flight set (visible to :meth:`drain` / :meth:`close`) until
        :meth:`settle_one` or :meth:`requeue` removes them."""
        deadline = time.monotonic() + max(0.0, float(wait_s))
        with self._cond:
            while True:
                now = time.monotonic()
                expired = self._sweep_expired_locked(now)
                if expired:
                    self.metrics.set_queue_depth(len(self._queue))
                    return [], expired
                if self._queue and max_n > 0:
                    ordered = sorted(
                        self._queue,
                        key=lambda p: _PRIORITY_RANK[p.priority])
                    batch = ordered[:int(max_n)]
                    taken = set(id(p) for p in batch)
                    self._queue = [p for p in self._queue
                                   if id(p) not in taken]
                    self._inflight.extend(batch)
                    self.metrics.set_queue_depth(len(self._queue))
                    break
                remaining = deadline - now
                if remaining <= 0 or self._closed:
                    return [], []
                self._cond.wait(remaining)
        now = time.monotonic()
        for p in batch:
            p.t_dispatch = now
            if p.trace is not None and p.t_dispatch_ns is None:
                # first dispatch only: a requeued entry already landed
                # its enqueue arrow and queue span
                p.t_dispatch_ns = time.perf_counter_ns()
                p.trace.flow_in(p.flow, "serve::enqueue")
                p.trace.span_at("serve::queue", p.t_enq_ns,
                                p.t_dispatch_ns,
                                {"batch_size": len(batch)})
        self.metrics.observe_batch(len(batch), self.max_batch_size)
        return batch, []

    def requeue(self, p):
        """Put an in-flight entry back at the FRONT of the queue — the
        scheduler's answer to :class:`~.engine.PoolExhausted` at admit
        time: the request keeps its place in line and is re-taken the
        moment retirements free KV pages. On a closed batcher the entry
        settles with a structural 503 instead of re-entering a queue
        nobody will ever drain."""
        closed = False
        with self._cond:
            try:
                self._inflight.remove(p)
            except ValueError:
                pass
            closed = self._closed
            if not closed:
                self._queue.insert(0, p)
                self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify_all()
        if closed:
            err = ServiceUnavailable(
                f"batcher {self.name!r} shut down before dispatch")
            _retire_traced(p, "shutdown", err)
            _settle_future(p.future, error=err)
            self._key_done(p)

    def settle_expired(self, expired):
        """Settle queue-expired entries (the second element of
        :meth:`take` / :meth:`_take_batch`) with the 504 taxonomy:
        ``observe_deadline("queue")``, a failed-request sample, a
        :class:`DeadlineExceeded` on the future."""
        now = time.monotonic()
        for p in expired:
            self.metrics.observe_deadline("queue", p.priority)
            self.metrics.observe_request(
                (now - p.t_enq) * 1e3, 0.0, ok=False,
                priority=p.priority)
            err = DeadlineExceeded(
                f"batcher {self.name!r}: deadline expired after "
                f"{(now - p.t_enq) * 1e3:.1f}ms in queue")
            _retire_traced(p, "expired", err)
            _settle_future(p.future, error=err)
            self._key_done(p)
        with self._cond:
            # the sweep may have emptied the queue: wake drain()
            # waiters now, not at their timeout
            self._cond.notify_all()

    def settle_one(self, p, result=None, error=None):
        """Per-request settle for iteration-level consumers — requests
        retire one at a time as they finish, not as a batch. Applies the
        same deadline+grace recheck, metrics, tracing, exactly-once
        future semantics, and service-time EWMA feed as the flusher's
        :meth:`_settle`, then removes the entry from the in-flight set
        (waking :meth:`drain`)."""
        done = time.monotonic()
        done_ns = time.perf_counter_ns()
        t_disp = p.t_dispatch if p.t_dispatch is not None else done
        queue_ms = (t_disp - p.t_enq) * 1e3
        exec_ms = (done - t_disp) * 1e3
        exc = error
        if exc is None:
            self._svc_ms = exec_ms if self._svc_ms is None \
                else 0.7 * self._svc_ms + 0.3 * exec_ms
        deadline_ok = True
        if exc is None and p.deadline is not None and done > p.deadline:
            if done > p.deadline + self.deadline_grace_s:
                self.metrics.observe_deadline("execute", p.priority)
                exc = DeadlineExceeded(
                    f"batcher {self.name!r}: completed "
                    f"{(done - p.deadline) * 1e3:.1f}ms past deadline "
                    f"(grace {self.deadline_grace_s * 1e3:.0f}ms)")
            else:
                deadline_ok = False  # delivered, but counted late
        self.metrics.observe_request(queue_ms, exec_ms,
                                     ok=exc is None,
                                     priority=p.priority,
                                     deadline_ok=deadline_ok)
        if p.trace is not None:
            p.trace.span_at("serve::execute",
                            p.t_dispatch_ns or done_ns, done_ns,
                            {"exec_ms": round(exec_ms, 3),
                             "ok": exc is None})
            p.trace.finish(error=exc)
        _settle_future(p.future,
                       result=result if exc is None else None,
                       error=exc)
        self._key_done(p)
        with self._cond:
            try:
                self._inflight.remove(p)
            except ValueError:
                pass
            self._cond.notify_all()

    def stats(self):
        out = self.metrics.snapshot()
        out["queue_depth"] = self.queue_depth()
        out["duplicate_submits"] = self.duplicate_submits
        return out
