"""`DynamicBatcher`: request admission + deadline-bounded batch assembly.

Single requests enter through :meth:`DynamicBatcher.submit` and come back
as futures; a background flusher thread assembles batches and hands them
to a ``runner`` callable. A batch dispatches when either trigger fires:

* **flush-on-full** — ``max_batch_size`` requests are waiting, or
* **flush-on-deadline** — the *oldest* admitted request has waited
  ``MXNET_SERVE_BATCH_TIMEOUT_MS``; latecomers never extend the deadline
  (no unbounded batch-coalescing tail latency).

Admission control is a hard queue-depth cap (``MXNET_SERVE_MAX_QUEUE``):
beyond it :meth:`submit` fast-rejects with
:class:`~mxnet_tpu.serve.engine.ServiceUnavailable` *synchronously* — the
overloaded server sheds load in O(1) instead of growing a backlog whose
every entry will miss its SLO anyway.

Failure isolation: a runner exception fails the *requests of that batch*
(each future carries the error) and the flusher thread keeps serving —
an injected ``op:dispatch`` fault is a per-request 5xx, not a dead server.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future

from .engine import ServeError, ServiceUnavailable
from .metrics import ServeMetrics


class _Pending:
    __slots__ = ("payload", "future", "t_enq", "t_dispatch")

    def __init__(self, payload):
        self.payload = payload
        self.future = Future()
        self.t_enq = time.monotonic()
        self.t_dispatch = None


class DynamicBatcher:
    """Deadline/size-triggered dynamic batching queue.

    Parameters
    ----------
    runner : callable(list) -> list
        Executes one assembled batch of payloads; must return one result
        per payload (an :class:`InferenceSession`-backed closure in the
        serving stack, but any callable works).
    max_batch_size, timeout_ms, max_queue : optional overrides of the
        ``MXNET_SERVE_*`` config flags.
    """

    def __init__(self, runner, max_batch_size=None, timeout_ms=None,
                 max_queue=None, name="batcher", metrics=None, start=True):
        from .. import config

        self.runner = runner
        self.max_batch_size = int(max_batch_size if max_batch_size is not None
                                  else config.get("MXNET_SERVE_MAX_BATCH"))
        if self.max_batch_size < 1:
            raise ServeError(
                f"max_batch_size must be >= 1, got {self.max_batch_size}")
        self.timeout_s = (timeout_ms if timeout_ms is not None
                          else config.get("MXNET_SERVE_BATCH_TIMEOUT_MS")) / 1e3
        self.max_queue = int(max_queue if max_queue is not None
                             else config.get("MXNET_SERVE_MAX_QUEUE"))
        if self.max_queue < 0:
            raise ServeError(
                f"max_queue must be >= 0, got {self.max_queue}")
        self.name = name
        self.metrics = metrics or ServeMetrics(name)
        self._queue = []               # FIFO of _Pending (guarded by _cond)
        self._cond = threading.Condition()
        self._closed = False
        self._thread = None
        if start:
            self.start()

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._flush_loop, daemon=True,
            name=f"mxtpu-serve-batcher[{self.name}]")
        self._thread.start()

    def close(self, timeout=5.0):
        """Stop the flusher. Already-admitted requests are drained first;
        anything still queued after the drain fails with 503."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
        with self._cond:
            leftovers, self._queue = self._queue, []
        for p in leftovers:
            p.future.set_exception(ServiceUnavailable(
                f"batcher {self.name!r} shut down before dispatch"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- admission ----------------------------------------------------------
    def submit(self, payload):
        """Admit one request; returns a :class:`concurrent.futures.Future`.
        Raises :class:`ServiceUnavailable` synchronously when the queue is
        at ``max_queue`` (admission control) or the batcher is closed."""
        with self._cond:
            if self._closed:
                raise ServiceUnavailable(
                    f"batcher {self.name!r} is shut down")
            if len(self._queue) >= self.max_queue:
                self.metrics.observe_reject()
                raise ServiceUnavailable(
                    f"batcher {self.name!r} queue is full "
                    f"({self.max_queue} waiting); shed load upstream")
            p = _Pending(payload)
            self._queue.append(p)
            self.metrics.set_queue_depth(len(self._queue))
            self._cond.notify()
        return p.future

    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    # -- flusher ------------------------------------------------------------
    def _take_batch(self):
        """Block until a batch is due; returns a list of _Pending (empty
        on shutdown). Flush triggers: size >= max_batch_size, or oldest
        entry older than timeout_s."""
        with self._cond:
            while True:
                if self._queue:
                    if len(self._queue) >= self.max_batch_size:
                        batch = self._queue[:self.max_batch_size]
                        del self._queue[:self.max_batch_size]
                        self.metrics.set_queue_depth(len(self._queue))
                        return batch
                    age = time.monotonic() - self._queue[0].t_enq
                    remaining = self.timeout_s - age
                    if remaining <= 0 or self._closed:
                        # deadline hit — or shutting down: drain what's
                        # queued NOW instead of sitting out the deadline
                        batch, self._queue = self._queue, []
                        self.metrics.set_queue_depth(0)
                        return batch
                    self._cond.wait(remaining)
                elif self._closed:
                    return []
                else:
                    self._cond.wait(0.5)

    def _flush_loop(self):
        while True:
            batch = self._take_batch()
            if not batch:
                if self._closed:
                    return
                continue
            now = time.monotonic()
            for p in batch:
                p.t_dispatch = now
            self.metrics.observe_batch(len(batch), self.max_batch_size)
            try:
                results = self.runner([p.payload for p in batch])
                if len(results) != len(batch):
                    raise ServiceUnavailable(
                        f"batcher runner returned {len(results)} results "
                        f"for a {len(batch)}-request batch")
            except Exception as exc:  # pylint: disable=broad-except
                # (BaseException — e.g. an injected SimulatedWorkerDeath —
                # still kills the flusher: worker-death semantics belong
                # to the resilience harness, not per-request errors.)
                # the batch fails, the SERVER does not: every affected
                # request gets the error on its future and the loop
                # continues (the test for an injected op:dispatch fault)
                self._settle(batch, error=exc)
                continue
            self._settle(batch, results=results)

    def _settle(self, batch, results=None, error=None):
        done = time.monotonic()
        for i, p in enumerate(batch):
            queue_ms = (p.t_dispatch - p.t_enq) * 1e3
            exec_ms = (done - p.t_dispatch) * 1e3
            self.metrics.observe_request(queue_ms, exec_ms,
                                         ok=error is None)
            if error is None:
                p.future.set_result(results[i])
            else:
                p.future.set_exception(error)

    def stats(self):
        out = self.metrics.snapshot()
        out["queue_depth"] = self.queue_depth()
        return out
