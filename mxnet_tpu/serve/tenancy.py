"""Multi-tenant serving: N named models per process (PR-14).

:class:`ModelRegistry` hosts several named models side by side, each
behind its own :class:`~.scheduler.ContinuousEngine` — so each tenant
gets a private ``PagedKVPool`` + prefix trie + two compiled signatures,
and requests route by name through :meth:`submit` with the full PR-6
admission surface (priority classes, deadlines → 504, queue caps/sheds
→ 503, idempotency keys) applied *by the tenant's own engine*, not
re-implemented here.

Budget semantics: at most ``MXNET_SERVE_MAX_MODELS`` tenants stay
resident. Loading past the budget LRU-evicts the coldest tenant —
preferring idle ones (no live slots, empty queue); a busy tenant is
only evicted when every resident tenant is busy. Eviction closes the
tenant's engine (503s its in-flight work, frees its pool and
executables) but **keeps its factory**, so a later ``load()``/
``submit()`` for that name reloads it — warm from the persistent
compile cache (:mod:`mxnet_tpu.compile_cache`) when
``MXNET_COMPILE_CACHE_DIR`` is set, which is what turns an eviction
round-trip from a compile storm into cache-read seconds.

Lock discipline (mxlint L002 / lockdep-clean): the registry lock only
guards the name → tenant map and LRU bookkeeping. Engine builds,
warmups, and closes — all blocking — happen *outside* it, serialized
per tenant by a loading event so two threads racing ``load()`` on one
name build once and the loser waits on the event, not the lock.
"""
from __future__ import annotations

import itertools
import threading
import weakref

from .engine import ServeError
from .scheduler import ContinuousEngine

__all__ = ["ModelRegistry", "registry_stats"]

# live registries, for the process-wide registry_stats() aggregate
# (profiler.export pulls it); weak so a retired registry never pins
_registries: "weakref.WeakSet" = weakref.WeakSet()


def registry_stats():
    """``{registry_name: summary}`` over every live ModelRegistry
    (pulled by ``profiler.export.snapshot()`` under ``tenancy.*``)."""
    return {r.name: r.summary() for r in list(_registries)}


class _Tenant:
    __slots__ = ("name", "factory", "engine_kwargs", "engine", "ready",
                 "last_used", "loads")

    def __init__(self, name, factory, engine_kwargs):
        self.name = name
        self.factory = factory
        self.engine_kwargs = dict(engine_kwargs)
        self.engine = None
        self.ready = threading.Event()  # set once engine is live (or load failed)
        self.last_used = 0
        self.loads = 0


class ModelRegistry:
    """Named-model host: ``load()`` builds/warms a tenant engine,
    ``submit(model=...)`` routes, cold tenants LRU-evict past the
    ``max_models`` budget.

    Parameters
    ----------
    max_models : resident-tenant budget (``MXNET_SERVE_MAX_MODELS``).
    name : registry label (tenant engines are named
        ``<name>.<tenant>``).
    engine_defaults : keyword defaults forwarded to every tenant's
        :class:`~.scheduler.ContinuousEngine` (``max_seq=``,
        ``num_slots=``, ``prefix_cache=``, ...); per-tenant ``load()``
        kwargs override them.
    """

    def __init__(self, max_models=None, name="registry",
                 **engine_defaults):
        from .. import config

        if max_models is None:
            max_models = int(config.get("MXNET_SERVE_MAX_MODELS"))
        self.max_models = int(max_models)
        if self.max_models < 1:
            raise ServeError("max_models must be >= 1")
        self.name = name
        self.engine_defaults = dict(engine_defaults)
        self._tenants = {}
        self._lock = threading.Lock()
        self._clock = itertools.count(1)
        self.evictions = 0
        self.loads = 0
        self._closed = False
        _registries.add(self)

    # -- loading -------------------------------------------------------------
    def load(self, name, model=None, factory=None, **engine_kwargs):
        """Make tenant ``name`` resident and return its engine.

        First call must supply ``model`` (an initialized block) or
        ``factory`` (zero-arg callable building one — kept for evicted-
        tenant reload, so prefer it for anything evictable). Later calls
        may omit both: a resident tenant is returned as-is (LRU-
        touched); an evicted one rebuilds from its stored factory. The
        build + warmup runs outside the registry lock; with the
        persistent compile cache enabled the warmup replays the bucket
        lattice from disk instead of compiling."""
        if factory is None and model is not None:
            factory = lambda m=model: m  # noqa: E731
        wait_for = None
        build_me = None
        with self._lock:
            if self._closed:
                raise ServeError(f"registry {self.name!r} is closed")
            t = self._tenants.get(name)
            if t is None:
                if factory is None:
                    raise ServeError(
                        f"unknown model {name!r}: first load() needs "
                        f"model= or factory=")
                t = _Tenant(name, factory, {**self.engine_defaults,
                                            **engine_kwargs})
                self._tenants[name] = t
            elif factory is not None:
                t.factory = factory
                if engine_kwargs:
                    t.engine_kwargs.update(engine_kwargs)
            t.last_used = next(self._clock)
            if t.engine is not None:
                return t.engine
            if t.ready.is_set() or t.loads == 0:
                # evicted (or brand new): this thread builds
                t.ready.clear()
                t.loads += 1
                self.loads += 1
                build_me = t
            else:
                wait_for = t  # another thread is mid-build
        if wait_for is not None:
            wait_for.ready.wait()
            if wait_for.engine is None:
                raise ServeError(
                    f"model {name!r} failed to load (concurrent load "
                    f"raised); retry load()")
            return wait_for.engine
        return self._build(build_me)

    def _build(self, t):
        from .. import compile_cache as _cc

        victims = self._pick_victims(exclude=t.name)
        for v in victims:
            self._close_engine(v)
        engine = None
        try:
            _cc.enable()  # warm from disk when MXNET_COMPILE_CACHE_DIR set
            engine = ContinuousEngine(
                t.factory(), name=f"{self.name}.{t.name}",
                **t.engine_kwargs)
            engine.start()  # warms up (disk-cache replay) + scheduler
        except BaseException:
            if engine is not None:
                engine.close()
            with self._lock:
                self._tenants.pop(t.name, None)
            t.ready.set()
            raise
        t.engine = engine
        t.ready.set()
        return engine

    # -- eviction ------------------------------------------------------------
    def _pick_victims(self, exclude=None):
        """Detach enough LRU tenants (idle-first) to fit one more
        resident engine under the budget. Runs its map surgery under the
        lock; the blocking engine.close() happens at the caller, outside
        it."""
        out = []
        with self._lock:
            while True:
                resident = [t for t in self._tenants.values()
                            if t.engine is not None and t.name != exclude]
                if len(resident) < self.max_models:
                    break
                idle = [t for t in resident if t.engine._idle()]
                pool = idle or resident
                victim = min(pool, key=lambda t: t.last_used)
                out.append(victim.engine)
                victim.engine = None
                self.evictions += 1
        return out

    def _close_engine(self, engine):
        engine.close()

    def evict(self, name):
        """Explicitly evict tenant ``name`` (keeps its factory for
        reload). Returns True if an engine was actually closed."""
        with self._lock:
            t = self._tenants.get(name)
            engine = t.engine if t is not None else None
            if t is not None:
                t.engine = None
                if engine is not None:
                    self.evictions += 1
        if engine is None:
            return False
        self._close_engine(engine)
        return True

    # -- routing -------------------------------------------------------------
    def submit(self, model, prompt, **kwargs):
        """Route one generation request to tenant ``model``; all
        :meth:`~.scheduler.ContinuousEngine.submit` semantics pass
        through (``priority=``, ``deadline_ms=``, ``key=``, ...). An
        evicted tenant with a stored factory transparently reloads
        (blocking this caller for the warmup) — an unknown name is a
        :class:`ServeError`."""
        engine = self.load(model)
        return engine.submit(prompt, **kwargs)

    def get(self, name):
        """The tenant's live engine, or None (unknown/evicted). Does not
        touch LRU order."""
        with self._lock:
            t = self._tenants.get(name)
            return t.engine if t is not None else None

    def resident(self):
        with self._lock:
            return sorted(n for n, t in self._tenants.items()
                          if t.engine is not None)

    # -- readout / lifecycle -------------------------------------------------
    def summary(self):
        with self._lock:
            tenants = dict(self._tenants)
        return {"max_models": self.max_models,
                "resident": sum(1 for t in tenants.values()
                                if t.engine is not None),
                "known": len(tenants),
                "loads": self.loads,
                "evictions": self.evictions,
                "kv_cache_bytes": {
                    n: t.engine.pool.nbytes()
                    for n, t in tenants.items() if t.engine is not None}}

    def stats(self):
        out = self.summary()
        with self._lock:
            engines = {n: t.engine for n, t in self._tenants.items()
                       if t.engine is not None}
        out["models"] = {n: e.stats() for n, e in engines.items()}
        return out

    def close(self, timeout=5.0):
        with self._lock:
            self._closed = True
            engines = [t.engine for t in self._tenants.values()
                       if t.engine is not None]
            for t in self._tenants.values():
                t.engine = None
        for e in engines:
            e.close(timeout)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
