"""Continuous batching: an iteration-level scheduler for the decode loop.

The PR-6/PR-10 serving stack batches at *request* granularity: the
``DynamicBatcher`` assembles a batch, ``Generator.generate`` runs it to
completion, and every request in the batch holds its slot until the
LONGEST one finishes — a 4-token interactive request admitted next to a
256-token batch job waits out all 256 steps (head-of-line blocking), and
each request's KV ring is sized ``max_seq`` whether it uses 6 positions
or all of them.

:class:`ContinuousEngine` rebatches at *iteration* granularity (Orca):
the decode loop runs forever over a fixed lattice of ``num_slots`` decode
lanes, and between any two decode steps it

* **retires** finished/expired slots — their futures settle immediately
  (an expired request keeps its partial output on the 504), their KV
  pages recycle to the free list;
* **admits** queued requests into the freed slots straight from the
  :class:`~.batcher.DynamicBatcher` queue (``start=False`` — the
  scheduler IS the consumer), interactive-first with the full PR-6
  admission surface (deadlines, shedding, idempotency keys, 503/504
  taxonomy) unchanged;
* **prefills one chunk** of one admitted prompt at a fixed ``(1, chunk)``
  signature, round-robin across prefilling slots — a long prompt streams
  through without ever stalling live decodes for more than one chunk;
* **decodes** every live slot in ONE fixed ``(num_slots, 1)`` step.

KV state lives in a :class:`~.kv_blocks.PagedKVPool`: per-layer page
pools plus a per-slot page table, gathered/scattered around the unchanged
model cache path (fused into the step executable on the fast rungs,
standalone exact-copy brackets around the ring executable on the strict
baseline rung — see ``kv_blocks``). A request holds
``ceil((prompt + max_new) / page_size)`` pages (reserved at admission —
it can never die mid-decode from pool pressure), not a ``max_seq`` ring;
a full pool rejects admission with :class:`~.engine.PoolExhausted` and
the request is requeued at the front, never dropped.

Trace-static by construction: occupancy changes only ever rewrite the
page-table *values* and the token/position vectors — never a shape. The
engine compiles exactly TWO signatures (one chunk prefill, one full-width
decode); :meth:`ContinuousEngine.assert_no_recompiles` holds across any
sequence of admits/retires after :meth:`warmup`. Idle slots point every
page-table entry at the null page, so one executable serves every
occupancy from empty to full.
"""
from __future__ import annotations

import threading
import time

import numpy as _onp

from ..base import MXNetError
from ..profiler import attribution as _attr
from ..profiler import trace as _trace
from ..resilience import faults as _faults
from .batcher import DynamicBatcher
from .engine import DeadlineExceeded, InferenceSession, PoolExhausted, \
    ServeError, ServiceUnavailable
from .generate import _CacheForward, _MultiStepForward, _STOP_WIDTH, \
    _fresh_key_bits, _int8_weights_enabled, _quantize_serving_weights, \
    _stop_matrix, resolve_decode_path, sample_tokens
from ..ops import nn as _ops
from .kv_blocks import PagedKVPool
from .prefix_cache import PrefixCache


def _no_runner(_batch):  # pragma: no cover - the scheduler IS the consumer
    raise ServeError("continuous-batching queue has no flusher runner")


class _Slot:
    """One decode lane's live request state (scheduler-thread private)."""

    __slots__ = ("p", "prompt", "consumed", "pos", "decoding", "pending",
                 "tokens", "max_new", "temperature", "top_k", "stop",
                 "finished", "expired", "t_admit", "admit_wait_steps",
                 "ttft_ms", "decode_steps", "seed")

    def __init__(self, p, steps_now, seed=0):
        payload = p.payload
        self.p = p
        self.prompt = payload["prompt"]
        self.consumed = 0          # prompt tokens already prefilled
        self.pos = 0               # ring write position once decoding
        self.decoding = False      # prefill complete, pending token live
        self.pending = 0           # next token id to feed the decode step
        self.tokens = []           # emitted output ids
        self.max_new = payload["max_new"]
        self.temperature = payload["temperature"]
        self.top_k = payload["top_k"]
        self.stop = payload["stop"]
        self.finished = False
        self.expired = False
        self.t_admit = time.monotonic()
        self.admit_wait_steps = steps_now - payload["enq_step"]
        self.ttft_ms = None
        self.decode_steps = 0
        # per-request sampling-stream id (the engine's admission counter):
        # the multistep in-trace sampler folds it into its key so two
        # requests reusing one slot never share a draw stream
        self.seed = int(seed)

    def emit(self, tid):
        """Account one sampled token; flips ``finished`` on stop/budget."""
        if tid in self.stop:
            self.finished = True
            return
        self.tokens.append(tid)
        if len(self.tokens) >= self.max_new:
            self.finished = True
        else:
            self.pending = tid


class ContinuousEngine:
    """Iteration-level scheduler + paged-KV decode loop for one model.

    Parameters
    ----------
    model : LlamaModel (same duck type :class:`~.generate.Generator`
        serves).
    max_seq : per-request logical ring length (prompt + generated tokens
        must fit); must be a whole number of KV pages.
    num_slots : decode lanes — the ONE compiled decode width
        (``MXNET_SERVE_SLOTS`` default).
    page_size / num_pages : pool geometry (see
        :class:`~.kv_blocks.PagedKVPool`); undersize ``num_pages`` to
        oversubscribe — admission then queues on pool pressure.
    prefill_chunk : tokens prefilled per scheduler iteration at the fixed
        ``(1, chunk)`` signature (``MXNET_SERVE_PREFILL_CHUNK``; 0 means
        one KV page).
    decode_path : serving rung ("baseline" | "pallas" | "int8", see
        :func:`~.generate.resolve_decode_path`). The baseline rung keeps
        the bitwise decode contract — paging brackets are exact copies.
    batcher_kwargs : extra :class:`~.batcher.DynamicBatcher` constructor
        overrides (``max_queue=``, ``timeout_ms=``, ...).
    """

    def __init__(self, model, max_seq=128, num_slots=None, page_size=None,
                 num_pages=None, prefill_chunk=None, pad_id=0,
                 name="llama_cb", decode_path=None, prefix_cache=None,
                 multistep=None, decode_steps=None, **batcher_kwargs):
        from .. import config

        self.model = model
        self.max_seq = int(max_seq)
        if num_slots is None:
            num_slots = int(config.get("MXNET_SERVE_SLOTS"))
        self.num_slots = int(num_slots)
        if self.num_slots < 1:
            raise ServeError(f"num_slots must be >= 1, got {num_slots}")
        self.pad_id = int(pad_id)
        self.decode_path = resolve_decode_path(decode_path)
        self._quant = "int8" if self.decode_path == "int8" else None
        self._qindex, self._qflat = [], []
        if self._quant and _int8_weights_enabled():
            self._qindex, self._qflat = _quantize_serving_weights(model)
        self.pool = PagedKVPool(model, self.num_slots, self.max_seq,
                                page_size=page_size, num_pages=num_pages,
                                quant=self._quant)
        if prefill_chunk is None:
            prefill_chunk = int(config.get("MXNET_SERVE_PREFILL_CHUNK"))
        self.prefill_chunk = (int(prefill_chunk) if prefill_chunk > 0
                              else self.pool.page_size)
        if self.prefill_chunk > self.max_seq:
            self.prefill_chunk = self.max_seq
        # cross-request prefix reuse (PR-14): a radix trie over prompt
        # token ids maps matched prefixes to refcounted pool pages, so
        # _admit can skip the matched portion of chunked prefill
        if prefix_cache is None:
            prefix_cache = bool(config.get("MXNET_SERVE_PREFIX_CACHE"))
        self.prefix = (PrefixCache(self.pool, name=f"{name}_prefix")
                       if prefix_cache else None)
        # fast rungs fuse the paging brackets into the step executable;
        # the strict baseline rung keeps the RING executable and runs
        # the brackets as standalone exact copies in _run_step, which is
        # what makes its decode bitwise identical to the ring path
        self._fused_paged = self.decode_path != "baseline"
        self._step_block = _CacheForward(
            model, self.max_seq, path=self.decode_path, quant=self._quant,
            qindex=self._qindex, paged=self._fused_paged)
        # exactly two live signatures: (1, chunk) chunked prefill and
        # (num_slots, 1) decode — the whole point of the design
        self.session = InferenceSession(
            self._step_block,
            batch_buckets=tuple(sorted({1, self.num_slots})),
            seq_buckets=tuple(sorted({1, self.prefill_chunk})),
            pad_value=self.pad_id, name=name,
            deterministic=(self.decode_path == "baseline"))
        self.metrics = self.session.metrics
        self.metrics.set_decode_path(self.decode_path)
        self.metrics.set_kv_cache_bytes(self.pool.nbytes())
        # the admission queue: PR-6 semantics intact, flusher OFF — the
        # scheduler consumes via take()/settle_one() between decode steps
        self._batcher = DynamicBatcher(
            _no_runner, start=False, max_batch_size=self.num_slots,
            name=f"{name}_queue", metrics=self.metrics, **batcher_kwargs)
        # decode critical-path ledger (tentpole PR 16): observations are
        # gated on _attr.ENABLED, the ledger object itself is always
        # there so tests/bench can read it without reaching into flags
        self.ledger = _attr.Ledger(name)
        self._last_emit_t = None   # previous decode step's token stamp
        self._slots = [None] * self.num_slots
        self._steps = 0            # completed scheduler iterations
        self._pf_next = 0          # round-robin cursor over prefill slots
        self._admit_wait_max = 0
        self._thread = None
        self._stop = threading.Event()
        # multi-step decode (tentpole PR 19): up to N decode iterations
        # per host visit inside one compiled loop. The super-step lives
        # in its own session; the engine still compiles exactly two
        # steady-state signatures — (1, chunk) prefill and the
        # (num_slots, N-loop) super-step (the classic (num_slots, 1)
        # decode is simply never compiled in this mode).
        if multistep is None:
            multistep = bool(config.get("MXNET_SERVE_MULTISTEP"))
        self._multistep = bool(multistep)
        if decode_steps is None:
            decode_steps = int(config.get("MXNET_SERVE_DECODE_STEPS"))
        self.decode_steps = max(1, int(decode_steps))
        self._msession = None
        self._itl_est = None   # EMA seconds per decode iteration
        self._seed_seq = 0     # admission counter -> _Slot.seed
        if self._multistep:
            self._mstep = _MultiStepForward(
                model, self.max_seq, self.decode_steps,
                path=self.decode_path, quant=self._quant,
                qindex=self._qindex, paged=True)
            self._msession = InferenceSession(
                self._mstep, batch_buckets=(self.num_slots,),
                seq_buckets=(1,), pad_value=self.pad_id,
                name=f"{name}_multi",
                deterministic=(self.decode_path == "baseline"))
            # one key per engine; per-request streams come from folding
            # each slot's admission seed (and position) into it in-trace
            self._key_bits = _fresh_key_bits()

    # -- admission -----------------------------------------------------------
    def submit(self, prompt, max_new_tokens=32, temperature=0.0,
               top_k=None, stop_ids=(), priority="interactive",
               deadline_ms=None, key=None):
        """Admit one generation request; returns a Future resolving to
        ``{"tokens": [...], "ttft_ms": ..., "admit_wait_steps": ...,
        "decode_steps": ...}``. The full PR-6 admission surface applies
        (priority classes, deadlines -> 504, queue caps/sheds -> 503,
        idempotency keys); a deadline that expires mid-decode settles
        with :class:`DeadlineExceeded` whose ``.partial`` carries the
        tokens generated so far."""
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise MXNetError("empty prompt (need >= 1 token)")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise MXNetError("max_new_tokens must be >= 1")
        if len(prompt) + max_new > self.max_seq:
            raise MXNetError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds max_seq ({self.max_seq})")
        payload = {"prompt": prompt, "max_new": max_new,
                   "temperature": temperature, "top_k": top_k,
                   "stop": frozenset(int(s) for s in stop_ids),
                   "enq_step": self._steps}
        return self._batcher.submit(payload, priority=priority,
                                    deadline_ms=deadline_ms, key=key)

    # -- scheduler iteration -------------------------------------------------
    def _live(self):
        return [s for s in self._slots if s is not None]

    def _free_idx(self):
        return [i for i, s in enumerate(self._slots) if s is None]

    def _settle_slot(self, i, error=None):
        """Retire slot ``i``: settle its future, recycle its pages. On a
        clean retirement the prefix trie adopts the prompt's full pages
        first (increfs while the slot still pins them), so the next
        request sharing this prompt prefix skips that much prefill."""
        s = self._slots[i]
        self._slots[i] = None
        if self.prefix is not None and error is None and s.decoding:
            self.prefix.insert(s.prompt, self.pool.table()[i])
        self.pool.release(i)
        if error is not None:
            self._batcher.settle_one(s.p, error=error)
            return
        if s.expired:
            err = DeadlineExceeded(
                f"continuous engine {self.session.name!r}: deadline "
                f"expired after {len(s.tokens)} of {s.max_new} tokens")
            err.partial = list(s.tokens)
            self._batcher.settle_one(s.p, error=err)
            return
        n = len(s.tokens)
        self.metrics.observe_tokens(
            n, max(time.monotonic() - s.t_admit, 1e-9))
        self._batcher.settle_one(s.p, result={
            "tokens": list(s.tokens),
            "ttft_ms": s.ttft_ms,
            "admit_wait_steps": s.admit_wait_steps,
            "decode_steps": s.decode_steps,
        })

    def _retire(self):
        now = time.monotonic()
        for i, s in enumerate(self._slots):
            if s is None:
                continue
            if not s.finished and s.p.deadline is not None \
                    and now >= s.p.deadline:
                # the request's budget ran out between steps: stop burning
                # decode work on output nobody will read
                s.finished = s.expired = True
                self.metrics.observe_deadline("decode", s.p.priority)
            if s.finished:
                self._settle_slot(i)

    def _admit(self):
        free = self._free_idx()
        while free:
            batch, sweep = self._batcher.take(1)
            if sweep:
                self._batcher.settle_expired(sweep)
                continue
            if not batch:
                return
            p = batch[0]
            i = free[0]
            need = len(p.payload["prompt"]) + p.payload["max_new"]
            matched, pages = 0, ()
            if self.prefix is not None:
                matched, pages = self.prefix.match(p.payload["prompt"])
            try:
                self._assign_with_reclaim(i, min(need, self.max_seq),
                                          pages)
            except PoolExhausted:
                # backpressure, not failure: the request keeps its place
                # at the queue front and is re-taken as pages recycle
                self._batcher.requeue(p)
                return
            free.pop(0)
            slot = _Slot(p, self._steps, seed=self._seed_seq)
            self._seed_seq += 1
            # a prefix hit: the matched pages already hold these tokens'
            # KV, so chunked prefill starts past them (consumed counts
            # prompt tokens already written)
            slot.consumed = matched
            self._slots[i] = slot
            if self.prefix is not None:
                self.metrics.observe_prefix(matched)
            if slot.admit_wait_steps > self._admit_wait_max:
                self._admit_wait_max = slot.admit_wait_steps

    def _assign_with_reclaim(self, i, budget, pages):
        """``assign_with_prefix`` with one eviction retry: on pool
        pressure the trie reclaims LRU cached prefixes (never the pages
        just matched, never pages a live slot references) before the
        PoolExhausted surfaces as backpressure."""
        try:
            return self.pool.assign_with_prefix(i, budget, pages)
        except PoolExhausted:
            if self.prefix is None:
                raise
            shortfall = (self.pool.pages_for(budget) - len(pages)
                         - self.pool.pages_free)
            if self.prefix.reclaim(max(shortfall, 1), exclude=pages) == 0:
                raise
            return self.pool.assign_with_prefix(i, budget, pages)

    def _run_step(self, tokens, start_pos, last_idx, table):
        from .. import numpy as mnp

        toks = mnp.array(_onp.asarray(tokens, _onp.int32))
        sp = mnp.array(_onp.asarray(start_pos, _onp.int32))
        li = mnp.array(_onp.asarray(last_idx, _onp.int32))
        tab = mnp.array(_onp.asarray(table, _onp.int32))
        if not self._fused_paged:
            # strict rung: paging brackets as standalone exact-copy ops
            # around the unchanged ring executable (bitwise contract)
            rings = [_ops.paged_kv_gather(p, tab)
                     for p in self.pool.flat()]
            out = self.session.run(toks, sp, li, *rings, *self._qflat)
            t_len = _onp.asarray(tokens).shape[1]
            self.pool.update_from_flat([
                _ops.paged_kv_scatter(p, tab, r, sp, t_len)
                for p, r in zip(self.pool.flat(), out[1:])])
            return out[0]
        out = self.session.run(toks, sp, li, tab,
                               *self.pool.flat(), *self._qflat)
        self.pool.update_from_flat(out[1:])
        return out[0]

    def _prefill_once(self):
        """Advance ONE prefilling slot by one chunk (round-robin), at the
        fixed (1, chunk) signature. The final chunk samples the first
        token — that's the request's TTFT."""
        waiting = [i for i, s in enumerate(self._slots)
                   if s is not None and not s.decoding and not s.finished]
        if not waiting:
            return
        i = min(waiting, key=lambda j: (j - self._pf_next) % self.num_slots)
        self._pf_next = (i + 1) % self.num_slots
        s = self._slots[i]
        chunk = self.prefill_chunk
        piece = s.prompt[s.consumed:s.consumed + chunk]
        n = len(piece)
        toks = _onp.full((1, chunk), self.pad_id, _onp.int32)
        toks[0, :n] = piece
        table = _onp.zeros((1, self.pool.pages_per_slot), _onp.int32)
        table[0] = self.pool.table()[i]
        try:
            pf_args = {"slot": i, "n": n}
            with _attr.phase_scope("prefill"):
                p0_ns = time.perf_counter_ns()
                try:
                    logits = self._run_step(toks, [s.consumed], [n - 1],
                                            table)
                except Exception as e:
                    pf_args["error"] = type(e).__name__
                    raise
                finally:
                    self._span_fanout("serve::prefill_chunk", p0_ns,
                                      time.perf_counter_ns(), pf_args,
                                      (i,))
        except Exception as exc:  # pylint: disable=broad-except
            # only THIS slot was inside the failing call
            self._settle_slot(i, error=exc)
            return
        s.consumed += n
        if s.consumed < len(s.prompt):
            return
        # prompt fully written: sample the first token off the last real
        # position's logits (exactly Generator._generate's step-0 sample)
        s.decoding = True
        s.pos = len(s.prompt)
        tid = int(sample_tokens(logits, temperature=s.temperature,
                                top_k=s.top_k)[0])
        s.ttft_ms = (time.monotonic() - s.p.t_enq) * 1e3
        self.metrics.observe_ttft(s.ttft_ms, s.p.priority)
        s.emit(tid)

    def _decode_once(self):
        """One fixed-width decode step over every decoding slot. Slots
        that are empty or still prefilling ride along as dead lanes:
        all-null page-table rows route their writes to the null page
        (re-zeroed in the scatter op), so they can neither corrupt live
        state nor feed garbage back to themselves."""
        decoding = [i for i, s in enumerate(self._slots)
                    if s is not None and s.decoding and not s.finished]
        if not decoding:
            # idle gap, not a stall: no live token stream is waiting, so
            # the next decode step's ITL restarts from its own window
            self._last_emit_t = None
            return
        _faults.fault_point("serve:decode",
                            {"session": self.session.name})
        t_build = time.perf_counter()
        S = self.num_slots
        toks = _onp.zeros((S, 1), _onp.int32)
        pos = _onp.zeros(S, _onp.int32)
        table = _onp.zeros((S, self.pool.pages_per_slot), _onp.int32)
        live_table = self.pool.table()
        for i in decoding:
            s = self._slots[i]
            toks[i, 0] = s.pending
            pos[i] = s.pos
            table[i] = live_table[i]
        temps = [self._slots[i].temperature for i in decoding]
        # the iteration's four-way attribution (host/dispatch/device/
        # wait partitions the span wall exactly; the pre-span input
        # assembly above lands in the ledger's schedule bucket): the
        # span covers dispatch, the blocking logits fetch (the ONE
        # sanctioned device sync — that delta is the device phase), and
        # the host-side sampling/emit bookkeeping
        attributing = _attr.ENABLED
        args = {"live": len(decoding)}
        with _attr.phase_scope("decode"):
            t1 = time.perf_counter()
            w1 = _attr.thread_wait_ns() if attributing else 0
            s0_ns = time.perf_counter_ns()
            try:
                logits = self._run_step(toks, pos,
                                        _onp.zeros(S, _onp.int32), table)
                t2 = time.perf_counter()
                w2 = _attr.thread_wait_ns() if attributing else 0
                if all(t is None or t <= 0.0 for t in temps):
                    # one greedy argmax for all rows; blocks on device
                    ids = sample_tokens(logits)
                    t3 = time.perf_counter()
                    w3 = _attr.thread_wait_ns() if attributing else 0
                    sampled = {i: int(ids[i]) for i in decoding}
                else:
                    arr = logits.asnumpy()  # blocking device fetch
                    t3 = time.perf_counter()
                    w3 = _attr.thread_wait_ns() if attributing else 0
                    sampled = {}
                    for i in decoding:
                        s = self._slots[i]
                        sampled[i] = int(sample_tokens(
                            arr[i:i + 1], temperature=s.temperature,
                            top_k=s.top_k)[0])
                for i in decoding:
                    s = self._slots[i]
                    s.pos += 1
                    s.decode_steps += 1
                    s.emit(sampled[i])
                if attributing:
                    t4 = time.perf_counter()
                    w4 = _attr.thread_wait_ns()
                    dispatch_ms = max(
                        0.0, (t2 - t1) * 1e3 - (w2 - w1) / 1e6)
                    device_ms = (t3 - t2) * 1e3
                    host_ms = max(
                        0.0, (t4 - t3) * 1e3 - (w4 - w3) / 1e6)
                    wait_ms = max(0.0, ((w2 - w1) + (w4 - w3)) / 1e6)
                    args.update(host_ms=round(host_ms, 4),
                                dispatch_ms=round(dispatch_ms, 4),
                                device_ms=round(device_ms, 4),
                                wait_ms=round(wait_ms, 4))
                    self.ledger.observe_step(host_ms, dispatch_ms,
                                             device_ms, wait_ms,
                                             live=len(decoding))
                    self.ledger.observe_schedule((t1 - t_build) * 1e3)
            except Exception as e:
                args["error"] = type(e).__name__
                raise
            finally:
                self._span_fanout("serve::decode_step", s0_ns,
                                  time.perf_counter_ns(), args, decoding)
        # ITL is the token-to-token gap, not just the device window: in
        # steady state it runs from the PREVIOUS step's emission, so
        # scheduler stalls between steps (admissions, prefill chunks, an
        # injected serve:decode delay) land in the stream-stall number
        # the SLO monitor judges. First step after idle has no waiting
        # stream; it falls back to its own decode window.
        prev = self._last_emit_t
        self._last_emit_t = t3
        itl_start = prev if prev is not None else t1
        self.metrics.observe_itl((t3 - itl_start) * 1e3,
                                 live=len(decoding))

    def _run_multi(self, toks, pos, table, limit, remaining, seeds,
                   temps, top_ks, stops):
        """Dispatch one super-step over the full slot lattice; returns
        ``(block, valid, done, t_dispatch, w_dispatch)`` — the stamp pair
        is taken right after the executable call returns (dispatch done,
        device still running) so :meth:`_decode_multi` can split
        dispatch from device time like :meth:`_decode_once` does."""
        from .. import numpy as mnp

        args = [
            mnp.array(_onp.asarray(toks, _onp.int32)),
            mnp.array(_onp.asarray(pos, _onp.int32)),
            mnp.array(_onp.asarray([limit], _onp.int32)),
            mnp.array(_onp.asarray(remaining, _onp.int32)),
            mnp.array(_onp.asarray(seeds, _onp.int32)),
            mnp.array(_onp.asarray(temps, _onp.float32)),
            mnp.array(_onp.asarray(top_ks, _onp.int32)),
            mnp.array(_onp.asarray(stops, _onp.int32)),
            mnp.array(_onp.asarray(self._key_bits, _onp.uint32)),
            mnp.array(_onp.asarray(table, _onp.int32)),
        ]
        out = self._msession.run(*args, *self.pool.flat(), *self._qflat)
        t2 = time.perf_counter()
        w2 = _attr.thread_wait_ns()
        self.pool.update_from_flat(out[3:])
        block = _onp.asarray(out[0].asnumpy(), _onp.int32)
        valid = _onp.asarray(out[1].asnumpy(), _onp.int32)
        done = _onp.asarray(out[2].asnumpy(), _onp.int32)
        return block, valid, done, t2, w2

    def _steps_limit(self, decoding):
        """The next super-step's iteration ceiling: N, degraded to 1
        when some live row's deadline could not survive a full
        N-iteration block (per-iteration EMA estimate), so 504
        retirement latency stays bounded by about one iteration —
        through the SAME executable (``steps_limit`` is traced)."""
        n = self.decode_steps
        if self._itl_est is None:
            return n
        now = time.monotonic()
        slack = min((self._slots[i].p.deadline - now for i in decoding
                     if self._slots[i].p.deadline is not None),
                    default=None)
        if slack is not None and slack < self._itl_est * n:
            return 1
        return n

    def _decode_multi(self):
        """One super-step over every decoding slot: up to
        ``decode_steps`` decode iterations inside the compiled loop,
        settled host-side in one pass by replaying :meth:`_Slot.emit`
        over each lane's valid token run. Dead/prefilling lanes ride
        along with ``remaining=0`` — device-side done from iteration 0,
        writes routed to the (re-zeroed) null page. When every lane is
        done the loop exits on-device, so an almost-finished lattice
        never burns N full iterations."""
        decoding = [i for i, s in enumerate(self._slots)
                    if s is not None and s.decoding and not s.finished]
        if not decoding:
            self._last_emit_t = None
            return
        _faults.fault_point("serve:decode",
                            {"session": self._msession.name})
        t_build = time.perf_counter()
        S = self.num_slots
        toks = _onp.zeros((S, 1), _onp.int32)
        pos = _onp.zeros(S, _onp.int32)
        remaining = _onp.zeros(S, _onp.int32)
        seeds = _onp.zeros(S, _onp.int32)
        temps = _onp.zeros(S, _onp.float32)
        tks = _onp.zeros(S, _onp.int32)
        table = _onp.zeros((S, self.pool.pages_per_slot), _onp.int32)
        live_table = self.pool.table()
        stop_sets = [frozenset()] * S
        for i in decoding:
            s = self._slots[i]
            toks[i, 0] = s.pending
            pos[i] = s.pos
            remaining[i] = s.max_new - len(s.tokens)
            seeds[i] = s.seed
            temps[i] = (s.temperature if s.temperature is not None
                        and s.temperature > 0.0 else 0.0)
            tks[i] = int(s.top_k) if s.top_k else 0
            table[i] = live_table[i]
            stop_sets[i] = s.stop
        stops = _stop_matrix(S, stop_sets)
        limit = self._steps_limit(decoding)
        attributing = _attr.ENABLED
        args = {"live": len(decoding), "steps": limit}
        with _attr.phase_scope("decode"):
            t1 = time.perf_counter()
            w1 = _attr.thread_wait_ns() if attributing else 0
            s0_ns = time.perf_counter_ns()
            try:
                block, valid, _done, t2, w2 = self._run_multi(
                    toks, pos, table, limit, remaining, seeds, temps,
                    tks, stops)
                t3 = time.perf_counter()
                w3 = _attr.thread_wait_ns() if attributing else 0
                # host settle: replay emit over each lane's token run —
                # the host stays the source of truth for stop/budget
                # (device done only bounds the iteration count)
                n_tok = 0
                steps_run = 0
                for i in decoding:
                    s = self._slots[i]
                    k = int(valid[i])
                    n_tok += k
                    if k > steps_run:
                        steps_run = k
                    s.pos += k
                    s.decode_steps += k
                    for j in range(k):
                        s.emit(int(block[i, j]))
                        if s.finished:
                            break
                if attributing:
                    t4 = time.perf_counter()
                    w4 = _attr.thread_wait_ns()
                    dispatch_ms = max(
                        0.0, (t2 - t1) * 1e3 - (w2 - w1) / 1e6)
                    device_ms = (t3 - t2) * 1e3
                    host_ms = max(
                        0.0, (t4 - t3) * 1e3 - (w4 - w3) / 1e6)
                    wait_ms = max(0.0, ((w2 - w1) + (w4 - w3)) / 1e6)
                    args.update(host_ms=round(host_ms, 4),
                                dispatch_ms=round(dispatch_ms, 4),
                                device_ms=round(device_ms, 4),
                                wait_ms=round(wait_ms, 4),
                                tokens=n_tok)
                    self.ledger.observe_step(host_ms, dispatch_ms,
                                             device_ms, wait_ms,
                                             live=len(decoding),
                                             tokens=n_tok)
                    self.ledger.observe_schedule((t1 - t_build) * 1e3)
            except Exception as e:
                args["error"] = type(e).__name__
                raise
            finally:
                self._span_fanout("serve::decode_step", s0_ns,
                                  time.perf_counter_ns(), args, decoding)
        prev = self._last_emit_t
        self._last_emit_t = t3
        itl_start = prev if prev is not None else t1
        if steps_run > 0:
            # the visit's wall amortizes over the iterations it ran —
            # k tokens means k consumer-visible gaps, not one giant one
            self.metrics.observe_itl((t3 - itl_start) * 1e3,
                                     live=len(decoding),
                                     tokens=steps_run)
            est = (t3 - t1) / steps_run
            self._itl_est = (est if self._itl_est is None
                             else 0.5 * self._itl_est + 0.5 * est)

    def _span_fanout(self, name, t0_ns, t1_ns, args, slot_idx):
        """Record one span into every listed slot's request trace — an
        iteration-level step is on EACH rider's critical path, and the
        engine thread has no ambient request trace to catch ``span()``
        — plus the ambient trace when one IS active (inline ``step()``
        under an activated trace), never duplicating a target."""
        targets = []
        amb = _trace.current()
        if amb is not None:
            targets.append(amb)
        for i in slot_idx:
            s = self._slots[i]
            tr = s.p.trace if s is not None else None
            if tr is not None and tr not in targets:
                targets.append(tr)
        for tr in targets:
            tr.span_at(name, t0_ns, t1_ns, args)

    def step(self):
        """One scheduler iteration: retire -> admit -> one prefill chunk
        -> one decode step -> gauges. Execution failures (an injected
        ``serve:execute``/``serve:decode`` fault, a watchdog timeout)
        fail the requests that were inside the failing call — the
        scheduler itself keeps serving, exactly like the batcher's
        batch-failure isolation."""
        t0 = time.perf_counter()
        self._retire()
        self._admit()
        if _attr.ENABLED:
            # host-schedule: the admit/retire bookkeeping between
            # device calls — ROADMAP item 3's kill target
            self.ledger.observe_schedule((time.perf_counter() - t0) * 1e3)
        self._prefill_once()
        try:
            if self._multistep:
                self._decode_multi()
            else:
                self._decode_once()
        except Exception as exc:  # pylint: disable=broad-except
            for i, s in enumerate(self._slots):
                if s is not None and s.decoding:
                    self._settle_slot(i, error=exc)
        self._steps += 1
        self.metrics.set_kv_pages(self.pool.pages_used,
                                  self.pool.pages_free)
        self.metrics.set_slot_occupancy(len(self._live()), self.num_slots)
        if _attr.ENABLED:
            self.metrics.set_attribution(
                self.ledger.host_overhead_fraction(),
                self.ledger.device_ms_per_token())
        if self.prefix is not None:
            self.metrics.set_prefix_gauges(self.pool.pages_shared,
                                           self.prefix.pages_held,
                                           self.prefix.evictions)

    def _idle(self):
        return not self._live() and self._batcher.queue_depth() == 0

    def _run_loop(self):
        from ..profiler import core as _prof

        _prof.register_thread_name()
        while not self._stop.is_set():
            if self._idle():
                with self._batcher._cond:
                    if not self._batcher._queue and not self._stop.is_set():
                        self._batcher._cond.wait(0.05)
                continue
            self.step()

    # -- lifecycle -----------------------------------------------------------
    def warmup(self):
        """Compile BOTH live signatures and freeze the set: one
        (1, chunk) prefill chunk plus — classic mode — one
        (num_slots, 1) decode step, or — multistep mode — one
        (num_slots,) super-step (the classic decode signature is never
        compiled there; the super-step IS the decode executable). Every
        later admit/retire/prefill/decode replays one of these two
        executables (``assert_no_recompiles`` is the test)."""
        t0 = time.perf_counter()
        n = self.pool.pages_per_slot
        S = self.num_slots
        self._run_step(
            _onp.zeros((1, self.prefill_chunk), _onp.int32), [0], [0],
            _onp.zeros((1, n), _onp.int32))
        if self._multistep:
            # remaining=0: zero runtime iterations, full trace/compile
            self._run_multi(
                _onp.zeros((S, 1), _onp.int32), _onp.zeros(S, _onp.int32),
                _onp.zeros((S, n), _onp.int32), self.decode_steps,
                _onp.zeros(S, _onp.int32), _onp.zeros(S, _onp.int32),
                _onp.zeros(S, _onp.float32), _onp.zeros(S, _onp.int32),
                _onp.full((S, _STOP_WIDTH), -1, _onp.int32))
            self._msession.freeze_signatures()
        else:
            self._run_step(
                _onp.zeros((S, 1), _onp.int32),
                _onp.zeros(S, _onp.int32),
                _onp.zeros(S, _onp.int32),
                _onp.zeros((S, n), _onp.int32))
        self.session.freeze_signatures()
        sigs = self.session.signature_count()
        if self._msession is not None:
            sigs += self._msession.signature_count()
        return {"signatures": sigs,
                "wall_s": time.perf_counter() - t0}

    def start(self):
        """Warm up (if not already) and start the scheduler thread."""
        if self.session._warm_signatures is None:
            self.warmup()
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run_loop, daemon=True,
            name=f"mxtpu-serve-scheduler[{self.session.name}]")
        self._thread.start()

    def close(self, timeout=5.0):
        """Stop the scheduler thread, fail live slots and queued work
        with 503 (the batcher's close taxonomy), release every page."""
        self._stop.set()
        with self._batcher._cond:
            self._batcher._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        for i, s in enumerate(self._slots):
            if s is not None:
                self._settle_slot(i, error=ServiceUnavailable(
                    f"continuous engine {self.session.name!r} shut down "
                    f"mid-request ({len(s.tokens)} tokens generated)"))
        self._batcher.close(timeout)

    def drain(self, timeout=30.0):
        """Stop admission and wait until every admitted request settles
        (queue empty AND all slots retired). :meth:`resume` reopens."""
        return self._batcher.drain(timeout)

    def resume(self):
        self._batcher.resume()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.close()
        return False

    # -- invariants / readout ------------------------------------------------
    def assert_no_recompiles(self):
        self.session.assert_no_recompiles()
        if self._msession is not None:
            self._msession.assert_no_recompiles()

    def stats(self):
        out = self.session.stats()
        out["pool"] = self.pool.stats()
        out["steps"] = self._steps
        if self._msession is not None:
            out["multistep"] = self._msession.stats()
            out["decode_steps"] = self.decode_steps
        out["slots_live"] = len(self._live())
        out["slots_total"] = self.num_slots
        out["admit_wait_steps_max"] = self._admit_wait_max
        out["queue_depth"] = self._batcher.queue_depth()
        out["duplicate_submits"] = self._batcher.duplicate_submits
        if self.prefix is not None:
            out["prefix"] = self.prefix.stats()
        return out
