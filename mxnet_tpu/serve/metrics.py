"""Serving SLO metrics: latency percentiles, queue depth, batch occupancy,
tokens/s.

One :class:`ServeMetrics` instance rides along with each serving component
(session, batcher, generator — they can share one). Observations land in
bounded rings (``MXNET_SERVE_METRICS_WINDOW`` samples) so a long-lived
server's snapshot cost stays flat, and every observation also emits a
``serve::*`` event through the profiler bus (``mxnet_tpu.profiler``) when
it is recording — the same chrome-trace/aggregate pipeline the training
stack uses, so a serve trace and a train trace read the same way.
"""
from __future__ import annotations

import collections
import math
import threading
import weakref

from ..profiler import core as _prof
from ..profiler import recorder as _recorder

# live ServeMetrics instances, for the process-wide all_snapshots()
# aggregate (profiler.export pulls it); weak so the registry never pins
# a retired server's accumulator
_instances: "weakref.WeakSet" = weakref.WeakSet()


def all_snapshots():
    """``{instance_name: snapshot()}`` over every live ServeMetrics.
    Same-named instances merge last-writer-wins (deployments that share
    one accumulator across session+batcher see exactly one entry)."""
    return {m.name: m.snapshot() for m in list(_instances)}


def percentile(samples, pct):
    """Nearest-rank percentile of an unsorted sequence (0 < pct <= 100):
    the smallest sample such that at least ``pct`` percent of the window
    is <= it, i.e. rank ``ceil(pct/100 * n)`` (1-based). ``round()`` would
    banker's-round even-window ranks off by one (p50 of ``[1, 2]`` must
    be 1, not 2). Returns 0.0 on no samples — a dashboard-friendly zero,
    not a crash."""
    if not samples:
        return 0.0
    s = sorted(samples)
    rank = max(1, min(len(s), int(math.ceil(pct / 100.0 * len(s)))))
    return s[rank - 1]


class ServeMetrics:
    """Thread-safe serving telemetry accumulator."""

    def __init__(self, name="serve", window=None):
        if window is None:
            from .. import config

            window = config.get("MXNET_SERVE_METRICS_WINDOW")
        self.name = name
        self._window = int(window)
        self._lock = threading.Lock()
        self._latency_ms = collections.deque(maxlen=int(window))
        self._queue_ms = collections.deque(maxlen=int(window))
        self._exec_ms = collections.deque(maxlen=int(window))
        # per-priority-class latency rings, materialized on first use so a
        # priority-free deployment's snapshot stays byte-identical
        self._class_lat = {}
        self.requests = 0
        self.errors = 0
        self.rejects = 0
        self.batches = 0
        self._batch_size_sum = 0
        self._occupancy_sum = 0.0
        self.tokens = 0
        self._token_time_s = 0.0
        self.queue_depth = 0  # gauge, written by the batcher
        # overload-safety counters (tentpole: deadline + shed + drain)
        self.sheds = collections.Counter()             # priority -> count
        self.deadline_expired = collections.Counter()  # stage -> count
        self.goodput = 0          # ok completions inside their deadline
        self.late_completions = 0  # delivered past deadline (inside grace)
        self.rate_limited = 0
        self.swaps = 0
        # decode-rung gauges (tentpole PR 10): footprint of the pooled KV
        # rings and which decode path this generator traced
        self.kv_cache_bytes = 0
        self.decode_path = None
        # continuous-batching telemetry (tentpole PR 12): streaming SLOs
        # (time-to-first-token, inter-token latency) plus the paged-KV and
        # slot-occupancy gauges the scheduler publishes between steps
        self._ttft_ms = collections.deque(maxlen=int(window))
        self._itl_ms = collections.deque(maxlen=int(window))
        self._itl_live = collections.deque(maxlen=int(window))
        self.kv_pages_used = 0
        self.kv_pages_free = 0
        self.slots_live = 0
        self.slots_total = 0
        # prefix-cache telemetry (tentpole PR 14): cross-request KV reuse
        # through the radix trie over the paged pool
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_tokens_skipped = 0
        self.prefix_pages_shared = 0
        self.prefix_pages_held = 0
        self.prefix_evictions = 0
        # attribution gauges (tentpole PR 16): the scheduler publishes
        # its Ledger's steady-state readout here so it rides the
        # serve.<name>.* export surface
        self.host_overhead_fraction = 0.0
        self.device_ms_per_token = 0.0
        # optional SLO burn-rate monitor (profiler.slo.SLOMonitor
        # .attach()); None keeps every observation at one branch
        self.slo = None
        _instances.add(self)

    # -- observations -------------------------------------------------------
    def observe_request(self, queue_ms=0.0, exec_ms=0.0, ok=True,
                        priority=None, deadline_ok=True):
        """One request completed (or failed after admission).
        ``priority`` feeds the per-class percentile rings; ``deadline_ok``
        False marks a completion that was delivered late (inside grace) —
        it counts against goodput."""
        total = queue_ms + exec_ms
        with self._lock:
            self.requests += 1
            if not ok:
                self.errors += 1
            elif deadline_ok:
                self.goodput += 1
            else:
                self.late_completions += 1
            self._latency_ms.append(total)
            self._queue_ms.append(queue_ms)
            self._exec_ms.append(exec_ms)
            if priority is not None:
                ring = self._class_lat.get(priority)
                if ring is None:
                    ring = self._class_lat.setdefault(
                        priority,
                        collections.deque(maxlen=self._window))
                ring.append(total)
        slo = self.slo
        if slo is not None:
            slo.observe("completion", ok=ok, deadline_ok=deadline_ok)
        if _prof.ENABLED:
            t1 = _prof.begin()
            _prof.record_duration(f"serve::request({self.name})", "serve",
                                  t1 - int(total * 1e6), t1,
                                  args={"queue_ms": round(queue_ms, 3),
                                        "exec_ms": round(exec_ms, 3),
                                        "ok": bool(ok)})

    def observe_batch(self, size, capacity):
        """One batch dispatched: ``size`` live requests padded into a
        ``capacity``-slot bucket (occupancy = size/capacity)."""
        occ = size / capacity if capacity else 0.0
        with self._lock:
            self.batches += 1
            self._batch_size_sum += size
            self._occupancy_sum += occ
        if _prof.ENABLED:
            _prof.record_instant(f"serve::batch({self.name})", "serve",
                                 args={"size": size, "capacity": capacity,
                                       "occupancy": round(occ, 3)})

    def observe_reject(self):
        """One fast-rejected submission (queue full / breaker open)."""
        with self._lock:
            self.rejects += 1
        _recorder.note("reject", f"serve.reject({self.name})")
        if _prof.ENABLED:
            _prof.record_instant(f"serve::reject({self.name})", "serve")

    def observe_shed(self, priority, reason="pressure"):
        """One request shed by the overload policy (always the lowest
        priority class present — ``reason`` says which mechanism fired:
        ``pressure`` for queue-displacement, ``rate`` for the token
        bucket, ``share`` for the batch-class queue-share cap)."""
        with self._lock:
            self.sheds[priority] += 1
            if reason == "rate":
                self.rate_limited += 1
        _recorder.note("shed", f"serve.shed({self.name})",
                       {"priority": priority, "reason": reason})
        if _prof.ENABLED:
            _prof.record_instant(f"serve::shed({self.name})", "serve",
                                 args={"priority": priority,
                                       "reason": reason})

    def observe_deadline(self, stage, priority=None):
        """One request cancelled at a stage boundary because its deadline
        passed (``admit`` / ``queue`` / ``execute`` / ``decode``)."""
        with self._lock:
            self.deadline_expired[stage] += 1
        _recorder.note("deadline", f"serve.deadline({self.name})",
                       {"stage": stage, "priority": priority})
        if _prof.ENABLED:
            _prof.record_instant(f"serve::deadline({self.name})", "serve",
                                 args={"stage": stage,
                                       "priority": priority})

    def observe_swap(self, mode, wall_s=0.0):
        """One model hot-swap completed (``warm`` = weights transplanted
        into the live executables, ``cold`` = fresh compile)."""
        with self._lock:
            self.swaps += 1
        if _prof.ENABLED:
            _prof.record_instant(f"serve::swap({self.name})", "serve",
                                 args={"mode": mode,
                                       "wall_s": round(wall_s, 3)})

    def observe_tokens(self, n, dt_s):
        """``n`` tokens decoded in ``dt_s`` seconds."""
        with self._lock:
            self.tokens += int(n)
            self._token_time_s += float(dt_s)
        if _prof.ENABLED and dt_s > 0:
            _prof.set_counter(f"serve.tokens_s({self.name})",
                              round(n / dt_s, 1), cat="serve")

    def observe_ttft(self, ms, priority=None):
        """Time-to-first-token for one request: admission to the first
        sampled token (prefill completes). THE interactive-latency SLO
        under continuous batching — admission waits show up here."""
        with self._lock:
            self._ttft_ms.append(float(ms))
        slo = self.slo
        if slo is not None:
            slo.observe("ttft_ms", float(ms))
        if _prof.ENABLED:
            _prof.record_instant(f"serve::ttft({self.name})", "serve",
                                 args={"ms": round(float(ms), 3),
                                       "priority": priority})

    def observe_itl(self, ms, live=1, tokens=1):
        """Inter-token latency: wall time of one decode host visit,
        observed once per visit for every live slot. Its p99 bounds how
        long any request's token stream can stall — including stalls
        caused by other requests' admissions/prefills. ``live`` is the
        step's live-slot count, so attribution can normalize device
        cost by occupancy (a 1-live step and a 16-live step are not the
        same sample).

        ``tokens`` is how many decode iterations the visit ran (1 for
        the classic loop, up to N for a multi-step super-step): a visit
        producing k tokens records k amortized token-to-token gaps of
        ``ms/k`` each, because that is what each consumer-visible gap
        actually was. Recording one giant k-iteration gap instead would
        silently inflate ITL p50/p99 by ~k and trip the SLO burn-rate
        monitor on a healthy server."""
        tokens = max(1, int(tokens))
        gap = float(ms) / tokens
        with self._lock:
            for _ in range(tokens):
                self._itl_ms.append(gap)
                self._itl_live.append(int(live))
        slo = self.slo
        if slo is not None:
            for _ in range(tokens):
                slo.observe("itl_ms", gap)

    def observe_prefix(self, matched_tokens):
        """One admission consulted the prefix trie: ``matched_tokens``
        prompt tokens (a whole number of KV pages) were already cached
        and skip prefill entirely; 0 counts as a miss."""
        with self._lock:
            if matched_tokens > 0:
                self.prefix_hits += 1
                self.prefix_tokens_skipped += int(matched_tokens)
            else:
                self.prefix_misses += 1
        if _prof.ENABLED:
            _prof.record_instant(f"serve::prefix({self.name})", "serve",
                                 args={"matched": int(matched_tokens)})

    def set_prefix_gauges(self, pages_shared, pages_held, evictions):
        """Gauge triple the scheduler publishes between steps: pool pages
        referenced more than once, pages the trie holds, and cumulative
        LRU evictions under pool pressure."""
        self.prefix_pages_shared = int(pages_shared)
        self.prefix_pages_held = int(pages_held)
        self.prefix_evictions = int(evictions)
        if _prof.ENABLED:
            _prof.set_counter(f"serve.prefix_pages_shared({self.name})",
                              int(pages_shared), cat="serve")

    def set_kv_pages(self, used, free):
        """Gauge pair: paged-KV pool occupancy (null page excluded)."""
        self.kv_pages_used = int(used)
        self.kv_pages_free = int(free)
        if _prof.ENABLED:
            _prof.set_counter(f"serve.kv_pages_used({self.name})",
                              int(used), cat="serve")

    def set_slot_occupancy(self, live, total):
        """Gauge pair: decode slots holding a live request vs the
        trace-static slot count."""
        self.slots_live = int(live)
        self.slots_total = int(total)
        if _prof.ENABLED:
            _prof.set_counter(f"serve.slots_live({self.name})",
                              int(live), cat="serve")

    def set_attribution(self, host_overhead_fraction, device_ms_per_token):
        """Gauge pair the attribution ledger publishes between steps:
        the fraction of windowed decode wall NOT spent in the blocking
        device window, and device ms per emitted token (ROADMAP item
        3's acceptance numbers)."""
        self.host_overhead_fraction = float(host_overhead_fraction)
        self.device_ms_per_token = float(device_ms_per_token)
        if _prof.ENABLED:
            _prof.set_counter(
                f"serve.host_overhead_fraction({self.name})",
                round(float(host_overhead_fraction), 4), cat="serve")
            _prof.set_counter(
                f"serve.device_ms_per_token({self.name})",
                round(float(device_ms_per_token), 4), cat="serve")

    def set_queue_depth(self, depth):
        self.queue_depth = int(depth)
        if _prof.ENABLED:
            _prof.set_counter(f"serve.queue_depth({self.name})", int(depth),
                              cat="serve")

    def set_kv_cache_bytes(self, nbytes):
        """Gauge: total bytes of the generator's pooled KV-cache rings
        (``KVCache.nbytes()`` summed over the warm batch buckets)."""
        self.kv_cache_bytes = int(nbytes)
        if _prof.ENABLED:
            _prof.set_counter(f"serve.kv_cache_bytes({self.name})",
                              int(nbytes), cat="serve")

    def set_decode_path(self, path):
        """Gauge: the decode rung this generator compiled
        ("baseline" | "pallas" | "int8")."""
        self.decode_path = str(path)
        if _prof.ENABLED:
            _prof.record_instant(f"serve::decode_path({self.name})", "serve",
                                 args={"path": str(path)})

    # -- readout ------------------------------------------------------------
    def itl_samples(self):
        """Windowed ``(ms, live)`` pairs, oldest first — the raw decode
        iteration record attribution normalizes by occupancy."""
        with self._lock:
            return list(zip(self._itl_ms, self._itl_live))

    def latency_percentiles(self):
        with self._lock:
            lat = list(self._latency_ms)
        return {"p50_ms": percentile(lat, 50), "p95_ms": percentile(lat, 95),
                "p99_ms": percentile(lat, 99)}

    def class_percentiles(self):
        """Per-priority-class latency percentiles: ``{priority: {p50_ms,
        p95_ms, p99_ms, n}}`` — the overload SLO surface (the bound is on
        the *interactive* class, not the blended window)."""
        with self._lock:
            rings = {k: list(v) for k, v in self._class_lat.items()}
        return {k: {"p50_ms": percentile(v, 50),
                    "p95_ms": percentile(v, 95),
                    "p99_ms": percentile(v, 99),
                    "n": len(v)}
                for k, v in rings.items()}

    def snapshot(self):
        """Full SLO readout (the dict SERVING.md documents)."""
        with self._lock:
            lat = list(self._latency_ms)
            q = list(self._queue_ms)
            e = list(self._exec_ms)
            ttft = list(self._ttft_ms)
            itl = list(self._itl_ms)
            itl_live = list(self._itl_live)
            batches = self.batches
            out = {
                "name": self.name,
                "requests": self.requests,
                "errors": self.errors,
                "rejects": self.rejects,
                "batches": batches,
                "queue_depth": self.queue_depth,
                "mean_batch_size": (self._batch_size_sum / batches
                                    if batches else 0.0),
                "batch_occupancy": (self._occupancy_sum / batches
                                    if batches else 0.0),
                "tokens": self.tokens,
                "tokens_s": (self.tokens / self._token_time_s
                             if self._token_time_s > 0 else 0.0),
                "sheds": dict(self.sheds),
                "deadline_expired": dict(self.deadline_expired),
                "goodput": self.goodput,
                "late_completions": self.late_completions,
                "rate_limited": self.rate_limited,
                "swaps": self.swaps,
                "kv_cache_bytes": self.kv_cache_bytes,
                "decode_path": self.decode_path,
                "kv_pages_used": self.kv_pages_used,
                "kv_pages_free": self.kv_pages_free,
                "slots_live": self.slots_live,
                "slots_total": self.slots_total,
                "slot_occupancy": (self.slots_live / self.slots_total
                                   if self.slots_total else 0.0),
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_hit_rate": (
                    self.prefix_hits
                    / (self.prefix_hits + self.prefix_misses)
                    if (self.prefix_hits + self.prefix_misses) else 0.0),
                "prefix_tokens_skipped": self.prefix_tokens_skipped,
                "prefix_pages_shared": self.prefix_pages_shared,
                "prefix_pages_held": self.prefix_pages_held,
                "prefix_evictions": self.prefix_evictions,
                "host_overhead_fraction": self.host_overhead_fraction,
                "device_ms_per_token": self.device_ms_per_token,
            }
        out["ttft_p50_ms"] = percentile(ttft, 50)
        out["ttft_p95_ms"] = percentile(ttft, 95)
        out["ttft_p99_ms"] = percentile(ttft, 99)
        out["itl_p50_ms"] = percentile(itl, 50)
        out["itl_p99_ms"] = percentile(itl, 99)
        out["itl_live_mean"] = (sum(itl_live) / len(itl_live)
                                if itl_live else 0.0)
        out["class_percentiles"] = self.class_percentiles()
        out["p50_ms"] = percentile(lat, 50)
        out["p95_ms"] = percentile(lat, 95)
        out["p99_ms"] = percentile(lat, 99)
        out["queue_p99_ms"] = percentile(q, 99)
        out["exec_p99_ms"] = percentile(e, 99)
        return out
