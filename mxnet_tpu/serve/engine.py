"""`InferenceSession`: bucketed AOT-compiled serving executor.

The serving analog of mxnet-model-server's worker atop ``Module.predict``:
a :class:`~mxnet_tpu.cachedop.CachedOpThreadSafe` wraps the block, and the
session *pads every call onto a small lattice of (batch, seq) buckets* so
steady-state traffic only ever replays already-compiled executables — the
recompile storm that per-request shapes would cause is structurally
impossible, and ``assert_no_recompiles`` turns that into a testable
invariant via ``cachedop.signature_count()``.

Resilience wiring (all existing subsystems, reused):

* cold-bucket compiles go through ``resilience.retry.call_with_retry``
  (the CachedOp build path) — a transient XLA compile failure backs off
  and retries instead of failing the request;
* a :class:`~mxnet_tpu.resilience.retry.CircuitBreaker` guards the
  session: repeated execution failures trip it open and requests
  fast-reject with a 503-style :class:`ServiceUnavailable` until a
  half-open probe heals it;
* ``MXNET_SERVE_TIMEOUT_MS`` bounds each execution with the resilience
  watchdog — a hung executable becomes a fast 503 instead of wedging the
  serving thread;
* the ``serve:execute`` fault site lets the fault-injection harness fail
  individual executions deterministically.
"""
from __future__ import annotations

import threading
import time

import numpy as _onp

from ..base import MXNetError
from ..cachedop import CachedOpThreadSafe
from ..profiler import core as _prof
from ..profiler import export as _export
from ..profiler import trace as _trace
from ..resilience import faults as _faults
from ..resilience.retry import CircuitBreaker, CollectiveTimeoutError, \
    run_with_watchdog
from .metrics import ServeMetrics


class ServeError(MXNetError):
    """Base class for serving-path errors; carries an HTTP-style status."""

    status = 500
    #: back-off hint (ms) for overload-shaped rejects: when set, the
    #: server expects capacity to free up after roughly this long (the
    #: batcher derives it from queue depth x its drain rate), so a client
    #: or router can back off intelligently instead of hammering.
    #: ``None`` on structural failures a retry won't fix (shutdown,
    #: breaker open) — the Router uses exactly this distinction to tell
    #: "loaded replica, pass the 503 through" from "broken replica,
    #: quarantine it".
    retry_after_ms = None


class ServiceUnavailable(ServeError):
    """Fast-reject: queue full, breaker open, or execution timed out (503)."""

    status = 503


class PoolExhausted(ServiceUnavailable):
    """The paged KV block pool has no free pages for a new admission
    (503-shaped: capacity frees as in-flight requests retire and their
    pages recycle). Raised by :class:`~mxnet_tpu.serve.kv_blocks.
    PagedKVPool`; the continuous-batching scheduler catches it at the
    admission boundary and requeues the request — the pool being full is
    backpressure, never a crash."""


class DeadlineExceeded(ServeError):
    """The request's deadline passed before (or while) it was served (504).

    Distinct from :class:`ServiceUnavailable` on purpose: a 503 means the
    *server* shed or failed the request and a retry may succeed; a 504
    means the *request's own time budget* ran out — the client has already
    moved on and a silent late completion would be worse than the error.
    Raised at every stage boundary (admission, queue sweep, post-execute
    settle, between decode steps) so an expired request never burns more
    server time than the stage it is already inside.
    """

    status = 504


def _deterministic_compiler_options():
    """XLA overrides for serving executables. On the CPU backend the
    default thunk runtime partitions fused loops differently per graph
    shape — even the shape-stable mul+reduce ops (``ops.nn.stable_dense``,
    ``cached_attention``) drift a few ulps between the T=1 and T=bucket
    executables under it; pin the legacy runtime, whose codegen is
    shape-stable for those formulations (both pieces are needed: with
    gemm-based Dense the legacy runtime drifts too). Other backends
    compile with their defaults."""
    import jax

    if jax.default_backend() == "cpu":
        return {"xla_cpu_use_thunk_runtime": False}
    return None


def pick_bucket(n, buckets):
    """Smallest bucket >= n; raises :class:`ServeError` when n overflows
    the largest bucket (the request can never be served — reject it
    loudly rather than silently truncating)."""
    for b in buckets:
        if n <= b:
            return b
    raise ServeError(
        f"request size {n} exceeds the largest configured bucket "
        f"{buckets[-1]}; raise the session's bucket lattice or shard the "
        "request")


class InferenceSession:
    """Bucketed, breaker-guarded, AOT-compiled executor for one block.

    Parameters
    ----------
    block : HybridBlock
        The model (parameters must be initialized).
    batch_buckets : sequence of int
        Ascending batch-size lattice; every call's leading axis pads up to
        one of these.
    seq_buckets : sequence of int, optional
        Ascending sequence-length lattice for axis 1 of 2-D+ inputs
        (token arrays). ``None`` disables seq padding.
    pad_value : scalar
        Fill for padded sequence positions (token id 0 by default).
    deterministic : bool
        Compile with the pinned shape-stable runtime options (the PR-5
        bitwise contract; default). ``False`` compiles with the backend's
        default options — the serving fast rungs select this per-CachedOp
        because the pinned CPU legacy runtime is itself a large decode-
        throughput tax.
    """

    def __init__(self, block, batch_buckets=(1, 2, 4, 8), seq_buckets=None,
                 pad_value=0, name=None, deterministic=True):
        from .. import config

        self.block = block
        self.batch_buckets = tuple(sorted(int(b) for b in batch_buckets))
        self.seq_buckets = (tuple(sorted(int(s) for s in seq_buckets))
                            if seq_buckets else None)
        self.pad_value = pad_value
        self.name = name or type(block).__name__
        self.deterministic = bool(deterministic)
        self._op = CachedOpThreadSafe(
            block, compiler_options=(_deterministic_compiler_options()
                                     if self.deterministic else None))
        self.metrics = ServeMetrics(self.name)
        self.breaker = CircuitBreaker(
            failure_threshold=config.get("MXNET_SERVE_BREAKER_THRESHOLD"),
            cooldown_calls=config.get("MXNET_SERVE_BREAKER_COOLDOWN"),
            name=f"serve:{self.name}")
        self._warm_signatures = None
        self._shapes_ready = False
        self._lock = threading.Lock()
        # drain/swap lifecycle: _quiesce guards the in-flight count;
        # drain() flips _draining and waits for it to reach zero. The
        # thread-local bypass lets swap()'s own warmup run while external
        # admission is still stopped.
        self._quiesce = threading.Condition()
        self._inflight = 0
        self._draining = False
        self._bypass = threading.local()
        # unified export surface: /healthz wraps this session's probes
        _export.register_health_provider(self)

    # -- raw protected execution -------------------------------------------
    def _timeout_s(self):
        from .. import config

        return config.get("MXNET_SERVE_TIMEOUT_MS") / 1e3

    def run(self, *args):
        """Execute one already-bucketed call under the full protection
        stack (breaker -> fault site -> watchdog -> cachedop). Raises
        :class:`ServiceUnavailable` on breaker-open or timeout; any other
        failure propagates unchanged (the batcher maps it onto the
        requests of the affected batch)."""
        from .. import autograd

        with self._quiesce:
            if self._draining and not getattr(self._bypass, "on", False):
                self.metrics.observe_reject()
                raise ServiceUnavailable(
                    f"serve session {self.name!r} is draining; no new "
                    "work admitted until swap/resume")
            self._inflight += 1
        try:
            if not self._shapes_ready:
                # complete any deferred (shape-inferred) parameter init
                # with one eager pass — CachedOp keys on param shapes,
                # which don't exist yet for in_units=0 Dense until a
                # first forward. Inside the admission gate + in-flight
                # count on purpose: this pass executes the model, and a
                # concurrent swap() must not see "quiesced" while it runs
                with self._lock:
                    if not self._shapes_ready:
                        params = self.block.collect_params().values()
                        if any(getattr(p, "_deferred_init", None)
                               is not None and p._data is None
                               for p in params):
                            with autograd.predict_mode():
                                self.block(*args)
                        self._shapes_ready = True
            if not self.breaker.allow():
                self.metrics.observe_reject()
                raise ServiceUnavailable(
                    f"serve session {self.name!r}: circuit breaker is "
                    f"{self.breaker.state} after repeated execution "
                    "failures; retry after cooldown")
            self._op.begin_serve_call()
            t0 = time.perf_counter()
            try:
                def body():
                    # fault site INSIDE the watchdog window: an injected
                    # delay models a hung execution and must trip the
                    # timeout
                    _faults.fault_point("serve:execute",
                                        {"session": self.name})
                    with autograd.predict_mode():
                        return self._op(*args)

                # ambient-trace span: when the batcher activated a
                # request trace on this thread, the session execution
                # shows up inside that request's lane
                with _trace.span(f"serve::session_run({self.name})"):
                    out = run_with_watchdog(body, self._timeout_s(),
                                            site=f"serve:{self.name}")
            except CollectiveTimeoutError as exc:
                self.breaker.record_failure()
                raise ServiceUnavailable(
                    f"serve session {self.name!r}: execution exceeded "
                    f"MXNET_SERVE_TIMEOUT_MS ({exc})") from exc
            except Exception:
                self.breaker.record_failure()
                raise
            self.breaker.record_success()
            exec_ms = (time.perf_counter() - t0) * 1e3
            if self._op.call_was_warm():
                # warm-path call: every signature it touched was already
                # compiled — the steady-state serving invariant. Tracked
                # per-thread, so a concurrent thread's cold compile can't
                # misattribute this call
                self._op.record_serve_hit()
            if _prof.ENABLED:
                _prof.record_instant(f"serve::execute({self.name})",
                                     "serve",
                                     args={"exec_ms": round(exec_ms, 3)})
            return out
        finally:
            with self._quiesce:
                self._inflight -= 1
                if self._inflight == 0:
                    self._quiesce.notify_all()

    # -- bucketed predict ---------------------------------------------------
    def _pad_input(self, data):
        """Pad a host array onto the bucket lattice. Returns
        (padded_ndarray, real_batch, real_seq)."""
        from .. import numpy as mnp
        from ..ndarray.ndarray import NDArray

        if isinstance(data, NDArray):
            data = data.asnumpy()
        data = _onp.asarray(data)
        b = data.shape[0]
        bb = pick_bucket(b, self.batch_buckets)
        padded = data
        if bb > b:
            # batch rows pad by edge-repeat (a real row: no NaN/denormal
            # surprises in the dead lanes)
            padded = _onp.pad(padded,
                              [(0, bb - b)] + [(0, 0)] * (data.ndim - 1),
                              mode="edge")
        t = None
        if self.seq_buckets is not None and data.ndim > 1:
            t = data.shape[1]
            st = pick_bucket(t, self.seq_buckets)
            if st > t:  # seq positions pad with pad_value
                seq_w = [(0, 0), (0, st - t)] + [(0, 0)] * (data.ndim - 2)
                padded = _onp.pad(padded, seq_w, mode="constant",
                                  constant_values=self.pad_value)
        return mnp.array(padded), b, t

    def predict(self, data):
        """Serve one request batch: pad onto the bucket lattice, execute,
        slice the outputs back to the real request shape — the batch
        axis always, and the seq axis of any output that preserved the
        padded seq extent (positions past the real length are pad-token
        artifacts, not model output)."""
        padded, b, t = self._pad_input(data)
        st = padded.shape[1] if padded.ndim > 1 else None
        out = self.run(padded)

        def unpad(o):
            o = o[:b]
            if t is not None and t != st and o.ndim >= 2 \
                    and o.shape[1] == st:
                o = o[:, :t]
            return o

        if isinstance(out, (tuple, list)):
            return type(out)(unpad(o) for o in out)
        return unpad(out)

    def __call__(self, data):
        return self.predict(data)

    # -- warmup & recompile accounting --------------------------------------
    def warmup(self, example):
        """Compile every (batch, seq) bucket combination from one example
        input (an array shaped like a single request batch). After this,
        any request within the lattice executes with zero compiles."""
        from ..ndarray.ndarray import NDArray

        if isinstance(example, NDArray):
            example = example.asnumpy()
        example = _onp.asarray(example)
        row = example[:1]
        t0 = time.perf_counter()
        for bb in self.batch_buckets:
            tiled = _onp.repeat(row, bb, axis=0)
            if self.seq_buckets is not None and example.ndim > 1:
                for st in self.seq_buckets:
                    self.predict(_resize_seq(tiled, st, self.pad_value))
            else:
                self.predict(tiled)
        self.freeze_signatures()
        if _prof.ENABLED:
            _prof.record_instant(
                f"serve::warmup({self.name})", "serve",
                args={"signatures": self._op.signature_count(),
                      "wall_s": round(time.perf_counter() - t0, 3)})
        return self._op.signature_count()

    def freeze_signatures(self):
        """Mark the current signature set as the warm set for
        :meth:`assert_no_recompiles`."""
        self._warm_signatures = self._op.signature_count()

    def assert_no_recompiles(self):
        """Raise :class:`ServeError` if any compile happened since
        :meth:`freeze_signatures` / :meth:`warmup` — the steady-state
        serving invariant, checked from ``cachedop.signature_count()``."""
        if self._warm_signatures is None:
            raise ServeError("assert_no_recompiles called before warmup()")
        now = self._op.signature_count()
        if now != self._warm_signatures:
            raise ServeError(
                f"serve session {self.name!r} recompiled after warmup: "
                f"{self._warm_signatures} -> {now} signatures "
                f"(bucket keys: {self._op.bucket_keys()!r})")

    def signature_count(self):
        return self._op.signature_count()

    def cache_stats(self):
        return self._op.cache_stats()

    def stats(self):
        """Combined serving snapshot: metrics + executable cache + breaker
        + watchdog-orphan accounting (abandoned execution bodies that may
        still be running — see resilience.retry.watchdog_orphans)."""
        from ..resilience.retry import watchdog_orphans

        out = self.metrics.snapshot()
        out["cache"] = self.cache_stats()
        out["breaker"] = self.breaker.snapshot()
        out["watchdog_orphans"] = watchdog_orphans()
        return out

    # -- drain / hot swap / health -------------------------------------------
    def drain(self, timeout=30.0):
        """Stop admitting work and wait for every in-flight execution to
        settle. Returns True once quiesced, False on timeout (admission
        stays stopped either way — call :meth:`resume` to reopen, or
        :meth:`swap` which resumes itself). Idempotent."""
        deadline = time.monotonic() + float(timeout)
        with self._quiesce:
            self._draining = True
            while self._inflight > 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._quiesce.wait(remaining)
        if _prof.ENABLED:
            _prof.record_instant(f"serve::drain({self.name})", "serve")
        return True

    def resume(self):
        """Reopen admission after :meth:`drain`."""
        with self._quiesce:
            self._draining = False
            self._quiesce.notify_all()

    def _signature_matches(self, new_block):
        """True when ``new_block``'s parameter lattice (count, shapes,
        dtypes, grad_req, in order) is identical to the serving block's —
        the condition under which the warm executables, which read param
        buffers at call time and key on param signatures, serve the new
        weights without a single recompile."""
        try:
            olds = list(self.block.collect_params().values())
            news = list(new_block.collect_params().values())
            if len(olds) != len(news):
                return False
            for po, pn in zip(olds, news):
                do, dn = po.data(), pn.data()
                if (tuple(do.shape) != tuple(dn.shape)
                        or do.dtype != dn.dtype
                        or po.grad_req != pn.grad_req):
                    return False
            return True
        except Exception:
            # uninitialized / deferred params on either side: no warm path
            return False

    def swap(self, new_block, example=None, timeout=30.0):
        """Hot-swap the served model: drain, switch executables atomically,
        resume. Returns the swap mode.

        * ``"warm"`` — ``new_block`` has the same parameter signature as
          the serving block: its weights are transplanted into the live
          parameter slots, so every already-compiled bucket executable
          (which reads param buffers per call) keeps serving —
          :meth:`assert_no_recompiles` still holds afterwards.
        * ``"cold"`` — different architecture/shapes: a fresh CachedOp
          replaces the old one; if ``example`` is given the full bucket
          lattice is re-warmed (through the internal admission bypass)
          before traffic resumes, and the new signature set is frozen.

        Raises :class:`ServiceUnavailable` if the drain times out —
        admission is resumed so the old model keeps serving."""
        from .. import autograd
        from .. import numpy as mnp

        t0 = time.perf_counter()
        if not self.drain(timeout):
            self.resume()
            raise ServiceUnavailable(
                f"serve session {self.name!r}: swap aborted — in-flight "
                f"work did not settle within {timeout}s; still serving "
                "the old model")
        try:
            if example is not None:
                # complete any deferred (shape-inferred) init on the
                # incoming block with one eager pass, so the signature
                # match sees real shapes and a same-architecture model
                # takes the warm path
                params = new_block.collect_params().values()
                if any(getattr(p, "_deferred_init", None) is not None
                       and p._data is None for p in params):
                    with autograd.predict_mode():
                        new_block(mnp.array(_onp.asarray(example)))
            if self._signature_matches(new_block):
                mode = "warm"
                olds = list(self.block.collect_params().values())
                news = list(new_block.collect_params().values())
                for po, pn in zip(olds, news):
                    po.set_data(pn.data())
            else:
                mode = "cold"
                self.block = new_block
                self._op = CachedOpThreadSafe(
                    new_block,
                    compiler_options=(_deterministic_compiler_options()
                                      if self.deterministic else None))
                self._warm_signatures = None
                self._shapes_ready = False
                if example is not None:
                    self._bypass.on = True
                    try:
                        self.warmup(example)
                    finally:
                        self._bypass.on = False
        finally:
            self.resume()
        self.metrics.observe_swap(mode, time.perf_counter() - t0)
        return mode

    def health(self):
        """Liveness probe payload: lifecycle state, breaker, in-flight
        count, error rate over the metrics window, warm flag, watchdog
        orphans. Always answers (a wedged executor is the watchdog's
        problem, not the probe's)."""
        from ..resilience.retry import watchdog_orphans

        snap = self.metrics.snapshot()
        with self._quiesce:
            draining = self._draining
            inflight = self._inflight
        requests = snap["requests"]
        out = {
            "state": "draining" if draining else "serving",
            "ready": self.ready(),
            "warm": self._warm_signatures is not None,
            "inflight": inflight,
            "breaker": self.breaker.snapshot(),
            "error_rate": (snap["errors"] / requests) if requests else 0.0,
            "rejects": snap["rejects"],
            "sheds": snap["sheds"],
            "deadline_expired": snap["deadline_expired"],
            "watchdog_orphans": watchdog_orphans(),
        }
        # SLO burn: degraded, not dead — ready() is untouched (an SLO
        # violation is a page, not a kill switch), the probe just says so
        slo_mon = getattr(self.metrics, "slo", None)
        if slo_mon is not None:
            out["slo"] = slo_mon.health()
            if out["slo"]["state"] == "degraded":
                out["state"] = "degraded"
        return out

    def ready(self):
        """Readiness probe: warm (lattice compiled + frozen), admitting
        (not draining), and the breaker is not open. A False here is the
        load balancer's cue to route around this replica."""
        with self._quiesce:
            if self._draining:
                return False
        return (self._warm_signatures is not None
                and self.breaker.state != "open")


def _resize_seq(arr, seq, pad_value):
    """Pad or slice axis 1 of a host array to exactly ``seq``."""
    t = arr.shape[1]
    if t == seq:
        return arr
    if t > seq:
        return arr[:, :seq]
    w = [(0, 0), (0, seq - t)] + [(0, 0)] * (arr.ndim - 2)
    return _onp.pad(arr, w, mode="constant", constant_values=pad_value)
