"""Fleet-grade serving: a health-aware `Router` over N in-process
:class:`~.replica.Replica` instances.

The single-replica stack (PRs 4/6/8: session + batcher + generator) is
hard to kill; this module makes a *fleet* of them hard to kill. One
:class:`Router` owns the request path in front of the replicas and
provides, in order of how often each saves you:

* **health-aware least-loaded dispatch** — every submit picks the ready
  replica with the smallest ``(load, p99_ms)`` key, where *ready* folds
  in the replica's liveness probe (flusher thread alive), admission
  state, session warmth, and the session's own circuit breaker. A
  replica the fleet should route around never sees the request.
* **failover with exactly-once settlement** — every request carries an
  idempotency key and a *generation* per dispatch attempt. When a
  replica dies (a ``die`` at the ``replica:dispatch`` site, a flusher
  killed mid-batch, a drain that never completes), the Router fences
  that replica's generations *first*, then requeues its undelivered
  in-flight requests to survivors — so the dead replica's late/dying
  503s settle into dropped duplicates, never client-visible errors,
  and a request is delivered exactly once no matter how many replicas
  it transited. Failed-over work is bounded by
  ``MXNET_FLEET_MAX_FAILOVERS``; each replica sits behind a fleet-level
  :class:`~..resilience.retry.CircuitBreaker` whose half-open state
  re-probes the replica with one real request.
* **hedged retries** — an *interactive* request dispatched to a
  straggler-flagged replica (per-replica latency-lag EWMAs in a
  :class:`~..resilience.elastic.StragglerMonitor`) is hedged to the
  next-best replica after ``MXNET_FLEET_HEDGE_MS``; the first settle
  wins, the loser is cancelled and counted. The batch class is never
  hedged (hedging doubles work — only latency-sensitive traffic earns
  it), and a request is never hedged twice.
* **zero-downtime rollout** — :meth:`Router.rollout` walks the live
  replicas one at a time: stop dispatching to one, let its in-flight
  work settle, hot-swap its session (warm swap = parameter transplant
  into the live executables — zero recompiles), resume. The rest of the
  fleet keeps serving; zero requests dropped.
* **autoscaling hooks** — :meth:`Router.scale_to` adds replicas through
  the ``factory`` or removes them by graceful drain (drain timeout =
  the failover path, never dropped work); :meth:`Router.autoscale_step`
  runs a pluggable policy over ``profiler.export.snapshot()`` gauges
  (queue depth / goodput / p99) — :class:`QueueDepthPolicy` is the
  default shape.

The Router registers itself as the fleet's *single* health provider on
the unified export surface (``/healthz``): a dead-and-routed-around
replica is an event in the fleet gauges, not a process-level 503.
``fleet_stats()`` feeds ``profiler.export.snapshot()`` with the
``fleet.<name>.*`` gauge namespace.
"""
from __future__ import annotations

import collections
import threading
import time
import weakref
from concurrent.futures import CancelledError, Future

from ..profiler import core as _prof
from ..profiler import export as _export
from ..resilience.elastic import StragglerMonitor
from ..resilience.faults import SimulatedWorkerDeath
from ..resilience.retry import CircuitBreaker
from .engine import DeadlineExceeded, ServeError, ServiceUnavailable
from .replica import Replica

__all__ = ["Router", "QueueDepthPolicy", "fleet_stats"]

# live routers, for the unified export surface (weak: a retired fleet
# drops out of the gauge namespace on its own)
_routers: "weakref.WeakSet" = weakref.WeakSet()

#: closed outcome ledger the Router keeps (all monotonic counters)
_COUNTERS = (
    "dispatched", "failovers", "requeued", "kills", "quarantines",
    "hedges", "hedge_wins", "hedge_losses", "fenced_results",
    "duplicate_settles", "duplicate_submits", "no_candidate",
    "rollouts", "scaled_up", "scaled_down",
)


def fleet_stats():
    """``{router_name: stats()}`` over every live Router (the gauge
    surface behind ``profiler.export.snapshot()``'s ``fleet.*``
    namespace)."""
    return {r.name: r.stats() for r in list(_routers)}


class _FleetRequest:
    """Router-side bookkeeping for one client request across dispatch
    attempts. ``valid_gens`` is the fencing set: a settle arriving with
    a generation not in it (a dead replica's dying 503, a cancelled
    hedge loser's late result) is dropped, never delivered."""

    __slots__ = ("key", "payload", "priority", "deadline", "future",
                 "valid_gens", "next_gen", "settled", "hedged",
                 "hedge_gen", "hedge_timer", "failovers", "t_submit",
                 "attempts")

    def __init__(self, key, payload, priority, deadline):
        self.key = key
        self.payload = payload
        self.priority = priority
        self.deadline = deadline          # absolute monotonic or None
        self.future = Future()            # the client's future
        self.valid_gens = set()
        self.next_gen = 0
        self.settled = False
        self.hedged = False
        self.hedge_gen = None
        self.hedge_timer = None
        self.failovers = 0
        self.t_submit = time.monotonic()
        self.attempts = []                # [(replica_idx, gen, fut)]


class _ReplicaState:
    """Router-side view of one replica: the fleet-level breaker that
    quarantines it, the admission flag rollout/scale toggle, and the
    outstanding map (key -> (request, generation)) that failover fences
    and requeues."""

    __slots__ = ("index", "replica", "breaker", "accepting", "dead",
                 "quarantined", "outstanding")

    def __init__(self, index, replica, breaker):
        self.index = index
        self.replica = replica
        self.breaker = breaker
        self.accepting = True
        self.dead = False
        self.quarantined = False
        self.outstanding = {}


class QueueDepthPolicy:
    """Default autoscaling policy: per-replica queue depth bands.

    Scale up one replica when mean queued+in-flight per live replica
    exceeds ``high``; scale down one when it falls below ``low`` (never
    past ``min_replicas``/``max_replicas``). The policy receives the
    full ``export.snapshot()`` dict too, so a custom policy can key off
    goodput or interactive p99 instead — the Router only requires
    ``policy(snapshot, router) -> target_replica_count``."""

    def __init__(self, high=4.0, low=0.5, min_replicas=1, max_replicas=8):
        self.high = float(high)
        self.low = float(low)
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)

    def __call__(self, snapshot, router):
        n = router.replica_count()
        if n == 0:
            return self.min_replicas
        per = router.total_load() / n
        if per > self.high and n < self.max_replicas:
            return n + 1
        if per < self.low and n > self.min_replicas:
            return n - 1
        return n


class Router:
    """Health-aware fleet router with exactly-once failover settlement.

    Parameters
    ----------
    replicas : iterable of Replica
        Initial fleet. Replica-owned sessions are adopted: they leave
        the process-level ``/healthz`` roll (the Router answers for the
        fleet) but keep their own breakers/watchdogs.
    factory : callable(index) -> Replica, optional
        Builds a new replica for :meth:`scale_to` / autoscaling.
    hedge_ms, straggler_ms, probe_ms, max_failovers,
    breaker_threshold, breaker_cooldown :
        Overrides of the matching ``MXNET_FLEET_*`` flags.
    autoscale_policy : callable(snapshot, router) -> int, optional
        Target-size policy for :meth:`autoscale_step`
        (:class:`QueueDepthPolicy` shape).
    """

    def __init__(self, replicas=(), factory=None, name="fleet",
                 hedge_ms=None, straggler_ms=None, probe_ms=None,
                 max_failovers=None, breaker_threshold=None,
                 breaker_cooldown=None, autoscale_policy=None):
        from .. import config

        def _flag(v, flag):
            return v if v is not None else config.get(flag)

        self.name = name
        self.factory = factory
        self.hedge_ms = float(_flag(hedge_ms, "MXNET_FLEET_HEDGE_MS"))
        self.probe_ms = float(_flag(probe_ms, "MXNET_FLEET_PROBE_MS"))
        self.max_failovers = int(
            _flag(max_failovers, "MXNET_FLEET_MAX_FAILOVERS"))
        self._breaker_threshold = int(
            _flag(breaker_threshold, "MXNET_FLEET_BREAKER_THRESHOLD"))
        self._breaker_cooldown = int(
            _flag(breaker_cooldown, "MXNET_FLEET_BREAKER_COOLDOWN"))
        self.monitor = StragglerMonitor(
            threshold_ms=_flag(straggler_ms, "MXNET_FLEET_STRAGGLER_MS"))
        self.autoscale_policy = autoscale_policy
        self._lock = threading.RLock()
        self._states = {}                 # index -> _ReplicaState
        self._next_idx = 0
        self._requests = {}               # key -> live _FleetRequest
        self._settled = collections.OrderedDict()  # key -> settled Future
        self._settled_cap = 4096
        self._seq = 0
        self._recent_lat = collections.deque(maxlen=256)
        self.counters = dict.fromkeys(_COUNTERS, 0)
        self._closed = False
        self._draining = False
        for r in replicas:
            self.add_replica(r)
        self._supervisor = None
        if self.probe_ms > 0:
            self._supervisor = threading.Thread(
                target=self._supervise, daemon=True,
                name=f"mxtpu-fleet-supervisor[{name}]")
            self._supervisor.start()
        _export.register_health_provider(self)
        _routers.add(self)

    # -- fleet membership ---------------------------------------------------
    def add_replica(self, replica):
        """Adopt ``replica`` into the fleet (assigns a fleet-unique
        index when the replica's collides). Returns its index."""
        with self._lock:
            idx = int(getattr(replica, "index", self._next_idx))
            if idx in self._states:
                idx = self._next_idx
            replica.index = idx
            self._next_idx = max(self._next_idx, idx + 1)
            breaker = CircuitBreaker(
                failure_threshold=self._breaker_threshold,
                cooldown_calls=self._breaker_cooldown,
                name=f"fleet:{self.name}:r{idx}")
            self._states[idx] = _ReplicaState(idx, replica, breaker)
        if replica.session is not None:
            # the Router answers /healthz for the whole fleet; a dead
            # (and routed-around) replica must not wedge the process
            # probe at 503
            _export.unregister_health_provider(replica.session)
        return idx

    def replica_count(self):
        with self._lock:
            return sum(1 for st in self._states.values() if not st.dead)

    def total_load(self):
        with self._lock:
            states = [st for st in self._states.values() if not st.dead]
        return sum(st.replica.load() for st in states)

    # -- submit / dispatch --------------------------------------------------
    def submit(self, payload, priority="interactive", deadline_ms=None,
               key=None):
        """Admit one request into the fleet; returns the client future.

        ``key`` is the request's idempotency key (one is generated when
        omitted): a duplicate submit — same key, whether the original is
        in flight or already settled — returns the original future and
        never dispatches a second copy. ``deadline_ms`` is the total
        fleet-side budget; failover re-dispatches carry the *remaining*
        budget, not a fresh one."""
        if self._closed or self._draining:
            raise ServiceUnavailable(
                f"fleet {self.name!r} is "
                f"{'closed' if self._closed else 'draining'}")
        deadline = (time.monotonic() + float(deadline_ms) / 1e3
                    if deadline_ms is not None and deadline_ms > 0
                    else None)
        with self._lock:
            if key is not None:
                live = self._requests.get(key)
                if live is not None:
                    self.counters["duplicate_submits"] += 1
                    return live.future
                done = self._settled.get(key)
                if done is not None:
                    self.counters["duplicate_submits"] += 1
                    return done
            else:
                self._seq += 1
                key = f"~{self.name}:{self._seq}"
            req = _FleetRequest(key, payload, priority, deadline)
            self._requests[key] = req
        self._dispatch(req)
        return req.future

    def _pick_locked(self, exclude):
        """Least-loaded ready replica by ``(load, p99_ms)``; caller
        holds the lock. Non-closed fleet breakers get an ``allow()``
        query each pick — that's what walks an open breaker's call-count
        cooldown toward half-open; a granted half-open probe slot means
        THIS request is the re-probe and the quarantined replica is
        chosen over the healthy ones (probes are one per cooldown
        window, and the failover budget protects the request)."""
        best = None
        best_key = None
        for st in self._states.values():
            if st.index in exclude or st.dead or not st.accepting:
                continue
            rep = st.replica
            if not rep.alive():
                continue
            if str(st.breaker.state) != "closed":
                if st.breaker.allow():
                    return st            # the half-open re-probe
                continue
            if not rep.ready():
                continue
            k = (rep.load(), rep.p99_ms())
            if best_key is None or k < best_key:
                best, best_key = st, k
        return best

    def _dispatch(self, req, exclude=None, hedge=False):
        """Dispatch (or re-dispatch) ``req`` onto the best replica,
        absorbing synchronous dispatch failures: a replica death fails
        over to a survivor, overload rotates to the next-best replica
        (passing the last ``retry_after_ms``-bearing 503 through when
        the whole fleet is saturated), structural failures count against
        the replica's fleet breaker. A hedge dispatch (``hedge=True``)
        gives up silently on any failure — the primary attempt is still
        in flight and must win rather than inherit a hedge-path error."""
        exclude = set(exclude or ())
        overload = None
        while True:
            now = time.monotonic()
            if req.deadline is not None and now >= req.deadline:
                if not hedge:
                    self._finish(req, error=DeadlineExceeded(
                        f"fleet {self.name!r}: deadline expired after "
                        f"{req.failovers} failover(s)"))
                return
            settle = None
            with self._lock:
                if req.settled:
                    return
                st = None if self._closed else self._pick_locked(exclude)
                if st is None:
                    if hedge:
                        return
                    self.counters["no_candidate"] += 1
                    err = overload or ServiceUnavailable(
                        f"fleet {self.name!r}: no ready replica "
                        f"(tried {len(exclude)} of "
                        f"{len(self._states)}; fleet "
                        f"{'closed' if self._closed else 'degraded'})")
                    settle = self._finish_locked(req, error=err)
                else:
                    gen = req.next_gen
                    req.next_gen += 1
                    req.valid_gens.add(gen)
                    if hedge:
                        req.hedge_gen = gen
                    st.outstanding[req.key] = (req, gen)
            if st is None:
                if settle is not None:
                    settle()
                return
            remaining_ms = None
            if req.deadline is not None:
                remaining_ms = max(0.1, (req.deadline - now) * 1e3)
            try:
                fut = st.replica.submit(req.payload, priority=req.priority,
                                        deadline_ms=remaining_ms,
                                        key=req.key)
            except SimulatedWorkerDeath:
                # replica death AT dispatch: fence + requeue its other
                # outstanding work, then fail this request over
                with self._lock:
                    st.outstanding.pop(req.key, None)
                    req.valid_gens.discard(gen)
                self._mark_dead(st, reason="dispatch_die")
                if hedge:
                    return
                if not self._count_failover(req):
                    return
                exclude.add(st.index)
                continue
            except DeadlineExceeded as exc:
                with self._lock:
                    st.outstanding.pop(req.key, None)
                    req.valid_gens.discard(gen)
                if not hedge:
                    self._finish(req, error=exc)
                return
            except Exception as exc:  # pylint: disable=broad-except
                with self._lock:
                    st.outstanding.pop(req.key, None)
                    req.valid_gens.discard(gen)
                if getattr(exc, "retry_after_ms", None) is not None:
                    # overload-shaped 503: healthy-but-full replica. No
                    # breaker penalty; rotate to the next-best replica,
                    # and if the WHOLE fleet is saturated hand the
                    # backpressure hint to the client
                    overload = exc
                    exclude.add(st.index)
                    if hedge:
                        return
                    continue
                # structural dispatch failure (flaky dispatch RPC, shut
                # batcher): penalize the fleet breaker and fail over
                self._record_failure(st)
                if hedge:
                    return
                if not self._count_failover(req):
                    return
                exclude.add(st.index)
                continue
            with self._lock:
                req.attempts.append((st.index, gen, fut))
                self.counters["dispatched"] += 1
            fut.add_done_callback(
                lambda f, s=st, g=gen, r=req: self._on_settle(r, s, g, f))
            if not hedge:
                self._maybe_arm_hedge(req, st)
            return

    def _count_failover(self, req):
        """Charge one failover against ``req``; False (and a terminal
        503) once the budget is exhausted."""
        with self._lock:
            req.failovers += 1
            self.counters["failovers"] += 1
            over = req.failovers > self.max_failovers
        if over:
            self._finish(req, error=ServiceUnavailable(
                f"fleet {self.name!r}: request exhausted its failover "
                f"budget (MXNET_FLEET_MAX_FAILOVERS="
                f"{self.max_failovers})"))
            return False
        return True

    def _record_failure(self, st):
        with self._lock:
            was_open = str(st.breaker.state) == "open"
            st.breaker.record_failure()
            if not was_open and str(st.breaker.state) == "open":
                self.counters["quarantines"] += 1

    # -- settlement ---------------------------------------------------------
    def _on_settle(self, req, st, gen, fut):
        """Done-callback for one dispatch attempt's batcher future —
        the exactly-once gate. Runs on the settling replica's flusher
        thread (or the canceller's)."""
        try:
            result, error = fut.result(timeout=0), None
        except CancelledError:
            # the hedge loser we cancelled ourselves; already counted
            with self._lock:
                entry = st.outstanding.get(req.key)
                if entry is not None and entry[1] == gen:
                    st.outstanding.pop(req.key, None)
            return
        except BaseException as exc:  # noqa: BLE001 -- per-request error
            result, error = None, exc
        failover = False
        record_fail = False
        settle = None
        with self._lock:
            entry = st.outstanding.get(req.key)
            if entry is not None and entry[1] == gen:
                st.outstanding.pop(req.key, None)
            if req.settled:
                self.counters["duplicate_settles"] += 1
                return
            if gen not in req.valid_gens:
                # fenced: a dead/quarantined replica's dying settle
                self.counters["fenced_results"] += 1
                return
            if error is None:
                st.breaker.record_success()
                self._observe_latency_locked(st, req)
                if req.hedged:
                    if gen == req.hedge_gen:
                        self.counters["hedge_wins"] += 1
                    else:
                        self.counters["hedge_losses"] += 1
                settle = self._finish_locked(req, result=result,
                                             winner_gen=gen)
            elif isinstance(error, DeadlineExceeded):
                # the request's own budget, not the replica's health
                settle = self._finish_locked(req, error=error,
                                             winner_gen=gen)
            elif isinstance(error, ServeError) \
                    and getattr(error, "retry_after_ms", None) is not None:
                # overload-shaped: pass the backpressure through
                settle = self._finish_locked(req, error=error,
                                             winner_gen=gen)
            elif isinstance(error, ServiceUnavailable):
                # structural 503 at settle time (session breaker open,
                # batcher shut under us): quarantine-worthy — fail over
                req.valid_gens.discard(gen)
                failover = True
            else:
                # a per-request model/user error: deliver it (retrying a
                # deterministic failure elsewhere just re-fails slower),
                # but count it against the replica's breaker so a
                # replica failing EVERY request still quarantines
                settle = self._finish_locked(req, error=error,
                                             winner_gen=gen)
                record_fail = True
        if settle is not None:
            # client future settles OUTSIDE the Router lock: done-
            # callbacks run on this thread and may re-enter the Router
            settle()
        if failover:
            self._record_failure(st)
            if self._count_failover(req):
                self._dispatch(req, exclude={st.index})
        elif record_fail:
            self._record_failure(st)

    def _observe_latency_locked(self, st, req):
        """Feed the straggler monitor: this attempt's fleet-relative
        latency lag (latency minus the recent fleet median)."""
        lat = time.monotonic() - req.t_submit
        self._recent_lat.append(lat)
        srt = sorted(self._recent_lat)
        median = srt[len(srt) // 2]
        self.monitor.observe(st.index, max(0.0, lat - median),
                             site="replica:settle")

    def _finish_locked(self, req, result=None, error=None,
                       winner_gen=None):
        """Bookkeeping half of exactly-once settlement (caller holds
        the lock): flip ``req.settled``, cancel the hedge timer, fence
        and unregister the losing attempts. Returns a zero-arg action
        that settles the CLIENT future and cancels the losers — the
        caller MUST run it after releasing the lock (``set_result``
        fires done-callbacks on this thread, and running arbitrary
        client callbacks / loser cancellation under the Router lock is
        a lock-order hazard the mxlint L002 gate flags). Returns None
        on a duplicate settle."""
        if req.settled:
            self.counters["duplicate_settles"] += 1
            return None
        req.settled = True
        if req.hedge_timer is not None:
            req.hedge_timer.cancel()
            req.hedge_timer = None
        losers = [(i, g, f) for (i, g, f) in req.attempts
                  if g != winner_gen and f is not None]
        req.valid_gens.clear()
        for i, _g, _f in losers:
            other = self._states.get(i)
            if other is not None:
                entry = other.outstanding.get(req.key)
                if entry is not None and entry[0] is req:
                    other.outstanding.pop(req.key, None)
        self._requests.pop(req.key, None)
        self._settled[req.key] = req.future
        while len(self._settled) > self._settled_cap:
            self._settled.popitem(last=False)

        def settle():
            # batcher futures are never RUNNING, so cancel() wins
            # unless the attempt already settled (in which case its
            # _on_settle is fenced/duplicate-dropped)
            if error is not None:
                req.future.set_exception(error)
            else:
                req.future.set_result(result)
            for _i, _g, f in losers:
                f.cancel()

        return settle

    def _finish(self, req, result=None, error=None, winner_gen=None):
        with self._lock:
            settle = self._finish_locked(req, result=result, error=error,
                                         winner_gen=winner_gen)
        if settle is not None:
            settle()

    # -- hedging ------------------------------------------------------------
    def _maybe_arm_hedge(self, req, st):
        """Arm a hedge timer iff: hedging on, interactive class, first
        hedge for this request, the chosen replica is straggler-flagged,
        and a second replica exists to hedge onto."""
        if self.hedge_ms <= 0 or req.priority != "interactive":
            return
        with self._lock:
            if req.settled or req.hedged or req.hedge_timer is not None:
                return
            if not self.monitor.flagged(st.index):
                return
            if not any(o.index != st.index and not o.dead and o.accepting
                       and o.replica.alive()
                       for o in self._states.values()):
                return
            t = threading.Timer(self.hedge_ms / 1e3, self._fire_hedge,
                                args=(req, st.index))
            t.daemon = True
            req.hedge_timer = t
        t.start()

    def _fire_hedge(self, req, primary_idx):
        with self._lock:
            if req.settled or req.hedged or self._closed:
                return
            req.hedged = True            # never hedge twice
            req.hedge_timer = None
            self.counters["hedges"] += 1
        self._dispatch(req, exclude={primary_idx}, hedge=True)

    # -- failure detection / failover ---------------------------------------
    def _mark_dead(self, st, reason="dead"):
        """Replica death: fence its generations FIRST (any settle still
        in flight from it is dropped as fenced), requeue its undelivered
        outstanding requests to survivors with their remaining deadline
        budget, then hard-kill the replica (whose dying 503s are now
        harmless). Idempotent."""
        with self._lock:
            if st.dead:
                return
            st.dead = True
            st.accepting = False
            self.counters["kills"] += 1
            requeue = []
            for _key, (req, gen) in list(st.outstanding.items()):
                req.valid_gens.discard(gen)
                if req.settled:
                    continue
                # a hedge/failover twin may still be live elsewhere; the
                # request is only requeued when NO valid attempt remains
                if req.valid_gens:
                    continue
                requeue.append(req)
            st.outstanding.clear()
            self.monitor.clear(st.index)
        for req in requeue:
            with self._lock:
                self.counters["requeued"] += 1
            if self._count_failover(req):
                self._dispatch(req, exclude={st.index})
        try:
            st.replica.kill()
        except Exception:  # noqa: BLE001 -- death cleanup is best-effort
            pass

    def kill_replica(self, index, reason="manual"):
        """Hard-kill replica ``index`` (the chaos harness's mid-traffic
        kill switch); its in-flight work fails over. True if it was
        alive."""
        with self._lock:
            st = self._states.get(int(index))
            if st is None or st.dead:
                return False
        self._mark_dead(st, reason=reason)
        return True

    def _supervise(self):
        """Background probe loop (``MXNET_FLEET_PROBE_MS``): detects
        replicas whose flusher died mid-batch (an execution-site ``die``
        kills the thread without any dispatch-time signal) and walks
        quarantined sessions' breaker cooldowns so an idle-but-routed-
        around replica can still reach half-open."""
        _prof.register_thread_name()
        while not self._closed:
            time.sleep(self.probe_ms / 1e3)
            if self._closed:
                return
            try:
                self._probe_once()
            except Exception:  # noqa: BLE001 -- the supervisor survives
                pass

    def _probe_once(self):
        with self._lock:
            states = [st for st in self._states.values() if not st.dead]
        for st in states:
            if not st.replica.alive():
                self._mark_dead(st, reason="probe_dead")
                continue
            sess = st.replica.session
            if sess is not None:
                sstate = str(sess.breaker.state)
                if sstate == "open":
                    if not st.quarantined:
                        with self._lock:
                            if not st.quarantined:
                                st.quarantined = True
                                self.counters["quarantines"] += 1
                    # no traffic reaches an un-ready replica, so ITS
                    # breaker's call-count cooldown would never advance;
                    # the probe loop stands in for the missing callers
                    sess.breaker.allow()
                elif st.quarantined and sstate == "closed":
                    with self._lock:
                        st.quarantined = False

    # -- rollout / scaling --------------------------------------------------
    def rollout(self, new_block, example=None, timeout=30.0):
        """Zero-downtime fleet rollout: one replica at a time, stop
        dispatching to it, wait for its outstanding fleet requests to
        settle, hot-swap its session (warm = zero recompiles), resume.
        A replica whose drain never completes is marked dead — its work
        fails over — and the rollout continues. Returns the list of
        per-replica swap modes (``"warm"``/``"cold"``/``"dead"``)."""
        with self._lock:
            states = [st for st in sorted(self._states.values(),
                                          key=lambda s: s.index)
                      if not st.dead]
        modes = []
        for st in states:
            with self._lock:
                if st.dead:
                    modes.append("dead")
                    continue
                st.accepting = False
            try:
                if not self._await_outstanding(st, timeout):
                    self._mark_dead(st, reason="rollout_drain_timeout")
                    modes.append("dead")
                    continue
                modes.append(st.replica.swap(new_block, example=example,
                                             timeout=timeout))
            except ServeError:
                self._mark_dead(st, reason="rollout_swap_failed")
                modes.append("dead")
                continue
            finally:
                with self._lock:
                    if not st.dead:
                        st.accepting = True
        with self._lock:
            self.counters["rollouts"] += 1
        return modes

    def _await_outstanding(self, st, timeout):
        """Wait until no fleet request is outstanding on ``st`` (its
        admission is already stopped). True when quiet."""
        deadline = time.monotonic() + float(timeout)
        while time.monotonic() < deadline:
            with self._lock:
                if not st.outstanding:
                    return True
            time.sleep(0.002)
        with self._lock:
            return not st.outstanding

    def scale_to(self, n, timeout=30.0):
        """Resize the fleet to ``n`` live replicas. Scaling up builds
        replicas through ``factory``; scaling down removes the highest-
        index replicas by graceful drain (a drain that never completes
        becomes a kill + failover — work is never dropped). Returns the
        live count."""
        n = int(n)
        if n < 0:
            raise ServeError(f"scale_to({n}): target must be >= 0")
        if self.replica_count() < n:
            # scale-up warms from the persistent compile cache when
            # MXNET_COMPILE_CACHE_DIR is set: the factory's session
            # warmup replays the bucket lattice from disk instead of
            # paying the XLA compile storm per new replica
            from .. import compile_cache as _cc

            _cc.enable()
        while self.replica_count() < n:
            if self.factory is None:
                raise ServeError(
                    f"fleet {self.name!r}: scale_to({n}) needs a replica "
                    "factory")
            with self._lock:
                idx = self._next_idx
            self.add_replica(self.factory(idx))
            with self._lock:
                self.counters["scaled_up"] += 1
        while self.replica_count() > n:
            with self._lock:
                live = sorted((st for st in self._states.values()
                               if not st.dead), key=lambda s: s.index)
                victim = live[-1]
                victim.accepting = False
            self._retire(victim, timeout)
        return self.replica_count()

    def _retire(self, st, timeout):
        """Graceful scale-down of one replica: no new dispatches, wait
        for outstanding to settle, then shut it down clean. Timeout =
        the failover path."""
        if not self._await_outstanding(st, timeout) \
                or not st.replica.drain(min(timeout, 5.0)):
            self._mark_dead(st, reason="scale_down_timeout")
        else:
            with self._lock:
                st.dead = True
            self.monitor.clear(st.index)
            try:
                st.replica.kill()
            except Exception:  # noqa: BLE001
                pass
        with self._lock:
            self.counters["scaled_down"] += 1
            self._states.pop(st.index, None)

    def autoscale_step(self):
        """Run one autoscaling decision: evaluate the policy over the
        unified export snapshot and apply the target via
        :meth:`scale_to`. Returns the (possibly unchanged) live count.
        No-op without a policy."""
        policy = self.autoscale_policy
        if policy is None:
            return self.replica_count()
        target = int(policy(_export.snapshot(include_aggregates=False),
                            self))
        if target != self.replica_count():
            return self.scale_to(target)
        return self.replica_count()

    # -- probes / stats / lifecycle -----------------------------------------
    def ready(self):
        """Fleet readiness: at least one live, accepting, ready
        replica."""
        if self._closed:
            return False
        with self._lock:
            states = [st for st in self._states.values()
                      if not st.dead and st.accepting]
        return any(st.replica.alive() and st.replica.ready()
                   for st in states)

    def health(self):
        """Fleet health payload for ``/healthz``: per-replica probes
        plus the failover/hedge ledger."""
        with self._lock:
            states = dict(self._states)
            counters = dict(self.counters)
        replicas = {}
        live = 0
        for idx, st in states.items():
            if st.dead:
                replicas[idx] = {"alive": False, "ready": False,
                                 "killed": True}
                continue
            live += 1
            row = st.replica.health()
            row["fleet_breaker"] = st.breaker.snapshot()
            row["accepting"] = st.accepting
            replicas[idx] = row
        return {
            "state": "closed" if self._closed else "serving",
            "ready": self.ready(),
            "live": live,
            "dead": len(states) - live,
            "replicas": replicas,
            "counters": counters,
        }

    def stats(self):
        """Flat-ish gauge dict for ``fleet.<name>.*`` in
        ``export.snapshot()``."""
        with self._lock:
            states = dict(self._states)
            out = dict(self.counters)
            out["inflight"] = len(self._requests)
        live = [st for st in states.values() if not st.dead]
        out["live"] = len(live)
        out["dead"] = len(states) - len(live)
        out["total_load"] = sum(st.replica.load() for st in live)
        rep = {}
        for idx, st in states.items():
            if st.dead:
                rep[idx] = {"alive": 0, "ready": 0, "load": 0}
                continue
            rep[idx] = {
                "alive": int(st.replica.alive()),
                "ready": int(st.replica.ready()),
                "accepting": int(st.accepting),
                "load": st.replica.load(),
                "p99_ms": st.replica.p99_ms(),
                "breaker": str(st.breaker.state),
            }
        out["replica"] = rep
        return out

    def drain(self, timeout=30.0):
        """Graceful preemption drain: stop admitting new requests
        (submit raises :class:`ServiceUnavailable`), wait up to
        ``timeout`` seconds for every in-flight request to settle
        through the normal dispatch/failover machinery, then
        :meth:`close`. Returns True when the fleet drained clean —
        False means the timeout expired and the leftovers settled 503
        through close(). This is the serving half of the SIGTERM
        lifecycle (``resilience.preemption`` routes the signal here)."""
        with self._lock:
            if self._closed:
                return True
            self._draining = True
        deadline = time.monotonic() + float(timeout)
        drained = True
        while True:
            with self._lock:
                pending = len(self._requests)
            if pending == 0:
                break
            if time.monotonic() >= deadline:
                drained = False
                break
            time.sleep(0.01)
        self.close(timeout=max(0.1, deadline - time.monotonic()))
        return drained

    def close(self, timeout=5.0):
        """Shut the fleet down: stop the supervisor, close every
        replica (their leftover work settles 503 through the normal
        fenced/failover machinery, which finds the fleet closed and
        delivers a structural 503)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        if self._supervisor is not None:
            self._supervisor.join(min(timeout, 2 * self.probe_ms / 1e3
                                      + 1.0))
        with self._lock:
            states = list(self._states.values())
        for st in states:
            try:
                st.replica.kill(timeout=timeout)
            except Exception:  # noqa: BLE001
                pass
        _export.unregister_health_provider(self)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
