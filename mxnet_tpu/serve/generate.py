"""Autoregressive decode with a real KV cache.

Without this module, generating token ``t`` re-runs the full prefill over
``t`` positions — O(n^2) work per sequence. :class:`KVCache` preallocates
per-layer K/V rings to ``max_seq`` and the decode step feeds exactly one
new token through the model (``cache=`` / ``start_pos=`` path in
``models/llama.py``), so each generated token costs one T=1 executable
replay.

Parity contract (asserted per-token in ``tests/test_serve.py``): the
decode path's logits are **bitwise identical** to re-running the full
prefill through the same cache-mode path. Both arms compile through the
shape-stable serving ops in ``ops/nn.py`` (see the section comment there)
— the KV cache is a pure work-skipping transform, not an approximation.

Shapes are bucketed the serving way: one decode executable per batch
bucket (T=1 is constant), one prefill executable per (batch, prompt)
bucket; after :meth:`Generator.warmup` a decode stream of any admitted
shape triggers zero XLA compiles.

Sampling (``greedy``, temperature, top-k) draws its keys from
``mxnet_tpu.random`` — seeded, reproducible streams, same as training.
"""
from __future__ import annotations

import time

import numpy as _onp

from .. import random as _rng
from ..base import MXNetError
from ..profiler import attribution as _attr
from ..profiler import trace as _trace
from ..gluon.block import HybridBlock
from ..ops import nn as _ops
from ..resilience import faults as _faults
from .engine import InferenceSession, PoolExhausted, pick_bucket


class _LayerKV:
    """One layer's view of the cache: read k/v (plus int8 scale rings when
    quantized), write back the updated rings (functional update — inside a
    trace these are tracers)."""

    __slots__ = ("_cache", "_idx")

    def __init__(self, cache, idx):
        self._cache = cache
        self._idx = idx

    @property
    def k(self):
        return self._cache._k[self._idx]

    @property
    def v(self):
        return self._cache._v[self._idx]

    @property
    def k_scale(self):
        return self._cache._ks[self._idx]

    @property
    def v_scale(self):
        return self._cache._vs[self._idx]

    @property
    def max_seq(self):
        return self._cache.max_seq

    @property
    def quant(self):
        return self._cache.quant

    @property
    def path(self):
        return self._cache.path

    @property
    def quant_weights(self):
        return self._cache.quant_weights

    def update(self, new_k, new_v, new_k_scale=None, new_v_scale=None):
        self._cache._k[self._idx] = new_k
        self._cache._v[self._idx] = new_v
        if new_k_scale is not None:
            self._cache._ks[self._idx] = new_k_scale
        if new_v_scale is not None:
            self._cache._vs[self._idx] = new_v_scale


class KVCache:
    """Preallocated per-layer K/V rings for autoregressive decode.

    Layout: ``num_layers`` pairs of (batch, kv_heads, max_seq, head_dim)
    NDArrays, zero-initialized. With ``quant="int8"`` the rings are int8
    and each carries a (batch, kv_heads, max_seq) f32 scale ring
    (per-token-per-head symmetric quantization, written by
    ``ops.nn.kv_cache_write_q``) — half the HBM of the f32 rings.
    Position accounting lives with the caller (per-row ``start_pos``
    vectors) — the cache itself is pure storage, so one compiled
    executable serves every decode step.

    ``path`` / ``quant_weights`` are trace-time routing attributes set by
    the serving step before the model forward: which ``cached_attention``
    formulation the layers should compile, and (int8 rung) the
    ``{id(param): (int8_weight, scale)}`` side table for
    ``ops.nn.quantized_dense``.
    """

    def __init__(self, keys, values, max_seq, key_scales=None,
                 value_scales=None, quant=None):
        if len(keys) != len(values):
            raise MXNetError("KVCache needs one value ring per key ring")
        self._k = list(keys)
        self._v = list(values)
        self._ks = list(key_scales) if key_scales is not None else None
        self._vs = list(value_scales) if value_scales is not None else None
        if quant is not None and (self._ks is None or self._vs is None):
            raise MXNetError("quantized KVCache needs scale rings")
        self.quant = quant
        self.max_seq = int(max_seq)
        self.path = "baseline"
        self.quant_weights = None

    @classmethod
    def alloc(cls, model, batch, max_seq, dtype="float32", quant=None):
        """Zeroed rings sized from the model's attention geometry."""
        from .. import numpy as mnp

        keys, values = [], []
        kscales, vscales = [], []
        for blk in model._blocks:
            attn = blk.attention
            shape = (int(batch), attn._kv_heads, int(max_seq),
                     attn._head_dim)
            if quant == "int8":
                keys.append(mnp.zeros(shape, dtype="int8"))
                values.append(mnp.zeros(shape, dtype="int8"))
                kscales.append(mnp.zeros(shape[:3], dtype="float32"))
                vscales.append(mnp.zeros(shape[:3], dtype="float32"))
            elif quant is None:
                keys.append(mnp.zeros(shape, dtype=dtype))
                values.append(mnp.zeros(shape, dtype=dtype))
            else:
                raise MXNetError(f"unknown KVCache quant {quant!r}")
        if quant is None:
            return cls(keys, values, max_seq)
        return cls(keys, values, max_seq, kscales, vscales, quant)

    @property
    def num_layers(self):
        return len(self._k)

    @property
    def batch(self):
        return self._k[0].shape[0]

    def layer(self, i) -> _LayerKV:
        return _LayerKV(self, i)

    def flat(self):
        """Interleaved [k0, v0, k1, v1, ...] — the executable's calling
        convention for cache state. Quantized caches interleave
        [k0, ks0, v0, vs0, ...] (scale ring right after its int8 ring)."""
        out = []
        if self.quant is not None:
            for k, ks, v, vs in zip(self._k, self._ks, self._v, self._vs):
                out.extend((k, ks, v, vs))
            return out
        for k, v in zip(self._k, self._v):
            out.extend((k, v))
        return out

    @classmethod
    def from_flat(cls, arrays, max_seq, quant=None):
        arrays = list(arrays)
        if quant is not None:
            if len(arrays) % 4:
                raise MXNetError(
                    "flat quantized KVCache needs 4 arrays per layer")
            return cls(arrays[0::4], arrays[2::4], max_seq,
                       arrays[1::4], arrays[3::4], quant)
        if len(arrays) % 2:
            raise MXNetError("flat KVCache needs an even array count")
        return cls(arrays[0::2], arrays[1::2], max_seq)

    def nbytes(self):
        arrays = self._k + self._v
        if self.quant is not None:
            arrays = arrays + self._ks + self._vs
        return sum(int(_onp.prod(a.shape)) * _onp.dtype(a.dtype).itemsize
                   for a in arrays)


class _CacheForward(HybridBlock):
    """The compiled serving step: (tokens, start_pos, last_idx, *rings) ->
    (last-position logits, *updated rings).

    One forward serves both phases — prefill (T = prompt bucket,
    start_pos = 0, last_idx = prompt_len - 1) and decode (T = 1,
    start_pos = per-row position, last_idx = 0). The phases differ only
    by shape, i.e. by CachedOp signature, never by code path: that shared
    path is what makes the bitwise decode-vs-prefill parity hold.

    ``paged=True`` switches the cache-state calling convention from
    contiguous rings to page pools: the call grows a ``page_table``
    (B, N) arg after ``last_idx``, the per-layer arrays are
    (P, KV, page, D) pools, and the step brackets the UNCHANGED model
    cache path with ``ops.nn.paged_kv_gather`` (pool -> per-slot ring)
    and ``ops.nn.paged_kv_scatter`` (freshly written rows -> pool).
    The fused form is for the fast rungs (pallas/int8, tolerance
    parity): fusing the brackets into the step lets XLA pick different
    loop partitions for the model subgraph, which drifts ulps from the
    ring executable. The strict baseline rung therefore never compiles
    ``paged=True`` — its callers run the same brackets as standalone
    exact-copy device ops around the unchanged *ring* executable, so
    paged baseline decode is bitwise identical to ring decode because
    it literally replays the same compiled step
    (tests/test_kv_blocks.py asserts it).
    """

    def __init__(self, model, max_seq, path="baseline", quant=None,
                 qindex=(), all_logits=False, paged=False, **kwargs):
        super().__init__(**kwargs)
        self.model = model  # child registration shares the params
        self._max_seq = int(max_seq)
        self._path = path
        self._quant = quant
        self._qindex = list(qindex)
        self._all_logits = bool(all_logits)
        self._paged = bool(paged)
        n_layers = len(model._blocks)
        self._n_cache = n_layers * (4 if quant else 2)

    def forward(self, tokens, start_pos, last_idx, *rest):
        page_table = None
        if self._paged:
            page_table, rest = rest[0], rest[1:]
        flat_cache = rest[:self._n_cache]
        qflat = rest[self._n_cache:]
        pools = None
        if self._paged:
            pools = flat_cache
            flat_cache = [_ops.paged_kv_gather(p, page_table)
                          for p in pools]
        cache = KVCache.from_flat(flat_cache, self._max_seq,
                                  quant=self._quant)
        cache.path = self._path
        if qflat:
            # int8 weight side table: quantized weights enter as two packed
            # traced call args (appended after the rings by Generator._run),
            # so they are neither jit-captured constants nor extra
            # Parameters; reslice them by the static qindex offsets
            packed_w, packed_s = qflat
            table, woff, soff = {}, 0, 0
            for pid, (o, u) in self._qindex:
                table[pid] = (packed_w[woff:woff + o * u].reshape(o, u),
                              packed_s[soff:soff + o])
                woff += o * u
                soff += o
            cache.quant_weights = table
        logits = self.model(tokens, cache=cache, start_pos=start_pos)
        updated = tuple(cache.flat())
        if self._paged:
            t_len = tokens.shape[1]
            updated = tuple(
                _ops.paged_kv_scatter(p, page_table, r, start_pos, t_len)
                for p, r in zip(pools, updated))
        if self._all_logits:
            # speculative verify step: the caller scores every position of
            # the (k+1)-token block, not just the last real one
            return (logits,) + updated
        last = _ops.gather_positions(logits, last_idx)
        return (last,) + updated


def sample_tokens(logits, temperature=0.0, top_k=None):
    """Next-token choice from (B, vocab) logits.

    ``temperature <= 0`` is greedy argmax; otherwise softmax sampling at
    the given temperature, optionally truncated to the ``top_k`` largest
    logits. Randomness comes from ``mxnet_tpu.random``'s key stream, so
    ``mx.random.seed(n)`` reproduces a generation exactly.
    Returns a host numpy (B,) int32 array.
    """
    import jax
    import jax.numpy as jnp

    from ..ndarray.ndarray import NDArray

    data = logits._data if isinstance(logits, NDArray) else jnp.asarray(logits)
    if temperature is None or temperature <= 0.0:
        return _onp.asarray(jnp.argmax(data, axis=-1)).astype(_onp.int32)
    scaled = data / float(temperature)
    if top_k is not None and 0 < int(top_k) < scaled.shape[-1]:
        kth = jax.lax.top_k(scaled, int(top_k))[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    key = _rng.next_key()
    return _onp.asarray(
        jax.random.categorical(key, scaled, axis=-1)).astype(_onp.int32)


# stop-token matrix width of the multi-step super-step: per-lane stop
# sets are padded/truncated to this many int32 entries (-1 = unused).
# Requests with more stop ids than this still stop correctly — the host
# settle replay checks the FULL stop set — the device loop just cannot
# freeze the lane early on the overflowed ids (graceful degradation:
# extra iterations, never wrong output).
_STOP_WIDTH = 8


def _stop_matrix(rows, stop_sets):
    """(len(rows), _STOP_WIDTH) int32 stop matrix, padded with -1."""
    m = _onp.full((rows, _STOP_WIDTH), -1, _onp.int32)
    for i, st in enumerate(stop_sets):
        ids = sorted(int(t) for t in st)[:_STOP_WIDTH]
        m[i, :len(ids)] = ids
    return m


def _fresh_key_bits():
    """(2,) uint32 threefry2x32 key data drawn from ``mxnet_tpu.random``'s
    seeded stream — the traced base-key input of the multi-step
    super-step (see ``ops.nn.sample_step``)."""
    import jax

    return _onp.asarray(
        jax.random.key_data(_rng.as_threefry(_rng.next_key()))
    ).astype(_onp.uint32).reshape(2)


class _MultiStepForward(HybridBlock):
    """The compiled decode super-step: up to N decode iterations in ONE
    executable (ROADMAP item 3 — the host round-trip killer).

    Calling convention::

        (tokens (S,1), start_pos (S,), steps_limit (1,), remaining (S,),
         seeds (S,), temps (S,), top_ks (S,), stops (S, _STOP_WIDTH),
         key_bits (2,), [page_table (S,P),] *rings)
        -> (block (S,N), valid (S,), done (S,), *rings)

    The body is a ``lax.while_loop`` whose iteration feeds each lane's
    pending token through the UNCHANGED model cache path (same
    layers/ops as the single-step executable — Pallas decode attention,
    int8 rings, fusion fences all compile per iteration with the
    loop-carried ``start_pos``), samples the successor in-trace
    (``ops.nn.sample_step``: greedy + per-lane temperature/top-k off
    counter-based threefry keys), records it in the (S, N) token block,
    and advances. ``steps_limit`` is a *traced* ceiling: the cond is
    ``(i < steps_limit) & ~all(done)``, so the host degrades N down to 1
    (tight deadlines) through the SAME executable, and the loop exits
    early the moment every lane is done.

    Finished lanes FREEZE instead of masking: a lane that hit a stop id
    or its token budget stops advancing ``(token, position)``, so each
    further iteration recomputes and rewrites byte-identical K/V at its
    frozen position — idempotent by induction (every input of the write
    is unchanged), which is why no masked cache-write variant is needed
    and dead lanes idle harmlessly at full batch width.

    Paged mode hoists the brackets: ONE ``paged_kv_gather`` before the
    loop, rings carried through it, ONE ``paged_kv_scatter`` of length N
    after. Rows past a lane's write extent scatter back the exact bytes
    the gather produced (no-op), and positions past its page budget
    clip onto the null page — both established-safe. Note this fuses
    the brackets into the executable on EVERY rung, including baseline:
    a compiled loop cannot run eager brackets per iteration, so
    multi-step baseline carries greedy token-identity (not the PR-5
    bitwise-vs-ring contract; the deterministic compiler options still
    apply).
    """

    def __init__(self, model, max_seq, steps, path="baseline", quant=None,
                 qindex=(), paged=False, **kwargs):
        super().__init__(**kwargs)
        self.model = model  # child registration shares the params
        self._max_seq = int(max_seq)
        self._steps = int(steps)
        self._path = path
        self._quant = quant
        self._qindex = list(qindex)
        self._paged = bool(paged)
        n_layers = len(model._blocks)
        self._n_cache = n_layers * (4 if quant else 2)

    def forward(self, tokens, start_pos, steps_limit, remaining, seeds,
                temps, top_ks, stops, key_bits, *rest):
        import jax
        import jax.numpy as jnp

        from ..ndarray.ndarray import NDArray

        def raw(x):
            return x._data if isinstance(x, NDArray) else jnp.asarray(x)

        page_table = None
        if self._paged:
            page_table, rest = rest[0], rest[1:]
        flat_cache = rest[:self._n_cache]
        qflat = rest[self._n_cache:]
        pools = None
        if self._paged:
            pools = flat_cache
            flat_cache = [_ops.paged_kv_gather(p, page_table)
                          for p in pools]
        quant_weights = None
        if qflat:
            # same packed int8 side-table reslice as _CacheForward; the
            # slices are loop-invariant captures of the while body
            packed_w, packed_s = qflat
            quant_weights, woff, soff = {}, 0, 0
            for pid, (o, u) in self._qindex:
                quant_weights[pid] = (
                    packed_w[woff:woff + o * u].reshape(o, u),
                    packed_s[soff:soff + o])
                woff += o * u
                soff += o

        n = self._steps
        lanes = tokens.shape[0]
        limit = raw(steps_limit).astype(jnp.int32)[0]
        rem = raw(remaining).astype(jnp.int32)
        stop_m = raw(stops).astype(jnp.int32)
        temps_r = raw(temps).astype(jnp.float32)
        tks_r = raw(top_ks).astype(jnp.int32)
        seeds_r = raw(seeds).astype(jnp.int32)
        kb = raw(key_bits)
        max_seq, quant, path = self._max_seq, self._quant, self._path
        model = self.model

        def body(carry):
            it, cur, pos, done, emitted, block = carry[:6]
            rings = carry[6:]
            cache = KVCache.from_flat([NDArray(r) for r in rings],
                                      max_seq, quant=quant)
            cache.path = path
            cache.quant_weights = quant_weights
            logits = model(NDArray(cur), cache=cache,
                           start_pos=NDArray(pos))
            new_rings = tuple(raw(a) for a in cache.flat())
            lg = raw(logits)[:, 0]  # T = 1: the only position's logits
            nxt = raw(_ops.sample_step(
                NDArray(lg), NDArray(temps_r), NDArray(tks_r),
                NDArray(seeds_r), NDArray(pos), NDArray(kb)))
            active = ~done
            block = block.at[:, it].set(jnp.where(active, nxt, -1))
            emitted = emitted + active.astype(jnp.int32)
            is_stop = jnp.any(stop_m == nxt[:, None], axis=1)
            done = done | (active & is_stop) | (emitted >= rem)
            # advance only lanes still alive AFTER this emission: newly
            # finished lanes freeze at their last written position, so
            # subsequent iterations are byte-identical rewrites
            adv = active & ~done
            cur = jnp.where(adv[:, None], nxt[:, None], cur)
            pos = jnp.where(adv, pos + 1, pos)
            return (it + 1, cur, pos, done, emitted, block) + new_rings

        def cond(carry):
            return (carry[0] < limit) & ~jnp.all(carry[3])

        init = ((jnp.int32(0),
                 raw(tokens).astype(jnp.int32),
                 raw(start_pos).astype(jnp.int32),
                 rem <= 0,
                 jnp.zeros((lanes,), jnp.int32),
                 jnp.full((lanes, n), -1, jnp.int32))
                + tuple(raw(r) for r in flat_cache))
        out = jax.lax.while_loop(cond, body, init)
        done, emitted, block = out[3], out[4], out[5]
        rings = [NDArray(r) for r in out[6:]]
        if self._paged:
            rings = [_ops.paged_kv_scatter(p, page_table, r, start_pos, n)
                     for p, r in zip(pools, rings)]
        return (NDArray(block), NDArray(emitted),
                NDArray(done.astype(jnp.int32))) + tuple(rings)


_DECODE_PATHS = ("baseline", "pallas", "int8")


def resolve_decode_path(decode_path=None):
    """The decode rung a Generator compiles. ``MXNET_SERVE_STRICT_PARITY``
    pins "baseline" (the PR-5 bitwise contract) over everything; otherwise
    an explicit ``decode_path`` argument wins over the
    ``MXNET_SERVE_DECODE_PATH`` flag, and "auto" means the fused-kernel
    "pallas" rung."""
    from .. import config

    if config.get("MXNET_SERVE_STRICT_PARITY"):
        return "baseline"
    path = decode_path
    if path is None:
        path = config.get("MXNET_SERVE_DECODE_PATH")
    if path in (None, "auto"):
        path = "pallas"
    if path not in _DECODE_PATHS:
        raise MXNetError(
            f"decode_path {path!r} not in {_DECODE_PATHS} "
            "(speculative decoding is serve.SpeculativeGenerator, not a "
            "KV-cache path)")
    return path


def _int8_weights_enabled():
    """Resolve MXNET_SERVE_DECODE_INT8_WEIGHTS for the int8 rung. "auto"
    enables int8 weights only where the backend has int8 matrix units
    (tpu/axon — the 394 TOP/s path); on CPU the per-step int8->f32 weight
    convert costs more than the f32 gemm saves, so auto keeps weights f32
    there and the rung's win is the halved KV-ring traffic."""
    import jax

    from .. import config

    flag = str(config.get("MXNET_SERVE_DECODE_INT8_WEIGHTS")).strip().lower()
    if flag == "auto":
        return jax.default_backend() in ("tpu", "axon")
    return flag in ("1", "true", "yes", "on")


def _quantize_serving_weights(model):
    """Pre-quantize the model's serving projections to per-output-channel
    int8 for ``ops.nn.quantized_dense``: returns ``(qindex, qflat)`` — an
    ordered ``(id(param), shape)`` list and exactly two packed NDArrays
    (all int8 weights concatenated flat, all scales concatenated flat)
    that the serving step threads through as call args. Packing keeps the
    per-step call-arg count flat in depth (2, not 2 x 8 x layers); the
    step reslices by the static offsets ``qindex`` implies, which XLA
    fuses away. Models without the llama projection layout fall back to
    KV-only quantization (with a flight-recorder note, so the silent-f32
    case is diagnosable)."""
    from .. import numpy as mnp
    from ..profiler import core as _prof
    from ..profiler import recorder as _recorder

    try:
        params = []
        for blk in model._blocks:
            attn, ffn = blk.attention, blk.ffn
            params += [attn.q_proj.weight, attn.k_proj.weight,
                       attn.v_proj.weight, attn.o_proj.weight,
                       ffn.gate_proj.weight, ffn.up_proj.weight,
                       ffn.down_proj.weight]
        params.append(model.embed.weight if model._tie
                      else model.lm_head.weight)
    except AttributeError:
        _recorder.note("fallback", "serve.decode_fallback",
                       {"reason": "quant_weights_unsupported_model",
                        "model": type(model).__name__})
        _prof.incr_counter("serve.decode_fallbacks", cat="serve")
        return [], []
    qindex, wchunks, schunks = [], [], []
    for p in params:
        w = p.data().asnumpy()
        scale = _onp.maximum(_onp.abs(w).max(axis=1) / 127.0,
                             1e-8).astype(_onp.float32)
        qw = _onp.clip(_onp.round(w / scale[:, None]),
                       -127, 127).astype(_onp.int8)
        qindex.append((id(p), qw.shape))
        wchunks.append(qw.reshape(-1))
        schunks.append(scale)
    qflat = [mnp.array(_onp.concatenate(wchunks)),
             mnp.array(_onp.concatenate(schunks))]
    return qindex, qflat


class Generator:
    """Bucketed KV-cache generation server for decoder LMs.

    Wraps the model into a :class:`_CacheForward` step compiled through an
    :class:`InferenceSession` (breaker, watchdog, fault site, serve-hit
    accounting all apply to every prefill and every decode step).

    Parameters
    ----------
    model : LlamaModel (or any block with ``_blocks[i].attention`` KV
        geometry and a ``cache=``/``start_pos=`` forward).
    max_seq : ring length — prompt + generated tokens must fit.
    batch_buckets / prompt_buckets : the compiled shape lattice.
    decode_path : which rung this generator compiles (see
        :func:`resolve_decode_path`): "baseline" keeps the PR-5 bitwise
        prefill/decode contract on the deterministic runtime; "pallas"
        routes attention through the fused decode kernel on the default
        runtime (tolerance parity); "int8" adds int8 KV rings and (by
        default) int8 projection weights.
    paged : back the KV state with a :class:`~.kv_blocks.PagedKVPool`
        per batch bucket instead of contiguous rings (``None`` reads
        ``MXNET_SERVE_KV_PAGED``). The pool is fully assigned
        (exhaustion-free) with identity page tables and persists across
        requests — stale pages need no zeroing (the attention position
        mask plus prefill's exact overwrite make them unreadable), but
        that persistence also means paged generates on one batch bucket
        must not run concurrently. The baseline rung stays bitwise
        identical to the ring path; dynamic tables, admission, and
        recycling live in :class:`~.scheduler.ContinuousEngine`.
    page_size / kv_pages : pool geometry overrides (see
        :class:`~.kv_blocks.PagedKVPool`).
    """

    def __init__(self, model, max_seq=128, batch_buckets=(1, 2, 4),
                 prompt_buckets=None, pad_id=0, name="llama_decode",
                 decode_path=None, paged=None, page_size=None,
                 kv_pages=None, prefix_cache=None, multistep=None,
                 decode_steps=None):
        from .. import config

        self.model = model
        self.max_seq = int(max_seq)
        self.batch_buckets = tuple(sorted(int(b) for b in batch_buckets))
        if prompt_buckets is None:
            prompt_buckets, p = [], 16
            while p < self.max_seq:
                prompt_buckets.append(p)
                p *= 2
            prompt_buckets.append(self.max_seq)
        self.prompt_buckets = tuple(sorted(set(int(p)
                                               for p in prompt_buckets)))
        if self.prompt_buckets[-1] > self.max_seq:
            raise MXNetError("prompt bucket exceeds max_seq")
        self.pad_id = int(pad_id)
        self.decode_path = resolve_decode_path(decode_path)
        self._quant = "int8" if self.decode_path == "int8" else None
        self._qindex, self._qflat = [], []
        if self._quant and _int8_weights_enabled():
            self._qindex, self._qflat = _quantize_serving_weights(model)
        if prefix_cache is None:
            prefix_cache = bool(config.get("MXNET_SERVE_PREFIX_CACHE"))
        self._prefix_on = bool(prefix_cache)
        if self._prefix_on and paged is False:
            raise MXNetError(
                "prefix_cache requires the paged KV pool (prefix pages "
                "are shared pool pages); don't pass paged=False with "
                "prefix_cache on")
        self._paged = (bool(config.get("MXNET_SERVE_KV_PAGED"))
                       if paged is None else bool(paged)) or self._prefix_on
        self._page_size = page_size
        self._kv_pages = kv_pages
        self._prefix = {}  # batch bucket -> PrefixCache over its pool
        # speculative decoding sets this to k+1: its verify/draft rounds
        # write that many ring positions past the accepted prefix, so
        # per-request page budgets must cover them
        self._budget_headroom = 0
        # fast rungs fuse the paging brackets into the step; the strict
        # baseline rung keeps the RING executable and runs the brackets
        # as standalone exact copies in _run — that's what makes paged
        # baseline decode bitwise identical to ring decode
        self._fused_paged = self._paged and self.decode_path != "baseline"
        self._step = _CacheForward(model, self.max_seq,
                                   path=self.decode_path,
                                   quant=self._quant, qindex=self._qindex,
                                   paged=self._fused_paged)
        # bucketing is done here (cache shapes are part of the lattice);
        # the session provides the protected raw-run path. Only the strict
        # baseline rung pins the deterministic compiler options — the
        # pinned CPU legacy runtime is itself a decode-throughput tax the
        # fast rungs exist to remove.
        self.session = InferenceSession(
            self._step, batch_buckets=self.batch_buckets,
            seq_buckets=self.prompt_buckets, pad_value=self.pad_id,
            name=name, deterministic=(self.decode_path == "baseline"))
        self.metrics = self.session.metrics
        self.metrics.set_decode_path(self.decode_path)
        # decode critical-path ledger (tentpole PR 16): observations
        # gated on _attr.ENABLED, the object always present for readout
        self.ledger = _attr.Ledger(name)
        self._zero_caches = {}  # batch bucket -> shared zeroed rings
        # multi-step decode (tentpole PR 19): the super-step lives in its
        # own InferenceSession (one more compiled signature per batch
        # bucket, frozen at warmup like everything else). The single-step
        # session stays — parity tests and the N=1 overhead bound compare
        # against it, and prefill always runs through it.
        if multistep is None:
            multistep = bool(config.get("MXNET_SERVE_MULTISTEP"))
        self._multistep = bool(multistep)
        if decode_steps is None:
            decode_steps = int(config.get("MXNET_SERVE_DECODE_STEPS"))
        self.decode_steps = max(1, int(decode_steps))
        self._msession = None
        self._itl_est = None  # EMA seconds per decode iteration
        if self._multistep:
            # paged=self._paged (not _fused_paged): a compiled loop cannot
            # run eager brackets per iteration, so the super-step fuses
            # them on every rung including baseline (greedy token-identity
            # contract, see _MultiStepForward)
            self._mstep = _MultiStepForward(
                model, self.max_seq, self.decode_steps,
                path=self.decode_path, quant=self._quant,
                qindex=self._qindex, paged=self._paged)
            self._msession = InferenceSession(
                self._mstep, batch_buckets=self.batch_buckets,
                seq_buckets=(1,), pad_value=self.pad_id,
                name=f"{name}_multi",
                deterministic=(self.decode_path == "baseline"))

    def _fresh_cache(self, batch_bucket):
        """Zeroed rings for one batch bucket, allocated once and shared
        by every request: device arrays are immutable and prefill/decode
        return functionally-updated rings without touching their input
        cache, so reuse is safe — and the serving hot path skips
        2 x num_layers allocations + zero-fills per request.

        Paged mode returns the bucket's persistent
        :class:`~.kv_blocks.PagedKVPool` instead — fully assigned with
        identity page tables (slot ``s`` owns pages ``[1 + s*N,
        1 + (s+1)*N)``), mutated in place by :meth:`_run`. Stale page
        contents between requests are safe for the same reason ring
        garbage is: the attention mask only admits positions the current
        request has actually written."""
        if self._paged:
            from .kv_blocks import PagedKVPool
            from .prefix_cache import PrefixCache

            pool = self._zero_caches.get(batch_bucket)
            if pool is None:
                pool = PagedKVPool(self.model, batch_bucket, self.max_seq,
                                   page_size=self._page_size,
                                   num_pages=self._kv_pages,
                                   quant=self._quant)
                if self._prefix_on:
                    # prefix mode: slots are assigned per generate()
                    # (per-request budgets + trie-matched prefix pages)
                    # instead of pinned identity tables, and the bucket
                    # gets its radix trie over this pool
                    self._prefix[batch_bucket] = PrefixCache(
                        pool, name=f"{self.session.name}_prefix")
                else:
                    for s in range(batch_bucket):
                        pool.assign(s, self.max_seq)
                self._zero_caches[batch_bucket] = pool
                self.metrics.set_kv_cache_bytes(
                    sum(c.nbytes()
                        for c in self._zero_caches.values()))
                self.metrics.set_kv_pages(pool.pages_used,
                                          pool.pages_free)
            return pool
        cache = self._zero_caches.get(batch_bucket)
        if cache is None:
            cache = self._zero_caches.setdefault(
                batch_bucket,
                KVCache.alloc(self.model, batch_bucket, self.max_seq,
                              quant=self._quant))
            self.metrics.set_kv_cache_bytes(
                sum(c.nbytes() for c in self._zero_caches.values()))
        return cache

    # -- phase helpers (also the parity-test surface) -----------------------
    def _run(self, tokens, start_pos, last_idx, cache):
        from .. import numpy as mnp

        if self._paged:
            toks = mnp.array(_onp.asarray(tokens, _onp.int32))
            sp = mnp.array(_onp.asarray(start_pos, _onp.int32))
            li = mnp.array(_onp.asarray(last_idx, _onp.int32))
            if not self._fused_paged:
                # strict rung: run the paging brackets as standalone
                # exact-copy device ops around the UNCHANGED ring
                # executable -> bitwise identical to ring decode
                table = cache.table_nd()
                rings = [_ops.paged_kv_gather(p, table)
                         for p in cache.flat()]
                out = self.session.run(toks, sp, li, *rings,
                                       *self._qflat)
                t_len = _onp.asarray(tokens).shape[1]
                cache.update_from_flat([
                    _ops.paged_kv_scatter(p, table, r, sp, t_len)
                    for p, r in zip(cache.flat(), out[1:])])
                return out[0], cache
            out = self.session.run(toks, sp, li, cache.table_nd(),
                                   *cache.flat(), *self._qflat)
            cache.update_from_flat(out[1:])
            return out[0], cache
        out = self.session.run(
            mnp.array(_onp.asarray(tokens, _onp.int32)),
            mnp.array(_onp.asarray(start_pos, _onp.int32)),
            mnp.array(_onp.asarray(last_idx, _onp.int32)),
            *cache.flat(), *self._qflat)
        logits, flat = out[0], out[1:]
        return logits, KVCache.from_flat(flat, self.max_seq,
                                         quant=self._quant)

    def prefill(self, prompts, prompt_lens, cache):
        """Run the prompt block through the cache path. ``prompts`` is a
        host (B, T_bucket) int array (already padded), ``prompt_lens`` the
        (B,) real lengths. Returns ((B, vocab) last-real-position logits,
        updated cache)."""
        b = len(prompt_lens)
        zeros = _onp.zeros(b, _onp.int32)
        last = _onp.asarray(prompt_lens, _onp.int32) - 1
        return self._run(prompts, zeros, last, cache)

    def decode_step(self, tokens, positions, cache):
        """One T=1 decode step: ``tokens`` (B,) the just-sampled ids,
        ``positions`` (B,) their absolute positions. Returns the next
        (B, vocab) logits and the updated cache. The ``serve:decode``
        fault site fires once per step, so the chaos harness can kill a
        generation stream mid-decode (distinct from ``serve:execute``,
        which also covers prefill)."""
        _faults.fault_point("serve:decode",
                            {"session": self.session.name})
        toks = _onp.asarray(tokens, _onp.int32).reshape(-1, 1)
        zeros = _onp.zeros(len(toks), _onp.int32)
        return self._run(toks, _onp.asarray(positions, _onp.int32),
                         zeros, cache)

    def decode_super(self, tokens, positions, steps_limit, remaining,
                     seeds, temps, top_ks, stops, key_bits, cache,
                     stamps=None):
        """One multi-step super-step: up to ``steps_limit`` decode
        iterations inside the compiled loop (see
        :class:`_MultiStepForward`). Returns ``(block, valid, done,
        cache)`` as host numpy — the (B, N) token block, per-lane valid
        counts and done flags the caller settles in one pass. Fires the
        same ``serve:decode`` fault site as :meth:`decode_step` (once
        per super-step — the host-visit granularity).

        ``stamps``: optional list; one ``(perf_counter, thread_wait_ns)``
        pair is appended right after the executable dispatch returns
        (before the blocking block fetch), so callers can split
        dispatch from device time in the attribution ledger without
        reimplementing the call."""
        from .. import numpy as mnp

        if self._msession is None:
            raise MXNetError(
                "decode_super needs multistep=True (or "
                "MXNET_SERVE_MULTISTEP=1) at construction")
        _faults.fault_point("serve:decode",
                            {"session": self._msession.name})
        b = len(positions)
        args = [
            mnp.array(_onp.asarray(tokens, _onp.int32).reshape(b, 1)),
            mnp.array(_onp.asarray(positions, _onp.int32)),
            mnp.array(_onp.asarray([steps_limit], _onp.int32)),
            mnp.array(_onp.asarray(remaining, _onp.int32)),
            mnp.array(_onp.asarray(seeds, _onp.int32)),
            mnp.array(_onp.asarray(temps, _onp.float32)),
            mnp.array(_onp.asarray(top_ks, _onp.int32)),
            mnp.array(_onp.asarray(stops, _onp.int32)),
            mnp.array(_onp.asarray(key_bits, _onp.uint32)),
        ]
        if self._paged:
            out = self._msession.run(*args, cache.table_nd(),
                                     *cache.flat(), *self._qflat)
            cache.update_from_flat(out[3:])
        else:
            out = self._msession.run(*args, *cache.flat(), *self._qflat)
            cache = KVCache.from_flat(out[3:], self.max_seq,
                                      quant=self._quant)
        if stamps is not None:
            stamps.append((time.perf_counter(), _attr.thread_wait_ns()))
        block = _onp.asarray(out[0].asnumpy(), _onp.int32)
        valid = _onp.asarray(out[1].asnumpy(), _onp.int32)
        done = _onp.asarray(out[2].asnumpy(), _onp.int32)
        return block, valid, done, cache

    # -- the serving API ----------------------------------------------------
    def _pad_prompts(self, prompts):
        lens = _onp.asarray([len(p) for p in prompts], _onp.int32)
        if int(lens.min()) < 1:
            raise MXNetError("empty prompt (need >= 1 token)")
        t_bucket = pick_bucket(int(lens.max()), self.prompt_buckets)
        b_bucket = pick_bucket(len(prompts), self.batch_buckets)
        toks = _onp.full((b_bucket, t_bucket), self.pad_id, _onp.int32)
        for i, p in enumerate(prompts):
            toks[i, :len(p)] = p
        full_lens = _onp.ones(b_bucket, _onp.int32)
        full_lens[:len(prompts)] = lens
        # dead batch lanes replay prompt 0's first token at length 1
        toks[len(prompts):, 0] = toks[0, 0]
        return toks, full_lens, b_bucket

    # -- prefix-cache plumbing (PR 14) --------------------------------------
    def _prefix_begin(self, prompts, toks, lens, b_bucket, max_new):
        """Reserve the batch's slots in the bucket's pool. With the
        prefix trie on, each real row's longest cached prefix arrives as
        shared (refcounted) pages at the front of its table row and its
        ``matched`` count says how many prompt tokens skip prefill; pool
        pressure LRU-evicts cached prefixes (never the pages just
        matched) before surfacing :class:`PoolExhausted`. Returns
        ``(cache, matched)``; non-prefix mode returns the persistent
        fully-assigned pool and all-zero ``matched``."""
        cache = self._fresh_cache(b_bucket)
        matched = _onp.zeros(b_bucket, _onp.int32)
        if not self._prefix_on:
            return cache, matched
        trie = self._prefix[b_bucket]
        try:
            for s in range(b_bucket):
                if s < len(prompts):
                    row = [int(t) for t in prompts[s]]
                    m, pages = trie.match(row)
                else:  # dead padding lane: 1-token prompt, never cached
                    row, m, pages = [int(toks[s, 0])], 0, ()
                budget = min(len(row) + int(max_new)
                             + self._budget_headroom, self.max_seq)
                try:
                    cache.assign_with_prefix(s, budget, pages)
                except PoolExhausted:
                    shortfall = (cache.pages_for(budget) - len(pages)
                                 - cache.pages_free)
                    if trie.reclaim(max(shortfall, 1),
                                    exclude=pages) == 0:
                        raise
                    cache.assign_with_prefix(s, budget, pages)
                matched[s] = m
                if s < len(prompts):
                    self.metrics.observe_prefix(m)
        except BaseException:
            for s in range(b_bucket):
                cache.release(s)
            raise
        return cache, matched

    def _prefix_prefill(self, toks, lens, matched, cache):
        """Prefill only each row's un-cached tail: row ``s``'s tokens
        ``[matched[s]:lens[s]]`` at ``start_pos=matched[s]`` (per-row).
        Chunked prefill at an arbitrary start_pos is bit-identical to
        full prefill (the PR-5 parity contract), and the tail bucket
        comes from the same prompt lattice warmup compiled — zero new
        signatures. All-miss batches take the unchanged full path."""
        if not matched.any():
            return self.prefill(toks, lens, cache)
        tail_lens = (_onp.asarray(lens, _onp.int32)
                     - _onp.asarray(matched, _onp.int32))
        t_bucket = pick_bucket(int(tail_lens.max()), self.prompt_buckets)
        tails = _onp.full((len(lens), t_bucket), self.pad_id, _onp.int32)
        for s in range(len(lens)):
            tails[s, :tail_lens[s]] = toks[s, matched[s]:lens[s]]
        return self._run(tails, matched, tail_lens - 1, cache)

    def _prefix_release(self, prompts, b_bucket, cache, ok):
        """Retire the batch's slots. On a clean run the trie first
        adopts each real prompt's full pages (increfs while the slot
        still pins them) so later requests sharing the prefix skip that
        much prefill; then every slot's references drop — pages the trie
        kept survive, the rest recycle."""
        if not self._prefix_on:
            return
        trie = self._prefix[b_bucket]
        if ok:
            table = cache.table()
            for s, p in enumerate(prompts):
                trie.insert([int(t) for t in p], table[s])
        for s in range(b_bucket):
            cache.release(s)
        self.metrics.set_prefix_gauges(cache.pages_shared,
                                       trie.pages_held, trie.evictions)
        self.metrics.set_kv_pages(cache.pages_used, cache.pages_free)

    def generate(self, prompts, max_new_tokens=32, temperature=0.0,
                 top_k=None, stop_ids=(), deadlines=None):
        """Traced entry point: when request tracing is on and no ambient
        trace is active (a direct ``generate()`` call, not one under a
        traced batcher runner), open a ``serve.generate[<name>]`` lane so
        the prefill/decode-step spans land somewhere; under a batcher the
        representative request's lane is already active and is used
        instead. See :meth:`_generate` for the actual semantics."""
        own = None
        if _trace.ENABLED and _trace.current() is None:
            own = _trace.start_trace(f"serve.generate[{self.session.name}]",
                                     args={"prompts": len(prompts)})
        try:
            with _trace.activate(own):
                out = self._generate(prompts, max_new_tokens=max_new_tokens,
                                     temperature=temperature, top_k=top_k,
                                     stop_ids=stop_ids, deadlines=deadlines)
        except Exception as exc:
            if own is not None:
                own.finish(error=exc)
            raise
        if own is not None:
            own.finish()
        return out

    def _generate(self, prompts, max_new_tokens=32, temperature=0.0,
                  top_k=None, stop_ids=(), deadlines=None):
        """Generate continuations for a batch of prompts (lists of ids).

        ``deadlines`` (optional) carries absolute ``time.monotonic()``
        deadlines — one scalar for the whole batch or one per prompt. A
        row whose deadline passes is **retired between decode steps**: it
        stops consuming decode work, keeps the tokens generated so far,
        and lands in ``info["deadline_expired"]`` so the serving layer can
        settle its future with :class:`~.engine.DeadlineExceeded` instead
        of delivering late. When every live row has expired the whole
        decode loop exits early. ``None`` (default) checks nothing — the
        original semantics, bitwise included.

        Returns ``(outputs, info)``: per-prompt generated id lists (stop
        token excluded) and a stats dict (tokens/s, per-phase wall time,
        expired row indices).
        """
        t_start = time.perf_counter()
        toks, lens, b_bucket = self._pad_prompts(prompts)
        n_real = len(prompts)
        max_new = int(max_new_tokens)
        if int(lens.max()) + max_new > self.max_seq:
            raise MXNetError(
                f"prompt ({int(lens.max())}) + max_new_tokens ({max_new}) "
                f"exceeds max_seq ({self.max_seq})")
        if deadlines is not None:
            try:
                deadlines = [float(d) for d in deadlines]
            except TypeError:
                deadlines = [float(deadlines)] * n_real
            if len(deadlines) != n_real:
                raise MXNetError(
                    f"generate() got {len(deadlines)} deadlines for "
                    f"{n_real} prompts")
        cache, matched = self._prefix_begin(prompts, toks, lens, b_bucket,
                                            max_new)
        run_ok = False
        try:
            with _attr.phase_scope("prefill"), \
                    _trace.span("serve::prefill", {"batch": n_real}):
                logits, cache = self._prefix_prefill(toks, lens, matched,
                                                     cache)
                # the step-0 sample blocks on the PREFILL logits: its
                # device time is prefill wall — the steady-state decode
                # rate and the attribution ledger both exclude it (same
                # call order as before — one sample per entered step, so
                # the RNG key stream is unchanged)
                next_ids = sample_tokens(logits, temperature=temperature,
                                         top_k=top_k)
            t_prefill = time.perf_counter()

            out = [[] for _ in range(n_real)]
            stopped = [False] * n_real
            expired = [False] * n_real
            positions = lens.copy()  # next write position per row
            stop = set(int(s) for s in stop_ids)
            n_decoded = 0
            n_visits = 0
            if self._multistep:
                cache, n_decoded, n_visits = self._decode_loop_multi(
                    next_ids, positions, out, stopped, expired, stop,
                    max_new, temperature, top_k, deadlines, cache,
                    n_real, b_bucket)
            # multistep consumed the whole budget above; the single-step
            # loop below then runs zero iterations
            for step in range(0 if self._multistep else max_new):
                th0 = time.perf_counter()
                for i in range(n_real):
                    if stopped[i]:
                        continue
                    tid = int(next_ids[i])
                    if tid in stop:
                        stopped[i] = True
                    else:
                        out[i].append(tid)
                if deadlines is not None:
                    # retire expired rows at the step boundary: their
                    # decode budget is spent — burning further T=1 passes
                    # for output nobody will read is the overload failure
                    # mode
                    now = time.monotonic()
                    for i in range(n_real):
                        if not stopped[i] and now >= deadlines[i]:
                            stopped[i] = True
                            expired[i] = True
                            self.metrics.observe_deadline("decode")
                if all(stopped) or step == max_new - 1:
                    # the last sampled token needs no successor logits —
                    # running decode_step here would be a discarded T=1
                    # pass
                    break
                live = n_real - sum(stopped)
                attributing = _attr.ENABLED
                if attributing:
                    # per-step token accounting above is host work
                    # between device calls: the schedule bucket
                    self.ledger.observe_schedule(
                        (time.perf_counter() - th0) * 1e3)
                args = {"step": step, "live": live}
                with _attr.phase_scope("decode"):
                    t1 = time.perf_counter()
                    w1 = _attr.thread_wait_ns() if attributing else 0
                    with _trace.span("serve::decode_step", args):
                        logits, cache = self.decode_step(next_ids,
                                                         positions, cache)
                        t2 = time.perf_counter()
                        w2 = _attr.thread_wait_ns() if attributing else 0
                        # the next step's sample is THIS step's blocking
                        # device fetch — inside the span, so the four
                        # phases partition the span wall
                        next_ids = sample_tokens(logits,
                                                 temperature=temperature,
                                                 top_k=top_k)
                        t3 = time.perf_counter()
                        if attributing:
                            w3 = _attr.thread_wait_ns()
                            dispatch_ms = max(
                                0.0, (t2 - t1) * 1e3 - (w2 - w1) / 1e6)
                            device_ms = (t3 - t2) * 1e3
                            wait_ms = max(0.0, (w2 - w1) / 1e6)
                            args.update(host_ms=0.0,
                                        dispatch_ms=round(dispatch_ms, 4),
                                        device_ms=round(device_ms, 4),
                                        wait_ms=round(wait_ms, 4))
                            self.ledger.observe_step(0.0, dispatch_ms,
                                                     device_ms, wait_ms,
                                                     live=live)
                self.metrics.observe_itl((t3 - t1) * 1e3, live=live)
                positions = positions + 1
                n_decoded += 1
                n_visits += 1
            run_ok = True
        finally:
            self._prefix_release(prompts, b_bucket, cache, run_ok)
        t_done = time.perf_counter()
        decode_s = t_done - t_prefill
        n_tokens = sum(len(o) for o in out)
        self.metrics.observe_tokens(n_tokens, decode_s)
        if _attr.ENABLED:
            self.metrics.set_attribution(
                self.ledger.host_overhead_fraction(),
                self.ledger.device_ms_per_token())
        info = {
            "prefill_ms": (t_prefill - t_start) * 1e3,
            "decode_ms": decode_s * 1e3,
            "decode_steps": n_decoded,
            "decode_visits": n_visits,
            "tokens_s": n_tokens / decode_s if decode_s > 0 else 0.0,
            "total_ms": (t_done - t_start) * 1e3,
            "deadline_expired": [i for i in range(n_real) if expired[i]],
        }
        return out, info

    def _steps_limit(self, deadlines, stopped, n_real):
        """The next super-step's dynamic iteration ceiling: N, degraded
        to 1 when some live row's deadline could not survive a full
        N-iteration super-step (estimated off the per-iteration EMA) —
        the PR-6 504 retirement latency stays bounded by about one
        decode iteration, through the SAME compiled executable
        (``steps_limit`` is a traced input, never a new signature)."""
        n = self.decode_steps
        if deadlines is None or self._itl_est is None:
            return n
        now = time.monotonic()
        slack = min((deadlines[i] - now for i in range(n_real)
                     if not stopped[i]), default=None)
        if slack is not None and slack < self._itl_est * n:
            return 1
        return n

    def _decode_loop_multi(self, next_ids, positions, out, stopped,
                           expired, stop, max_new, temperature, top_k,
                           deadlines, cache, n_real, b_bucket):
        """The multi-step decode loop behind :meth:`_generate`: the
        step-0 token is emitted host-side (exactly like single-step),
        then every further token comes out of compiled super-steps —
        one host visit per block of up to ``decode_steps`` tokens,
        settled by replaying :class:`_Slot`-style emission over the
        returned token block. Token streams are invariant to the
        super-step boundary (counter-based in-trace keys), so N=8 and
        N=1 multistep output is identical, and greedy output matches
        the single-step loop token for token."""
        # step-0 emission: the prefill-sampled token, one per row
        for i in range(n_real):
            tid = int(next_ids[i])
            if tid in stop:
                stopped[i] = True
            else:
                out[i].append(tid)
                if len(out[i]) >= max_new:
                    stopped[i] = True
        pending = _onp.zeros(b_bucket, _onp.int32)
        pending[:len(next_ids)] = _onp.asarray(next_ids, _onp.int32)
        temp = float(temperature) if temperature is not None else 0.0
        # greedy runs never consume a host RNG draw (matching the
        # single-step loop, whose greedy path draws no keys either)
        key_bits = (_fresh_key_bits() if temp > 0.0
                    else _onp.zeros(2, _onp.uint32))
        seeds = _onp.arange(b_bucket, dtype=_onp.int32)
        temps = _onp.full(b_bucket, max(temp, 0.0), _onp.float32)
        tks = _onp.full(b_bucket, int(top_k) if top_k else 0, _onp.int32)
        stops_m = _stop_matrix(b_bucket, [stop] * b_bucket)
        n_decoded = n_visits = 0
        while True:
            if deadlines is not None:
                now = time.monotonic()
                for i in range(n_real):
                    if not stopped[i] and now >= deadlines[i]:
                        stopped[i] = True
                        expired[i] = True
                        self.metrics.observe_deadline("decode")
            if all(stopped):
                break
            th0 = time.perf_counter()
            remaining = _onp.zeros(b_bucket, _onp.int32)
            for i in range(n_real):
                if not stopped[i]:
                    remaining[i] = max_new - len(out[i])
            limit = self._steps_limit(deadlines, stopped, n_real)
            live = n_real - sum(stopped)
            attributing = _attr.ENABLED
            if attributing:
                self.ledger.observe_schedule(
                    (time.perf_counter() - th0) * 1e3)
            args = {"steps": limit, "live": live}
            with _attr.phase_scope("decode"):
                t1 = time.perf_counter()
                w1 = _attr.thread_wait_ns() if attributing else 0
                with _trace.span("serve::decode_step", args):
                    stamps = []
                    block, valid, _done, cache = self.decode_super(
                        pending, positions, limit, remaining, seeds,
                        temps, tks, stops_m, key_bits, cache,
                        stamps=stamps)
                    t3 = time.perf_counter()
                    w3 = _attr.thread_wait_ns() if attributing else 0
                    steps_run = int(valid.max()) if valid.size else 0
                    n_tok = 0
                    for i in range(n_real):
                        if stopped[i]:
                            continue
                        k = int(valid[i])
                        n_tok += k
                        for j in range(k):
                            tid = int(block[i, j])
                            if tid in stop:
                                stopped[i] = True
                                break
                            out[i].append(tid)
                            pending[i] = tid
                            if len(out[i]) >= max_new:
                                stopped[i] = True
                                break
                        positions[i] += k
                    if attributing:
                        t4 = time.perf_counter()
                        w4 = _attr.thread_wait_ns()
                        t2, w2 = stamps[0]
                        dispatch_ms = max(
                            0.0, (t2 - t1) * 1e3 - (w2 - w1) / 1e6)
                        device_ms = (t3 - t2) * 1e3
                        host_ms = max(
                            0.0, (t4 - t3) * 1e3 - (w4 - w3) / 1e6)
                        wait_ms = max(
                            0.0, ((w2 - w1) + (w4 - w3)) / 1e6)
                        args.update(host_ms=round(host_ms, 4),
                                    dispatch_ms=round(dispatch_ms, 4),
                                    device_ms=round(device_ms, 4),
                                    wait_ms=round(wait_ms, 4),
                                    tokens=n_tok)
                        self.ledger.observe_step(
                            host_ms, dispatch_ms, device_ms, wait_ms,
                            live=live, tokens=n_tok)
            if steps_run > 0:
                # k amortized token-to-token gaps, not one giant gap
                self.metrics.observe_itl((t3 - t1) * 1e3, live=live,
                                         tokens=steps_run)
                est = (t3 - t1) / steps_run
                self._itl_est = (est if self._itl_est is None
                                 else 0.5 * self._itl_est + 0.5 * est)
            n_decoded += steps_run
            n_visits += 1
        return cache, n_decoded, n_visits

    # -- warmup / invariants -------------------------------------------------
    def warmup(self):
        """Compile every (batch bucket x prompt bucket) prefill and every
        batch bucket's decode step — plus, in multistep mode, every batch
        bucket's super-step; freezes the signature sets so
        ``assert_no_recompiles`` guards steady state."""
        t0 = time.perf_counter()
        for bb in self.batch_buckets:
            for pb in self.prompt_buckets:
                cache = self._fresh_cache(bb)
                toks = _onp.zeros((bb, pb), _onp.int32)
                lens = _onp.ones(bb, _onp.int32)
                logits, cache = self.prefill(toks, lens, cache)
                if pb == self.prompt_buckets[0]:
                    ids = _onp.zeros(bb, _onp.int32)
                    self.decode_step(ids, lens, cache)
                    if self._multistep:
                        # remaining=0: the loop replays zero iterations
                        # but the body still traces/compiles in full
                        self.decode_super(
                            ids, lens, self.decode_steps,
                            _onp.zeros(bb, _onp.int32),
                            _onp.zeros(bb, _onp.int32),
                            _onp.zeros(bb, _onp.float32),
                            _onp.zeros(bb, _onp.int32),
                            _onp.full((bb, _STOP_WIDTH), -1, _onp.int32),
                            _onp.zeros(2, _onp.uint32), cache)
        self.session.freeze_signatures()
        sigs = self.session.signature_count()
        if self._msession is not None:
            self._msession.freeze_signatures()
            sigs += self._msession.signature_count()
        return {"signatures": sigs,
                "wall_s": time.perf_counter() - t0}

    def assert_no_recompiles(self):
        self.session.assert_no_recompiles()
        if self._msession is not None:
            self._msession.assert_no_recompiles()

    def stats(self):
        out = self.session.stats()
        if self._msession is not None:
            out["multistep"] = self._msession.stats()
            out["decode_steps"] = self.decode_steps
        return out


class SpeculativeGenerator:
    """Speculative decoding (Leviathan et al.): a cheap draft model
    proposes ``k`` tokens per round, the target model scores the whole
    block in ONE (k+1)-wide step, and the longest proposal prefix that
    matches the target's greedy choices is accepted plus one
    correction/bonus token — so each target pass emits between 1 and k+1
    tokens instead of exactly 1.

    Greedy-only by construction: with argmax acceptance the emitted
    sequence is **token-identical** to non-speculative greedy decoding for
    *any* draft model (a bad draft only costs speed, never output). The
    proof is inductive: the accepted prefix always equals the target's own
    greedy chain, and the correction token is the target's argmax
    conditioned on exactly that chain.

    No cache rollback is needed on rejection: ``cached_attention`` masks
    ring positions ``> start_pos + t``, so the K/V of rejected proposals
    is dead weight that the next round's writes overwrite before any read
    reaches it. Everything reuses the bucketed session machinery — the
    target and draft are plain :class:`Generator` s, the verify step is a
    third :class:`InferenceSession` compiled at T = k+1, and
    :meth:`assert_no_recompiles` spans all three.
    """

    def __init__(self, model, draft_model, k=None, max_seq=128,
                 batch_buckets=(1, 2, 4), prompt_buckets=None, pad_id=0,
                 name="llama_spec", decode_path=None, paged=None,
                 page_size=None, kv_pages=None, prefix_cache=None,
                 multistep=None):
        from .. import config

        self.k = int(k) if k is not None else int(
            config.get("MXNET_SERVE_SPEC_TOKENS"))
        if self.k < 1:
            raise MXNetError("speculative decoding needs k >= 1")
        if multistep is None:
            multistep = bool(config.get("MXNET_SERVE_MULTISTEP"))
        self._multistep = bool(multistep)
        self.target = Generator(
            model, max_seq=max_seq, batch_buckets=batch_buckets,
            prompt_buckets=prompt_buckets, pad_id=pad_id, name=name,
            decode_path=decode_path, paged=paged, page_size=page_size,
            kv_pages=kv_pages, prefix_cache=prefix_cache,
            multistep=False)
        # multistep: the whole draft-propose phase of a round IS one
        # super-step — k proposal iterations plus the (k+1)-th that
        # writes d_k's K/V run inside the draft's compiled loop, so a
        # round costs 2 host visits (draft block + verify) instead of
        # k+2. The target stays single-step (prefill + verify are its
        # only executables; it never runs a token loop here).
        self.draft = Generator(
            draft_model, max_seq=max_seq, batch_buckets=batch_buckets,
            prompt_buckets=prompt_buckets, pad_id=pad_id,
            name=f"{name}_draft", decode_path=decode_path, paged=paged,
            page_size=page_size, kv_pages=kv_pages,
            prefix_cache=prefix_cache, multistep=self._multistep,
            decode_steps=self.k + 1)
        # draft rounds write k+1 positions past the accepted prefix and
        # the verify block writes k+1 target positions — per-request
        # page budgets in prefix mode must cover that overhang
        self.target._budget_headroom = self.k + 1
        self.draft._budget_headroom = self.k + 1
        self.decode_path = self.target.decode_path
        self.max_seq = self.target.max_seq
        self.batch_buckets = self.target.batch_buckets
        self.pad_id = self.target.pad_id
        self._verify_step = _CacheForward(
            model, self.max_seq, path=self.decode_path,
            quant=self.target._quant, qindex=self.target._qindex,
            all_logits=True)
        self._verify = InferenceSession(
            self._verify_step, batch_buckets=self.batch_buckets,
            seq_buckets=(self.k + 1,), pad_value=self.pad_id,
            name=f"{name}_verify",
            deterministic=(self.decode_path == "baseline"))
        self.metrics = self.target.metrics

    def _verify_run(self, tokens_blk, start_pos, cache):
        """One target pass over the (B, k+1) block [pending, d_1..d_k] at
        per-row ``start_pos``; returns the full (B, k+1, vocab) logits and
        the updated target cache. A paged target pool is bracketed with
        the standalone exact-copy gather/scatter ops around the
        ring-shaped verify executable (the strict-rung pattern from
        :meth:`Generator._run`), writing k+1 rows at per-row start_pos —
        so draft and target share the same prefix pages the trie
        handed out at admission."""
        from .. import numpy as mnp

        blk = _onp.asarray(tokens_blk, _onp.int32)
        toks = mnp.array(blk)
        sp = mnp.array(_onp.asarray(start_pos, _onp.int32))
        li = mnp.array(_onp.zeros(len(blk), _onp.int32))
        if self.target._paged:
            table = cache.table_nd()
            rings = [_ops.paged_kv_gather(p, table)
                     for p in cache.flat()]
            out = self._verify.run(toks, sp, li, *rings,
                                   *self.target._qflat)
            cache.update_from_flat([
                _ops.paged_kv_scatter(p, table, r, sp, blk.shape[1])
                for p, r in zip(cache.flat(), out[1:])])
            return out[0], cache
        out = self._verify.run(toks, sp, li, *cache.flat(),
                               *self.target._qflat)
        logits, flat = out[0], out[1:]
        return logits, KVCache.from_flat(flat, self.max_seq,
                                         quant=self.target._quant)

    def generate(self, prompts, max_new_tokens=32, temperature=0.0,
                 top_k=None, stop_ids=(), deadlines=None):
        """Same contract as :meth:`Generator.generate` (greedy only):
        per-prompt generated id lists plus a stats dict — with
        ``rounds``, ``draft_steps``, ``verify_steps`` and the measured
        ``acceptance_rate`` added."""
        if temperature is not None and temperature > 0.0:
            raise MXNetError(
                "SpeculativeGenerator is greedy-only: sampled acceptance "
                "needs the rejection-sampling correction this build does "
                "not implement (temperature must be 0)")
        t_start = time.perf_counter()
        toks, lens, b_bucket = self.target._pad_prompts(prompts)
        n_real = len(prompts)
        max_new = int(max_new_tokens)
        # +k+1 headroom: the last round's verify block writes k+1 ring
        # positions past the accepted prefix
        if int(lens.max()) + max_new + self.k + 1 > self.max_seq:
            raise MXNetError(
                f"prompt ({int(lens.max())}) + max_new_tokens ({max_new}) "
                f"+ speculative headroom ({self.k + 1}) exceeds max_seq "
                f"({self.max_seq})")
        if deadlines is not None:
            try:
                deadlines = [float(d) for d in deadlines]
            except TypeError:
                deadlines = [float(deadlines)] * n_real
            if len(deadlines) != n_real:
                raise MXNetError(
                    f"generate() got {len(deadlines)} deadlines for "
                    f"{n_real} prompts")
        tcache, tmatched = self.target._prefix_begin(
            prompts, toks, lens, b_bucket, max_new)
        try:
            dcache, dmatched = self.draft._prefix_begin(
                prompts, toks, lens, b_bucket, max_new)
        except BaseException:
            self.target._prefix_release(prompts, b_bucket, tcache, False)
            raise
        run_ok = False
        try:
            with _trace.span("serve::prefill", {"batch": n_real}):
                logits, tcache = self.target._prefix_prefill(
                    toks, lens, tmatched, tcache)
                _, dcache = self.draft._prefix_prefill(
                    toks, lens, dmatched, dcache)
            t_prefill = time.perf_counter()

            pending = sample_tokens(logits)  # (b_bucket,) greedy
            out = [[] for _ in range(n_real)]
            stopped = [False] * b_bucket
            for i in range(n_real, b_bucket):
                stopped[i] = True  # dead padding lanes ride along frozen
            expired = [False] * n_real
            stop = set(int(s) for s in stop_ids)
            # the prefill-sampled token is the first emission (exactly
            # like Generator._generate's step-0 sample)
            for i in range(n_real):
                tid = int(pending[i])
                if tid in stop:
                    stopped[i] = True
                else:
                    out[i].append(tid)
                    if len(out[i]) >= max_new:
                        stopped[i] = True
            positions = lens.copy()  # write position of row's `pending`
            rounds = draft_steps = verify_steps = 0
            proposed = accepted = 0
            proposals = _onp.zeros((b_bucket, self.k), _onp.int32)
            while not all(stopped):
                rounds += 1
                # draft proposes d_1..d_k; the extra (k+1)-th step writes
                # d_k's K/V into the draft ring so a fully-accepted round
                # leaves no hole at position + k
                if self._multistep:
                    # one compiled super-step runs all k+1 draft
                    # iterations: iteration j feeds d_j at pos+j, writes
                    # its K/V and greedily samples d_{j+1} — identical to
                    # the sequential loop below, one host visit instead
                    # of k+1. No stops, no budget: every lane runs the
                    # full k+1 iterations (spare proposals for frozen
                    # lanes are ignored at settle, same as sequential).
                    with _trace.span("serve::draft_step",
                                     {"steps": self.k + 1}):
                        blk_d, _, _, dcache = self.draft.decode_super(
                            pending, positions, self.k + 1,
                            _onp.full(b_bucket, self.k + 2, _onp.int32),
                            _onp.arange(b_bucket, dtype=_onp.int32),
                            _onp.zeros(b_bucket, _onp.float32),
                            _onp.zeros(b_bucket, _onp.int32),
                            _onp.full((b_bucket, _STOP_WIDTH), -1,
                                      _onp.int32),
                            _onp.zeros(2, _onp.uint32), dcache)
                    draft_steps += self.k + 1
                    proposals[:, :] = blk_d[:, :self.k]
                else:
                    cur = pending.copy()
                    dpos = positions.copy()
                    for j in range(self.k + 1):
                        with _trace.span("serve::draft_step", {"j": j}):
                            dlog, dcache = self.draft.decode_step(
                                cur, dpos, dcache)
                        dpos = dpos + 1
                        draft_steps += 1
                        if j < self.k:
                            cur = sample_tokens(dlog)
                            proposals[:, j] = cur
                blk = _onp.concatenate(
                    [_onp.asarray(pending).reshape(-1, 1), proposals],
                    axis=1)
                with _trace.span("serve::verify_step", {"k": self.k}):
                    vlogits, tcache = self._verify_run(blk, positions,
                                                       tcache)
                verify_steps += 1
                greedy = sample_tokens(
                    vlogits.reshape(-1, vlogits.shape[-1]))
                greedy = greedy.reshape(b_bucket, self.k + 1)
                for i in range(b_bucket):
                    if stopped[i]:
                        continue
                    a = 0
                    while a < self.k and proposals[i, a] == greedy[i, a]:
                        a += 1
                    proposed += self.k
                    accepted += a
                    emit = [int(t) for t in proposals[i, :a]]
                    emit.append(int(greedy[i, a]))
                    for tid in emit:
                        if tid in stop:
                            stopped[i] = True
                            break
                        out[i].append(tid)
                        if len(out[i]) >= max_new:
                            stopped[i] = True
                            break
                    pending[i] = greedy[i, a]
                    positions[i] += a + 1
                if deadlines is not None:
                    now = time.monotonic()
                    for i in range(n_real):
                        if not stopped[i] and now >= deadlines[i]:
                            stopped[i] = True
                            expired[i] = True
                            self.metrics.observe_deadline("decode")
            run_ok = True
        finally:
            self.target._prefix_release(prompts, b_bucket, tcache, run_ok)
            self.draft._prefix_release(prompts, b_bucket, dcache, run_ok)
        t_done = time.perf_counter()
        decode_s = t_done - t_prefill
        n_tokens = sum(len(o) for o in out)
        self.metrics.observe_tokens(n_tokens, decode_s)
        info = {
            "prefill_ms": (t_prefill - t_start) * 1e3,
            "decode_ms": decode_s * 1e3,
            "rounds": rounds,
            "draft_steps": draft_steps,
            "verify_steps": verify_steps,
            "acceptance_rate": accepted / proposed if proposed else 0.0,
            "tokens_s": n_tokens / decode_s if decode_s > 0 else 0.0,
            "total_ms": (t_done - t_start) * 1e3,
            "deadline_expired": [i for i in range(n_real) if expired[i]],
        }
        return out, info

    # -- warmup / invariants -------------------------------------------------
    def warmup(self):
        """Warm all three sessions: the target and draft lattices plus one
        verify signature per batch bucket."""
        t0 = time.perf_counter()
        self.target.warmup()
        self.draft.warmup()
        for bb in self.batch_buckets:
            cache = self.target._fresh_cache(bb)
            blk = _onp.zeros((bb, self.k + 1), _onp.int32)
            self._verify_run(blk, _onp.zeros(bb, _onp.int32), cache)
        self._verify.freeze_signatures()
        return {"signatures": (self.target.session.signature_count()
                               + self.draft.session.signature_count()
                               + self._verify.signature_count()),
                "wall_s": time.perf_counter() - t0}

    def assert_no_recompiles(self):
        self.target.assert_no_recompiles()
        self.draft.assert_no_recompiles()
        self._verify.assert_no_recompiles()

    def stats(self):
        return {"target": self.target.stats(), "draft": self.draft.stats(),
                "verify": self._verify.stats()}
