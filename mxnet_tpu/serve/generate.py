"""Autoregressive decode with a real KV cache.

Without this module, generating token ``t`` re-runs the full prefill over
``t`` positions — O(n^2) work per sequence. :class:`KVCache` preallocates
per-layer K/V rings to ``max_seq`` and the decode step feeds exactly one
new token through the model (``cache=`` / ``start_pos=`` path in
``models/llama.py``), so each generated token costs one T=1 executable
replay.

Parity contract (asserted per-token in ``tests/test_serve.py``): the
decode path's logits are **bitwise identical** to re-running the full
prefill through the same cache-mode path. Both arms compile through the
shape-stable serving ops in ``ops/nn.py`` (see the section comment there)
— the KV cache is a pure work-skipping transform, not an approximation.

Shapes are bucketed the serving way: one decode executable per batch
bucket (T=1 is constant), one prefill executable per (batch, prompt)
bucket; after :meth:`Generator.warmup` a decode stream of any admitted
shape triggers zero XLA compiles.

Sampling (``greedy``, temperature, top-k) draws its keys from
``mxnet_tpu.random`` — seeded, reproducible streams, same as training.
"""
from __future__ import annotations

import time

import numpy as _onp

from .. import random as _rng
from ..base import MXNetError
from ..profiler import trace as _trace
from ..gluon.block import HybridBlock
from ..ops import nn as _ops
from ..resilience import faults as _faults
from .engine import InferenceSession, pick_bucket


class _LayerKV:
    """One layer's view of the cache: read k/v, write back the updated
    rings (functional update — inside a trace these are tracers)."""

    __slots__ = ("_cache", "_idx")

    def __init__(self, cache, idx):
        self._cache = cache
        self._idx = idx

    @property
    def k(self):
        return self._cache._k[self._idx]

    @property
    def v(self):
        return self._cache._v[self._idx]

    @property
    def max_seq(self):
        return self._cache.max_seq

    def update(self, new_k, new_v):
        self._cache._k[self._idx] = new_k
        self._cache._v[self._idx] = new_v


class KVCache:
    """Preallocated per-layer K/V rings for autoregressive decode.

    Layout: ``num_layers`` pairs of (batch, kv_heads, max_seq, head_dim)
    NDArrays, zero-initialized. Position accounting lives with the caller
    (per-row ``start_pos`` vectors) — the cache itself is pure storage, so
    one compiled executable serves every decode step.
    """

    def __init__(self, keys, values, max_seq):
        if len(keys) != len(values):
            raise MXNetError("KVCache needs one value ring per key ring")
        self._k = list(keys)
        self._v = list(values)
        self.max_seq = int(max_seq)

    @classmethod
    def alloc(cls, model, batch, max_seq, dtype="float32"):
        """Zeroed rings sized from the model's attention geometry."""
        from .. import numpy as mnp

        keys, values = [], []
        for blk in model._blocks:
            attn = blk.attention
            shape = (int(batch), attn._kv_heads, int(max_seq),
                     attn._head_dim)
            keys.append(mnp.zeros(shape, dtype=dtype))
            values.append(mnp.zeros(shape, dtype=dtype))
        return cls(keys, values, max_seq)

    @property
    def num_layers(self):
        return len(self._k)

    @property
    def batch(self):
        return self._k[0].shape[0]

    def layer(self, i) -> _LayerKV:
        return _LayerKV(self, i)

    def flat(self):
        """Interleaved [k0, v0, k1, v1, ...] — the executable's calling
        convention for cache state."""
        out = []
        for k, v in zip(self._k, self._v):
            out.extend((k, v))
        return out

    @classmethod
    def from_flat(cls, arrays, max_seq):
        arrays = list(arrays)
        if len(arrays) % 2:
            raise MXNetError("flat KVCache needs an even array count")
        return cls(arrays[0::2], arrays[1::2], max_seq)

    def nbytes(self):
        return sum(int(_onp.prod(a.shape)) * _onp.dtype(a.dtype).itemsize
                   for a in self._k + self._v)


class _CacheForward(HybridBlock):
    """The compiled serving step: (tokens, start_pos, last_idx, *rings) ->
    (last-position logits, *updated rings).

    One forward serves both phases — prefill (T = prompt bucket,
    start_pos = 0, last_idx = prompt_len - 1) and decode (T = 1,
    start_pos = per-row position, last_idx = 0). The phases differ only
    by shape, i.e. by CachedOp signature, never by code path: that shared
    path is what makes the bitwise decode-vs-prefill parity hold.
    """

    def __init__(self, model, max_seq, **kwargs):
        super().__init__(**kwargs)
        self.model = model  # child registration shares the params
        self._max_seq = int(max_seq)

    def forward(self, tokens, start_pos, last_idx, *flat_cache):
        cache = KVCache.from_flat(flat_cache, self._max_seq)
        logits = self.model(tokens, cache=cache, start_pos=start_pos)
        last = _ops.gather_positions(logits, last_idx)
        return (last,) + tuple(cache.flat())


def sample_tokens(logits, temperature=0.0, top_k=None):
    """Next-token choice from (B, vocab) logits.

    ``temperature <= 0`` is greedy argmax; otherwise softmax sampling at
    the given temperature, optionally truncated to the ``top_k`` largest
    logits. Randomness comes from ``mxnet_tpu.random``'s key stream, so
    ``mx.random.seed(n)`` reproduces a generation exactly.
    Returns a host numpy (B,) int32 array.
    """
    import jax
    import jax.numpy as jnp

    from ..ndarray.ndarray import NDArray

    data = logits._data if isinstance(logits, NDArray) else jnp.asarray(logits)
    if temperature is None or temperature <= 0.0:
        return _onp.asarray(jnp.argmax(data, axis=-1)).astype(_onp.int32)
    scaled = data / float(temperature)
    if top_k is not None and 0 < int(top_k) < scaled.shape[-1]:
        kth = jax.lax.top_k(scaled, int(top_k))[0][..., -1:]
        scaled = jnp.where(scaled < kth, -jnp.inf, scaled)
    key = _rng.next_key()
    return _onp.asarray(
        jax.random.categorical(key, scaled, axis=-1)).astype(_onp.int32)


class Generator:
    """Bucketed KV-cache generation server for decoder LMs.

    Wraps the model into a :class:`_CacheForward` step compiled through an
    :class:`InferenceSession` (breaker, watchdog, fault site, serve-hit
    accounting all apply to every prefill and every decode step).

    Parameters
    ----------
    model : LlamaModel (or any block with ``_blocks[i].attention`` KV
        geometry and a ``cache=``/``start_pos=`` forward).
    max_seq : ring length — prompt + generated tokens must fit.
    batch_buckets / prompt_buckets : the compiled shape lattice.
    """

    def __init__(self, model, max_seq=128, batch_buckets=(1, 2, 4),
                 prompt_buckets=None, pad_id=0, name="llama_decode"):
        self.model = model
        self.max_seq = int(max_seq)
        self.batch_buckets = tuple(sorted(int(b) for b in batch_buckets))
        if prompt_buckets is None:
            prompt_buckets, p = [], 16
            while p < self.max_seq:
                prompt_buckets.append(p)
                p *= 2
            prompt_buckets.append(self.max_seq)
        self.prompt_buckets = tuple(sorted(set(int(p)
                                               for p in prompt_buckets)))
        if self.prompt_buckets[-1] > self.max_seq:
            raise MXNetError("prompt bucket exceeds max_seq")
        self.pad_id = int(pad_id)
        self._step = _CacheForward(model, self.max_seq)
        # bucketing is done here (cache shapes are part of the lattice);
        # the session provides the protected raw-run path
        self.session = InferenceSession(
            self._step, batch_buckets=self.batch_buckets,
            seq_buckets=self.prompt_buckets, pad_value=self.pad_id,
            name=name)
        self.metrics = self.session.metrics
        self._zero_caches = {}  # batch bucket -> shared zeroed rings

    def _fresh_cache(self, batch_bucket):
        """Zeroed rings for one batch bucket, allocated once and shared
        by every request: device arrays are immutable and prefill/decode
        return functionally-updated rings without touching their input
        cache, so reuse is safe — and the serving hot path skips
        2 x num_layers allocations + zero-fills per request."""
        cache = self._zero_caches.get(batch_bucket)
        if cache is None:
            cache = self._zero_caches.setdefault(
                batch_bucket,
                KVCache.alloc(self.model, batch_bucket, self.max_seq))
        return cache

    # -- phase helpers (also the parity-test surface) -----------------------
    def _run(self, tokens, start_pos, last_idx, cache):
        from .. import numpy as mnp

        out = self.session.run(
            mnp.array(_onp.asarray(tokens, _onp.int32)),
            mnp.array(_onp.asarray(start_pos, _onp.int32)),
            mnp.array(_onp.asarray(last_idx, _onp.int32)),
            *cache.flat())
        logits, flat = out[0], out[1:]
        return logits, KVCache.from_flat(flat, self.max_seq)

    def prefill(self, prompts, prompt_lens, cache):
        """Run the prompt block through the cache path. ``prompts`` is a
        host (B, T_bucket) int array (already padded), ``prompt_lens`` the
        (B,) real lengths. Returns ((B, vocab) last-real-position logits,
        updated cache)."""
        b = len(prompt_lens)
        zeros = _onp.zeros(b, _onp.int32)
        last = _onp.asarray(prompt_lens, _onp.int32) - 1
        return self._run(prompts, zeros, last, cache)

    def decode_step(self, tokens, positions, cache):
        """One T=1 decode step: ``tokens`` (B,) the just-sampled ids,
        ``positions`` (B,) their absolute positions. Returns the next
        (B, vocab) logits and the updated cache. The ``serve:decode``
        fault site fires once per step, so the chaos harness can kill a
        generation stream mid-decode (distinct from ``serve:execute``,
        which also covers prefill)."""
        _faults.fault_point("serve:decode",
                            {"session": self.session.name})
        toks = _onp.asarray(tokens, _onp.int32).reshape(-1, 1)
        zeros = _onp.zeros(len(toks), _onp.int32)
        return self._run(toks, _onp.asarray(positions, _onp.int32),
                         zeros, cache)

    # -- the serving API ----------------------------------------------------
    def _pad_prompts(self, prompts):
        lens = _onp.asarray([len(p) for p in prompts], _onp.int32)
        if int(lens.min()) < 1:
            raise MXNetError("empty prompt (need >= 1 token)")
        t_bucket = pick_bucket(int(lens.max()), self.prompt_buckets)
        b_bucket = pick_bucket(len(prompts), self.batch_buckets)
        toks = _onp.full((b_bucket, t_bucket), self.pad_id, _onp.int32)
        for i, p in enumerate(prompts):
            toks[i, :len(p)] = p
        full_lens = _onp.ones(b_bucket, _onp.int32)
        full_lens[:len(prompts)] = lens
        # dead batch lanes replay prompt 0's first token at length 1
        toks[len(prompts):, 0] = toks[0, 0]
        return toks, full_lens, b_bucket

    def generate(self, prompts, max_new_tokens=32, temperature=0.0,
                 top_k=None, stop_ids=(), deadlines=None):
        """Traced entry point: when request tracing is on and no ambient
        trace is active (a direct ``generate()`` call, not one under a
        traced batcher runner), open a ``serve.generate[<name>]`` lane so
        the prefill/decode-step spans land somewhere; under a batcher the
        representative request's lane is already active and is used
        instead. See :meth:`_generate` for the actual semantics."""
        own = None
        if _trace.ENABLED and _trace.current() is None:
            own = _trace.start_trace(f"serve.generate[{self.session.name}]",
                                     args={"prompts": len(prompts)})
        try:
            with _trace.activate(own):
                out = self._generate(prompts, max_new_tokens=max_new_tokens,
                                     temperature=temperature, top_k=top_k,
                                     stop_ids=stop_ids, deadlines=deadlines)
        except Exception as exc:
            if own is not None:
                own.finish(error=exc)
            raise
        if own is not None:
            own.finish()
        return out

    def _generate(self, prompts, max_new_tokens=32, temperature=0.0,
                  top_k=None, stop_ids=(), deadlines=None):
        """Generate continuations for a batch of prompts (lists of ids).

        ``deadlines`` (optional) carries absolute ``time.monotonic()``
        deadlines — one scalar for the whole batch or one per prompt. A
        row whose deadline passes is **retired between decode steps**: it
        stops consuming decode work, keeps the tokens generated so far,
        and lands in ``info["deadline_expired"]`` so the serving layer can
        settle its future with :class:`~.engine.DeadlineExceeded` instead
        of delivering late. When every live row has expired the whole
        decode loop exits early. ``None`` (default) checks nothing — the
        original semantics, bitwise included.

        Returns ``(outputs, info)``: per-prompt generated id lists (stop
        token excluded) and a stats dict (tokens/s, per-phase wall time,
        expired row indices).
        """
        t_start = time.perf_counter()
        toks, lens, b_bucket = self._pad_prompts(prompts)
        n_real = len(prompts)
        max_new = int(max_new_tokens)
        if int(lens.max()) + max_new > self.max_seq:
            raise MXNetError(
                f"prompt ({int(lens.max())}) + max_new_tokens ({max_new}) "
                f"exceeds max_seq ({self.max_seq})")
        if deadlines is not None:
            try:
                deadlines = [float(d) for d in deadlines]
            except TypeError:
                deadlines = [float(deadlines)] * n_real
            if len(deadlines) != n_real:
                raise MXNetError(
                    f"generate() got {len(deadlines)} deadlines for "
                    f"{n_real} prompts")
        cache = self._fresh_cache(b_bucket)
        with _trace.span("serve::prefill", {"batch": n_real}):
            logits, cache = self.prefill(toks, lens, cache)
        t_prefill = time.perf_counter()

        out = [[] for _ in range(n_real)]
        stopped = [False] * n_real
        expired = [False] * n_real
        positions = lens.copy()  # next write position per row
        stop = set(int(s) for s in stop_ids)
        n_decoded = 0
        for step in range(max_new):
            next_ids = sample_tokens(logits, temperature=temperature,
                                     top_k=top_k)
            for i in range(n_real):
                if stopped[i]:
                    continue
                tid = int(next_ids[i])
                if tid in stop:
                    stopped[i] = True
                else:
                    out[i].append(tid)
            if deadlines is not None:
                # retire expired rows at the step boundary: their decode
                # budget is spent — burning further T=1 passes for output
                # nobody will read is the overload failure mode
                now = time.monotonic()
                for i in range(n_real):
                    if not stopped[i] and now >= deadlines[i]:
                        stopped[i] = True
                        expired[i] = True
                        self.metrics.observe_deadline("decode")
            if all(stopped) or step == max_new - 1:
                # the last sampled token needs no successor logits —
                # running decode_step here would be a discarded T=1 pass
                break
            with _trace.span("serve::decode_step", {"step": step}):
                logits, cache = self.decode_step(next_ids, positions,
                                                 cache)
            positions = positions + 1
            n_decoded += 1
        t_done = time.perf_counter()
        decode_s = t_done - t_prefill
        n_tokens = sum(len(o) for o in out)
        self.metrics.observe_tokens(n_tokens, decode_s)
        info = {
            "prefill_ms": (t_prefill - t_start) * 1e3,
            "decode_ms": decode_s * 1e3,
            "decode_steps": n_decoded,
            "tokens_s": n_tokens / decode_s if decode_s > 0 else 0.0,
            "total_ms": (t_done - t_start) * 1e3,
            "deadline_expired": [i for i in range(n_real) if expired[i]],
        }
        return out, info

    # -- warmup / invariants -------------------------------------------------
    def warmup(self):
        """Compile every (batch bucket x prompt bucket) prefill and every
        batch bucket's decode step; freezes the signature set so
        ``assert_no_recompiles`` guards steady state."""
        t0 = time.perf_counter()
        for bb in self.batch_buckets:
            for pb in self.prompt_buckets:
                cache = self._fresh_cache(bb)
                toks = _onp.zeros((bb, pb), _onp.int32)
                lens = _onp.ones(bb, _onp.int32)
                logits, cache = self.prefill(toks, lens, cache)
                if pb == self.prompt_buckets[0]:
                    ids = _onp.zeros(bb, _onp.int32)
                    self.decode_step(ids, lens, cache)
        self.session.freeze_signatures()
        return {"signatures": self.session.signature_count(),
                "wall_s": time.perf_counter() - t0}

    def assert_no_recompiles(self):
        self.session.assert_no_recompiles()

    def stats(self):
        return self.session.stats()
