"""Radix prefix cache over the paged KV pool (PR-14).

``PrefixCache`` is a trie over token-id sequences, one edge per
*full* KV page (``page_size`` tokens), mapping every cached prefix to
the pages that already hold its keys/values in a
:class:`~mxnet_tpu.serve.kv_blocks.PagedKVPool`. Serving consults it at
admission: a hit hands the new request the matched pages via
``pool.assign_with_prefix()`` — refcounts bump, nothing is copied — and
the request's chunked prefill starts *past* the matched tokens. A miss
costs one dict probe per page.

Sharing rules (the copy-on-extend contract):

* Only **full** pages are ever shared, and never the page holding a
  request's final prompt token: ``match()`` caps at
  ``(len(prompt) - 1) // page_size`` pages so at least one prompt token
  is always prefilled. That keeps the engine's "sample on final chunk"
  flow unchanged and guarantees the request's first write position is
  at/after the shared boundary — shared pages are read-only by
  construction (``paged_kv_scatter`` writes only ``start_pos + [0,
  t_len)``).
* The trie holds **one refcount per adopted page** (so a cached prefix
  survives its originating request's retirement); live slots hold their
  own references. ``release()``/eviction *decrement*; the device page
  recycles only when the last reference drops.
* Eviction is LRU over trie **leaves only** (an interior page is, by
  construction, more recently used than its deepest descendant), runs
  only under pool pressure (``reclaim()`` before surfacing
  ``PoolExhausted``), and never touches a page some slot still
  references (refcount > 1) or one in the caller's ``exclude`` set (the
  pages it just matched but has not yet assigned).

Token identity of cached decode: shared pages hold bits produced by the
same deterministic chunked prefill the request would have run itself,
and chunked prefill at an arbitrary ``start_pos`` is bit-identical to
full prefill (the PR-5 parity contract the engine already relies on),
so a prefix-hit greedy decode is token-identical to a cache-off run.

Lock order: ``PrefixCache._lock`` (outer) → ``PagedKVPool._lock``
(inner, via incref/decref/refcount). The pool never calls back into the
trie.
"""
import itertools
import threading

from ..base import MXNetError

__all__ = ["PrefixCache"]


class _Node:
    """One full page of cached prefix: ``key`` is its ``page_size``-token
    window, ``page`` the pool page holding those tokens' KV."""
    __slots__ = ("key", "page", "children", "parent", "last_used")

    def __init__(self, key, page, parent):
        self.key = key
        self.page = page
        self.parent = parent
        self.children = {}
        self.last_used = 0


class PrefixCache:
    """Trie index from token-id prefixes to refcounted KV pages.

    Parameters
    ----------
    pool : PagedKVPool
        The pool whose pages are being indexed; the trie owns one
        reference per adopted page.
    name : str
        Label for stats/metrics.
    """

    def __init__(self, pool, name="prefix"):
        self.pool = pool
        self.page_size = pool.page_size
        self.name = name
        self._root = _Node(None, 0, None)
        self._lock = threading.Lock()
        self._clock = itertools.count(1)
        self._nodes = 0
        self.evictions = 0
        self.inserts = 0
        self.hits = 0
        self.misses = 0
        self.tokens_matched = 0

    # -- lookup --------------------------------------------------------------
    def match(self, tokens):
        """Longest cached prefix of ``tokens``.

        Returns ``(matched_tokens, pages)`` where ``matched_tokens`` is
        a multiple of ``page_size`` and ``pages`` the corresponding pool
        pages, front first. Caps at ``(len(tokens) - 1) // page_size``
        pages so the caller always prefills >= 1 token. The returned
        pages stay valid until the next ``reclaim()`` — callers that
        will assign them must pass them as ``exclude=`` to any reclaim
        in between.
        """
        ps = self.page_size
        max_pages = max(0, (len(tokens) - 1) // ps)
        pages = []
        with self._lock:
            node = self._root
            tick = next(self._clock)
            for i in range(max_pages):
                child = node.children.get(tuple(tokens[i * ps:(i + 1) * ps]))
                if child is None:
                    break
                child.last_used = tick
                pages.append(child.page)
                node = child
            matched = len(pages) * ps
            if pages:
                self.hits += 1
                self.tokens_matched += matched
            else:
                self.misses += 1
        return matched, pages

    # -- adoption ------------------------------------------------------------
    def insert(self, tokens, pages):
        """Adopt the full-page prefix of ``tokens`` into the trie.

        ``pages`` is the owning slot's page list (front first) — called
        at retirement, *before* ``pool.release(slot)``, while the slot's
        references still pin the pages. Each newly created node increfs
        its page; pages already cached under an identical token window
        keep the existing node's page (the duplicate copy just recycles
        with its slot). Returns the number of pages newly adopted.
        """
        ps = self.page_size
        n = min(len(tokens) // ps, len(pages))
        if n <= 0:
            return 0
        adopted = 0
        with self._lock:
            node = self._root
            tick = next(self._clock)
            for i in range(n):
                key = tuple(tokens[i * ps:(i + 1) * ps])
                child = node.children.get(key)
                if child is None:
                    page = int(pages[i])
                    self.pool.incref([page])
                    child = _Node(key, page, node)
                    node.children[key] = child
                    self._nodes += 1
                    adopted += 1
                child.last_used = tick
                node = child
            self.inserts += 1
        return adopted

    # -- eviction ------------------------------------------------------------
    def reclaim(self, need, exclude=()):
        """Evict LRU cached prefixes until ``need`` pages have recycled
        to the pool's free list, skipping pages a live slot still
        references (pool refcount > 1) and pages in ``exclude``.
        Returns the number of pages actually freed (may be < ``need``
        when everything left is pinned)."""
        exclude = {int(p) for p in exclude}
        freed = 0
        with self._lock:
            while freed < need:
                victims = [c for c in self._iter_leaves()
                           if c.page not in exclude
                           and self.pool.refcount(c.page) == 1]
                if not victims:
                    break
                victims.sort(key=lambda c: c.last_used)
                for c in victims:
                    if freed >= need:
                        break
                    freed += len(self.pool.decref([c.page]))
                    del c.parent.children[c.key]
                    self._nodes -= 1
                    self.evictions += 1
        return freed

    def clear(self):
        """Drop every cached prefix (tenancy eviction / shutdown): the
        trie's references release; pages pinned by live slots recycle
        when those slots retire."""
        with self._lock:
            pages = [c.page for c in self._iter_all()]
            self.pool.decref(pages)
            self.evictions += self._nodes
            self._root.children.clear()
            self._nodes = 0
        return len(pages)

    def _iter_all(self):
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    def _iter_leaves(self):
        for node in self._iter_all():
            if not node.children:
                yield node

    # -- introspection -------------------------------------------------------
    @property
    def pages_held(self):
        """Pages the trie currently holds a reference on."""
        with self._lock:
            return self._nodes

    def stats(self):
        with self._lock:
            nodes = self._nodes
            hits, misses = self.hits, self.misses
        total = hits + misses
        return {"pages_held": nodes,
                "hits": hits,
                "misses": misses,
                "hit_rate": (hits / total) if total else 0.0,
                "tokens_matched": self.tokens_matched,
                "inserts": self.inserts,
                "evictions": self.evictions,
                "pages_shared": self.pool.pages_shared}

    def __repr__(self):
        return (f"PrefixCache(name={self.name!r}, pages={self._nodes}, "
                f"evictions={self.evictions})")
