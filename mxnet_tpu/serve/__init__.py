"""Inference serving subsystem: dynamic batching, bucketed AOT
executables, KV-cache decode.

The serving split the reference ecosystem made with mxnet-model-server
(a serving layer over ``Module.predict``), rebuilt TPU-first over this
framework's own substrate:

* :class:`InferenceSession` (``engine``) — pads requests onto a small
  (batch, seq) bucket lattice compiled through ``CachedOpThreadSafe``,
  so steady-state serving never recompiles; guarded by the resilience
  circuit breaker, execution watchdog, and fault sites.
* :class:`DynamicBatcher` (``batcher``) — admission-controlled request
  queue: flush on max-batch-size or deadline, O(1) fast-reject (503)
  when full, per-request failure isolation.
* :class:`Generator` / :class:`KVCache` (``generate``) — autoregressive
  decode for the llama-family models with preallocated per-layer KV
  rings. The decode path is a per-generator rung: "baseline" (bitwise
  prefill/decode parity, the PR-5 contract — pinned process-wide by
  ``MXNET_SERVE_STRICT_PARITY=1``), "pallas" (fused decode-attention
  kernel), or "int8" (pallas + int8 KV rings/weights), each with
  tolerance parity.
* :class:`SpeculativeGenerator` (``generate``) — draft-propose-k /
  target-verify-one-step decoding over the same bucketed sessions;
  greedy acceptance is token-identical to non-speculative greedy.
* :class:`ServeMetrics` (``metrics``) — p50/p95/p99 latency, queue
  depth, batch occupancy, tokens/s; emitted as ``serve::*`` events on
  the profiler bus.
* :class:`ContinuousEngine` / :class:`PagedKVPool` (``scheduler``,
  ``kv_blocks``) — continuous batching: an iteration-level scheduler
  that admits/retires requests *between decode steps* over a fixed slot
  lattice (two compiled signatures total), with KV state in a paged
  block pool (reserve-at-admit, recycle-on-retire, null-page masking
  for idle lanes).
* :class:`PrefixCache` (``prefix_cache``) — cross-request KV reuse: a
  radix trie over prompt token ids maps matched prefixes to refcounted
  pages in the paged pool (copy-on-extend sharing, LRU eviction only
  under pool pressure), so admission skips the matched portion of
  chunked prefill with token-identical greedy output.
* :class:`ModelRegistry` (``tenancy``) — N named models per process,
  each behind its own engine (per-tenant pool + prefix trie), LRU
  eviction of cold tenants under ``MXNET_SERVE_MAX_MODELS``, reload
  warm from the persistent compile cache, routed via
  ``submit(model=...)``.
* :class:`Router` / :class:`Replica` (``fleet``, ``replica``) — the
  fleet layer: health-aware least-loaded dispatch over N replicas,
  replica failover with exactly-once settlement (idempotency keys +
  generation fencing), hedged retries for straggler-flagged
  interactive traffic, zero-downtime rollout via per-replica hot swap,
  and graceful-drain autoscaling hooks.

See SERVING.md for architecture, bucket policy, and the env knobs
(``MXNET_SERVE_*``).
"""
from __future__ import annotations

from .batcher import PRIORITIES, DynamicBatcher, TokenBucket
from .engine import DeadlineExceeded, InferenceSession, PoolExhausted, \
    ServeError, ServiceUnavailable, pick_bucket
from .fleet import QueueDepthPolicy, Router, fleet_stats
from .generate import Generator, KVCache, SpeculativeGenerator, \
    resolve_decode_path, sample_tokens
from .kv_blocks import PagedKVPool, resolve_page_size
from .metrics import ServeMetrics, percentile
from .prefix_cache import PrefixCache
from .replica import Replica
from .scheduler import ContinuousEngine
from .tenancy import ModelRegistry, registry_stats

__all__ = [
    "InferenceSession", "DynamicBatcher", "Generator", "KVCache",
    "SpeculativeGenerator", "ServeMetrics", "ServeError",
    "ServiceUnavailable", "DeadlineExceeded", "PoolExhausted",
    "TokenBucket", "PRIORITIES",
    "Router", "Replica", "QueueDepthPolicy", "fleet_stats",
    "ContinuousEngine", "PagedKVPool", "resolve_page_size",
    "PrefixCache", "ModelRegistry", "registry_stats",
    "sample_tokens", "pick_bucket", "percentile", "resolve_decode_path",
]
