"""Paged KV block allocator: one device-resident page pool per layer.

The vLLM-style substrate under continuous batching
(``serve.scheduler``): instead of per-(batch-bucket) contiguous KV
rings sized ``B x max_seq`` whether or not anyone uses them, every
layer's K/V storage is ONE pool of fixed-width pages —
``(P, KV, page, D)`` rings, plus ``(P, KV, page)`` f32 scale pools on
the int8 rung (PR-10 quantize-on-write composes unchanged: the pages
just hold int8 payloads and their scale rows). A per-slot *page table*
maps each slot's logical ring onto the pages it owns; each step gathers
the table into a contiguous ring, runs the unchanged model cache path,
and scatters the freshly-written rows back — both directions exact
copies (``ops.nn.paged_kv_gather`` / ``paged_kv_scatter``). Fast rungs
fuse the brackets into the step executable
(``serve.generate._CacheForward(paged=True)``); the strict baseline
rung instead runs them as standalone eager device ops around the
UNCHANGED ring executable, so its bitwise decode contract survives
paging by construction (in-graph, XLA partitions the attention loops
differently when they read a gather output vs an entry parameter, which
drifts ulps).

Page id 0 is the reserved **null page**: dead/idle slots of a
fixed-width decode step point every table entry at it, their writes
land there, and the scatter op re-zeros it each step — so one compiled
executable serves every occupancy without masking inputs per slot.

The allocator itself is host-side and O(1): a LIFO free list of page
ids. ``assign()`` reserves a slot's whole token budget up front
(prompt + max_new rounded up to pages) so a request can never die
mid-decode from pool pressure — exhaustion surfaces exactly once, at
the admission boundary, as :class:`~.engine.PoolExhausted` (503), and
the scheduler's answer is to requeue, never to crash. ``release()``
recycles the pages the moment a request retires — the memory win over
bucket rings: a slot holds ``ceil((prompt+max_new)/page)`` pages, not
``max_seq``, and holds them only while the request is live.

Every allocated page carries a **reference count** so pages can be
shared across owners (PR-14 prefix caching, ``serve.prefix_cache``):
``assign_with_prefix()`` installs already-written prefix pages at the
front of a slot's table row and bumps their refcounts instead of
copying them, ``incref()`` lets the prefix trie adopt a retiring
request's prompt pages, and ``release()``/``decref()`` *decrement* —
a page returns to the free list only when its last reference drops.
Sharing is copy-on-extend at page granularity: shared pages are
read-only by construction (``paged_kv_scatter`` only writes rows at
``start_pos + [0, t_len)``, and a prefix-hit request's first write
position starts past the shared boundary), so the first divergent
token always lands in a slot-private page and no copy is ever needed.

Page size defaults to the Pallas decode kernel's natural block
(``ops.pallas.decode_attention.natural_block()`` = 128, clamped to
``max_seq``), so the kernel's block-skip masking skips whole unreached
pages; ``MXNET_SERVE_KV_PAGE_SIZE`` / ``MXNET_SERVE_KV_PAGES``
override (CPU tests run 16-wide pages).
"""
from __future__ import annotations

import threading

import numpy as _onp

from ..base import MXNetError
from .engine import PoolExhausted


def resolve_page_size(page_size, max_seq):
    """The pool's page width: an explicit argument wins, then
    ``MXNET_SERVE_KV_PAGE_SIZE``, then the decode kernel's natural block
    clamped to ``max_seq``. ``max_seq`` must divide into whole pages —
    the gathered ring must have exactly the contiguous ring's S extent
    or the paged executables would compile different shapes than the
    ring ones (and the bitwise parity contract would be vacuous)."""
    from .. import config

    ps = page_size
    if ps is None:
        ps = int(config.get("MXNET_SERVE_KV_PAGE_SIZE"))
    if ps <= 0:
        from ..ops.pallas.decode_attention import natural_block

        ps = min(natural_block(), int(max_seq))
    ps = int(ps)
    if int(max_seq) % ps:
        raise MXNetError(
            f"max_seq ({max_seq}) must be a multiple of the KV page size "
            f"({ps}); pick a page size that divides it "
            "(MXNET_SERVE_KV_PAGE_SIZE or the page_size argument)")
    return ps


class PagedKVPool:
    """Device page pools + host free-list allocator + per-slot page tables.

    Parameters
    ----------
    model : block with ``_blocks[i].attention`` KV geometry (same duck
        type :class:`~.generate.KVCache.alloc` reads).
    num_slots : fixed decode width — page-table rows (the trace-static
        slot lattice of the continuous-batching step).
    max_seq : logical ring length per slot (page table width =
        ``max_seq // page_size``).
    page_size : page width in tokens; ``None`` resolves via
        :func:`resolve_page_size`.
    num_pages : pool capacity in pages **including** the reserved null
        page; ``None`` resolves ``MXNET_SERVE_KV_PAGES``, whose 0
        default auto-sizes to full capacity
        (``num_slots * pages_per_slot + 1`` — exhaustion impossible).
        Size it smaller to oversubscribe: admission then queues on
        :class:`~.engine.PoolExhausted` until retirements recycle pages.
    quant : ``None`` (f32 pools) or ``"int8"`` (int8 ring pools + f32
        scale pools — PR-10's quantize-on-write flavor).
    """

    def __init__(self, model, num_slots, max_seq, page_size=None,
                 num_pages=None, quant=None):
        from .. import config
        from .. import numpy as mnp

        self.num_slots = int(num_slots)
        self.max_seq = int(max_seq)
        self.page_size = resolve_page_size(page_size, self.max_seq)
        self.pages_per_slot = self.max_seq // self.page_size
        if num_pages is None:
            num_pages = int(config.get("MXNET_SERVE_KV_PAGES"))
        if num_pages <= 0:
            num_pages = self.num_slots * self.pages_per_slot + 1
        self.num_pages = int(num_pages)
        if self.num_pages < 2:
            raise MXNetError(
                f"PagedKVPool needs >= 2 pages (1 null + 1 usable), got "
                f"{self.num_pages}")
        if quant not in (None, "int8"):
            raise MXNetError(f"unknown PagedKVPool quant {quant!r}")
        self.quant = quant
        # one (P, KV, page, D) k/v pool pair per layer; int8 adds the
        # (P, KV, page) f32 scale pools — interleaved in flat() exactly
        # like KVCache.flat() so _CacheForward's calling convention is
        # shared between ring and paged steps
        self._arrays = []
        for blk in model._blocks:
            attn = blk.attention
            shape = (self.num_pages, attn._kv_heads, self.page_size,
                     attn._head_dim)
            if quant == "int8":
                self._arrays.extend((
                    mnp.zeros(shape, dtype="int8"),
                    mnp.zeros(shape[:3], dtype="float32"),
                    mnp.zeros(shape, dtype="int8"),
                    mnp.zeros(shape[:3], dtype="float32")))
            else:
                self._arrays.extend((mnp.zeros(shape, dtype="float32"),
                                     mnp.zeros(shape, dtype="float32")))
        # host allocator state: LIFO free list (hot pages recycle first),
        # per-slot owned pages, the canonical page-table matrix
        self._lock = threading.Lock()
        self._free = list(range(self.num_pages - 1, 0, -1))
        self._owned = [[] for _ in range(self.num_slots)]
        self._refs = {}  # page id -> reference count (allocated pages)
        self._table = _onp.zeros((self.num_slots, self.pages_per_slot),
                                 _onp.int32)
        self._table_nd = None
        self.high_water = 0
        self.exhausted_count = 0

    # -- executable calling convention --------------------------------------
    def flat(self):
        """The pool arrays in the step executable's calling convention
        (interleaved per layer, like ``KVCache.flat()``)."""
        return list(self._arrays)

    def update_from_flat(self, arrays):
        """Rebind the pool state to the executable's returned arrays.
        In-place by design: pool state is the *persistent* serving
        substrate (unlike per-request ring caches), and every slot's
        live data rides in it between steps."""
        arrays = list(arrays)
        if len(arrays) != len(self._arrays):
            raise MXNetError(
                f"pool update: got {len(arrays)} arrays, expected "
                f"{len(self._arrays)}")
        self._arrays = arrays

    def table(self):
        """Copy of the canonical (num_slots, pages_per_slot) int32 page
        table. Rows of released slots are all-null (0)."""
        with self._lock:
            return self._table.copy()

    def table_nd(self):
        """The canonical page table as a cached device NDArray — for
        callers whose table never changes between calls (the
        fully-assigned Generator paged mode). Invalidated by
        assign/release."""
        from .. import numpy as mnp

        with self._lock:
            if self._table_nd is None:
                self._table_nd = mnp.array(self._table)
            return self._table_nd

    # -- allocator -----------------------------------------------------------
    def pages_for(self, n_tokens):
        """Pages needed to hold ``n_tokens`` ring positions."""
        n = int(n_tokens)
        return max(1, -(-n // self.page_size))

    def slot_budget(self, slot):
        """Token positions ``slot``'s assigned pages can hold (pages
        owned x page_size); 0 for an unassigned slot. The multi-step
        super-step's scatter bracket writes a static-length block of
        ``N`` rows per slot starting at its current position — safe
        because (a) a lane's budget is reserved up front from prompt +
        max_new, and the device loop freezes the lane at its remaining
        budget, so every *advancing* write stays inside this bound; and
        (b) block rows past the lane's write extent scatter back the
        bytes the bracket gathered (a no-op), while positions past the
        table row's last page clip to the null page, which the scatter
        re-zeroes. No page-table view wider than the slot's own row is
        ever needed for an N-token write."""
        with self._lock:
            return len(self._owned[int(slot)]) * self.page_size

    def assign(self, slot, n_tokens):
        """Reserve ``pages_for(n_tokens)`` pages for ``slot`` and install
        them in its page-table row (remaining row entries stay null).
        Raises :class:`PoolExhausted` — atomically, nothing allocated —
        when the free list is short; raises :class:`MXNetError` on a
        slot that already owns pages (the scheduler must release first).
        Returns the number of pages assigned."""
        return self.assign_with_prefix(slot, n_tokens, ())

    def assign_with_prefix(self, slot, n_tokens, prefix_pages):
        """Like :meth:`assign`, but the slot's table row *starts* with
        ``prefix_pages`` — already-written pages (a prefix-trie match)
        whose refcounts are bumped instead of allocating + rewriting
        them. Only ``pages_for(n_tokens) - len(prefix_pages)`` fresh
        pages come off the free list; exhaustion is still atomic
        (nothing increffed, nothing allocated). Shared pages are
        read-only for this slot by the copy-on-extend contract: its
        first write position is at/after the shared-token boundary, so
        every write lands in one of the slot-private pages."""
        slot = int(slot)
        need = self.pages_for(n_tokens)
        shared = [int(p) for p in prefix_pages]
        if n_tokens > self.max_seq:
            raise MXNetError(
                f"slot budget {n_tokens} exceeds max_seq {self.max_seq}")
        if shared and len(shared) >= need:
            raise MXNetError(
                f"prefix ({len(shared)} pages) must leave >= 1 private "
                f"page in a {need}-page budget (the divergent token "
                "needs somewhere to land)")
        fresh_need = need - len(shared)
        with self._lock:
            if self._owned[slot]:
                raise MXNetError(
                    f"slot {slot} already owns {len(self._owned[slot])} "
                    "pages; release() before re-assigning")
            if any(self._refs.get(p, 0) < 1 for p in shared):
                raise MXNetError(
                    f"prefix pages {shared} are not all live (evicted "
                    "between match and assign?)")
            if fresh_need > len(self._free):
                self.exhausted_count += 1
                err = PoolExhausted(
                    f"KV page pool exhausted: need {fresh_need} pages, "
                    f"{len(self._free)} free of {self.num_pages - 1}")
                # backpressure hint: pages free as requests retire; one
                # slot's worth of decode is the natural retry horizon
                err.retry_after_ms = 50.0
                raise err
            fresh = [self._free.pop() for _ in range(fresh_need)]
            for p in shared:
                self._refs[p] += 1
            for p in fresh:
                self._refs[p] = 1
            pages = shared + fresh
            self._owned[slot] = pages
            self._table[slot] = 0
            self._table[slot, :need] = pages
            self._table_nd = None
            used = self.pages_used
            if used > self.high_water:
                self.high_water = used
            return need

    def release(self, slot):
        """Drop ``slot``'s reference on every page it holds and null its
        table row; pages whose refcount reaches zero recycle to the free
        list (pages the prefix trie still references survive).
        Idempotent (releasing an empty slot is a no-op). The pages'
        device contents are left stale on purpose: the attention
        position mask plus prefill's exact overwrite make stale pages
        unreadable before they are rewritten, so retirement costs zero
        device work."""
        slot = int(slot)
        with self._lock:
            pages, self._owned[slot] = self._owned[slot], []
            if not pages:
                return 0
            if len(set(pages)) != len(pages) or 0 in pages:
                raise MXNetError(
                    f"corrupt page ownership for slot {slot}: {pages}")
            self._decref_locked(pages)
            self._table[slot] = 0
            self._table_nd = None
            return len(pages)

    # -- reference counting (prefix-cache sharing) ---------------------------
    def _decref_locked(self, pages):
        freed = []
        for p in pages:
            n = self._refs.get(p, 0) - 1
            if n > 0:
                self._refs[p] = n
            elif n == 0:
                del self._refs[p]
                freed.append(p)
            else:
                raise MXNetError(f"decref of free page {p}")
        self._free.extend(reversed(freed))
        return freed

    def incref(self, pages):
        """Add one reference to each of ``pages`` (the prefix trie
        adopting a retiring slot's prompt pages). Pages must be live."""
        pages = [int(p) for p in pages]
        with self._lock:
            for p in pages:
                if self._refs.get(p, 0) < 1:
                    raise MXNetError(f"incref of free page {p}")
            for p in pages:
                self._refs[p] += 1

    def decref(self, pages):
        """Drop one reference from each of ``pages``; returns the pages
        that reached zero and recycled to the free list (the prefix
        trie's eviction path)."""
        with self._lock:
            return self._decref_locked([int(p) for p in pages])

    def refcount(self, page):
        """Current reference count of ``page`` (0 = free)."""
        with self._lock:
            return self._refs.get(int(page), 0)

    @property
    def pages_shared(self):
        """Pages currently held by more than one reference (a live slot
        plus the trie, or several slots on one prefix)."""
        with self._lock:
            return sum(1 for n in self._refs.values() if n > 1)

    # -- readout -------------------------------------------------------------
    @property
    def pages_total(self):
        """Usable pages (the null page is bookkeeping, not capacity)."""
        return self.num_pages - 1

    @property
    def pages_free(self):
        with self._lock:
            return len(self._free)

    @property
    def pages_used(self):
        return self.pages_total - len(self._free)

    def nbytes(self):
        return sum(int(_onp.prod(a.shape)) * _onp.dtype(a.dtype).itemsize
                   for a in self._arrays)

    def stats(self):
        with self._lock:
            free = len(self._free)
            owned = sum(len(o) for o in self._owned)
            shared = sum(1 for n in self._refs.values() if n > 1)
        return {"page_size": self.page_size,
                "pages_total": self.pages_total,
                "pages_free": free,
                "pages_used": self.pages_total - free,
                "pages_owned": owned,
                "pages_shared": shared,
                "high_water": self.high_water,
                "exhausted_count": self.exhausted_count,
                "nbytes": self.nbytes()}
