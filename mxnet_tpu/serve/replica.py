"""`Replica`: one in-process serving unit a fleet :class:`Router` can
dispatch to, probe, drain, swap, and kill.

A replica is the smallest thing the fleet layer reasons about: a
``runner`` callable behind its own :class:`~.batcher.DynamicBatcher`
(so each replica has an independent admission queue, flusher thread,
and metrics window), optionally attached to the
:class:`~.engine.InferenceSession` that executes its batches (the
session contributes warm/breaker/drain state to the replica's probes
and its ``swap()`` to the fleet rollout path).

The ``replica:dispatch`` fault site fires inside :meth:`submit`, before
the request enters the batcher, with ``info={"replica": index}`` — a
``die`` there is a serving-replica death at dispatch time (the Router
catches the :class:`~..resilience.faults.SimulatedWorkerDeath`, marks
the replica dead, and fails the request over to a survivor), while
``transient``/``fatal`` model a flaky dispatch RPC. A ``die`` injected
at an *execution* site (``serve:execute``, ``serve:decode``) instead
kills the batcher's flusher thread mid-batch — that replica stops
settling work, which is exactly what :meth:`alive` detects and the
Router's supervisor sweeps up.
"""
from __future__ import annotations

import time

from ..profiler import export as _export
from ..resilience import faults as _faults
from .batcher import DynamicBatcher
from .engine import ServeError

__all__ = ["Replica"]


class Replica:
    """One serving replica: a private batcher + flusher over ``runner``.

    Parameters
    ----------
    runner : callable(list) -> list
        Executes one assembled batch (the :class:`DynamicBatcher`
        contract: one result per payload, an Exception instance in a
        slot fails that request alone).
    index : int
        Fleet-unique replica id; lands in fault-site info, metrics
        names, and the Router's straggler/health bookkeeping.
    session : InferenceSession, optional
        The session executing this replica's batches. Wires
        ``ready()``/``health()`` depth and enables :meth:`swap`.
    max_batch_size, timeout_ms, max_queue :
        Per-replica :class:`DynamicBatcher` overrides.
    """

    def __init__(self, runner, index=0, name=None, session=None,
                 max_batch_size=None, timeout_ms=None, max_queue=None):
        self.index = int(index)
        self.name = name or f"replica{self.index}"
        self.session = session
        self.batcher = DynamicBatcher(
            runner, max_batch_size=max_batch_size, timeout_ms=timeout_ms,
            max_queue=max_queue, name=self.name)
        self.metrics = self.batcher.metrics
        self._killed = False
        self.t_started = time.monotonic()

    # -- dispatch -----------------------------------------------------------
    def submit(self, payload, priority="interactive", deadline_ms=None,
               key=None):
        """Dispatch one request into this replica's queue; returns the
        batcher future. The ``replica:dispatch`` fault site fires first
        (an injected ``die`` here propagates
        :class:`SimulatedWorkerDeath` to the caller — replica death at
        dispatch time, the Router's failover trigger)."""
        _faults.fault_point("replica:dispatch",
                            {"replica": self.index, "name": self.name,
                             "priority": priority})
        return self.batcher.submit(payload, priority=priority,
                                   deadline_ms=deadline_ms, key=key)

    # -- probes -------------------------------------------------------------
    def alive(self):
        """Liveness: not killed AND the flusher thread is still running.
        A ``die`` fault inside the runner kills the flusher (it is a
        BaseException — deliberately not caught by the batcher's
        per-batch isolation), so a dead flusher IS a dead replica."""
        if self._killed:
            return False
        t = self.batcher._thread
        return t is not None and t.is_alive()

    def ready(self):
        """Readiness: alive, admitting (not draining/closed), and — when
        a session is attached — the session's own readiness (warm lattice,
        breaker not open). False is the Router's route-around cue."""
        if not self.alive():
            return False
        with self.batcher._cond:
            if self.batcher._closed or self.batcher._draining:
                return False
        if self.session is not None:
            return bool(self.session.ready())
        return True

    def load(self):
        """Dispatch-cost gauge: queued + in-flight requests."""
        with self.batcher._cond:
            return len(self.batcher._queue) + len(self.batcher._inflight)

    def p99_ms(self):
        return self.metrics.latency_percentiles()["p99_ms"]

    def health(self):
        """Probe payload for the fleet ``/healthz`` aggregation."""
        out = {
            "alive": self.alive(),
            "ready": self.ready(),
            "killed": self._killed,
            "load": self.load(),
            "p99_ms": self.p99_ms(),
        }
        if self.session is not None:
            out["session"] = self.session.health()
        return out

    # -- lifecycle ----------------------------------------------------------
    def drain(self, timeout=30.0):
        """Graceful quiesce: stop admission, wait for queue + in-flight
        to settle. Returns True once quiet, False on timeout."""
        return self.batcher.drain(timeout)

    def resume(self):
        self.batcher.resume()

    def swap(self, new_block, example=None, timeout=30.0):
        """Zero-downtime model swap for THIS replica: drain the batcher
        (no new batches dispatch), hot-swap the session (warm = param
        transplant, zero recompiles), resume. Returns the swap mode."""
        if self.session is None:
            raise ServeError(
                f"replica {self.name!r} has no session to swap")
        if not self.batcher.drain(timeout):
            self.batcher.resume()
            raise ServeError(
                f"replica {self.name!r}: swap aborted — batcher did not "
                f"quiesce within {timeout}s")
        try:
            mode = self.session.swap(new_block, example=example,
                                     timeout=timeout)
        finally:
            self.batcher.resume()
        return mode

    def kill(self, timeout=2.0):
        """Hard-stop this replica. The batcher close fails anything
        still queued or wedged in-flight with a structural 503 — by the
        time the Router calls this it has already fenced those requests'
        generations and requeued them to survivors, so the 503s settle
        into dropped duplicates, not client-visible errors. Idempotent."""
        if self._killed:
            return
        self._killed = True
        if self.session is not None:
            # the fleet Router answers /healthz for the fleet; a dead
            # replica's session must not keep 503ing the process probe
            _export.unregister_health_provider(self.session)
        self.batcher.close(timeout=timeout)

    def stats(self):
        out = self.batcher.stats()
        out["alive"] = self.alive()
        out["ready"] = self.ready()
        out["load"] = self.load()
        if self.session is not None:
            out["breaker"] = self.session.breaker.snapshot()
        return out
