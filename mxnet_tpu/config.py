"""Environment-flag registry with introspection.

The reference configures itself through ~100 ``MXNET_*`` env vars read via
``dmlc::GetEnv`` at use sites, documented centrally in
``docs/.../env_var.md``, plus self-describing ``dmlc::Parameter`` structs.
This module is the TPU build's equivalent: every flag the framework reads
is registered here with its type, default, and doc, and
``mx.config.describe()`` prints the live table (value, source) the way
``__getdoc__`` exposes Parameter fields.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, NamedTuple


class Flag(NamedTuple):
    name: str
    default: Any
    doc: str
    parse: Callable[[str], Any]


_FLAGS: Dict[str, Flag] = {}


def _bool(s: str) -> bool:
    return s not in ("0", "false", "False", "")


def register_flag(name, default, doc, parse=str):
    _FLAGS[name] = Flag(name, default, doc, parse)
    return _FLAGS[name]


def get(name):
    """Typed value of a registered flag (env wins over default)."""
    flag = _FLAGS[name]
    raw = os.environ.get(name)
    if raw is None:
        return flag.default
    return flag.parse(raw)


def is_set(name) -> bool:
    return name in os.environ


def list_flags():
    """All registered flag names (env_var.md table analog)."""
    return sorted(_FLAGS)


def describe(file=None):
    """Print name / current value / default / doc for every flag."""
    import sys

    out = file or sys.stdout
    for name in list_flags():
        f = _FLAGS[name]
        cur = get(name)
        src = "env" if is_set(name) else "default"
        print(f"{name} = {cur!r} ({src}; default {f.default!r})\n"
              f"    {f.doc}", file=out)


# ---------------------------------------------------------------------------
# The flags this framework reads (each registered next to its semantics;
# reference: docs/static_site/src/pages/api/faq/env_var.md)
# ---------------------------------------------------------------------------

register_flag(
    "MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice",
    "Execution engine. 'NaiveEngine' blocks after every op (serialized "
    "debugging, reference src/engine/naive_engine.cc); the default maps to "
    "XLA async dispatch.")
register_flag(
    "MXNET_EAGER_JIT_CACHE", True,
    "Cache one jax.jit executable per (op, static config) for imperative "
    "dispatch (SURVEY §7 hard part 2). 0 disables.", _bool)
register_flag(
    "MXNET_ENGINE_BULK_SIZE", 0,
    "Default per-thread bulk-execution segment size for deferred eager "
    "dispatch (engine.bulk() analog, reference engine.h:311-317). > 1: "
    "imperative ops record into a pending segment flushed as ONE compiled "
    "executable (one tunnel RTT) at N ops / materialization / wait points "
    "/ tape boundaries. 0 (default) dispatches per op; NaiveEngine forces "
    "per-op synchronous semantics regardless.", int)
register_flag(
    "MXNET_ENGINE_BULK_FUSE", False,
    "Let XLA fuse across the ops of a bulk segment. Default off: per-op "
    "optimization barriers keep bulk-vs-unbulked numerics bitwise "
    "identical (the RTT win comes from batched dispatch, not fusion); "
    "on trades last-ulp reduction drift for less memory traffic.", _bool)
register_flag(
    "MXNET_ENGINE_SEG_CACHE_MAX", 512,
    "Segment-executable cache entries above which the deferred-dispatch "
    "caches are cleared (same clear-don't-evict runaway guard as the "
    "eager per-op jit cache).", int)
register_flag(
    "MXNET_WAITALL_FULL", False,
    "mx.npx.waitall() sweeps every live array (exhaustive, slow) instead "
    "of the recently-dispatched set.", _bool)
register_flag(
    "MXNET_TPU_PEAK_FLOPS", None,
    "Override the chip peak FLOP/s used as the MFU denominator in "
    "bench.py (default: by device_kind).",
    float)
register_flag(
    "MXNET_TPU_NO_NATIVE", False,
    "Disable the ctypes native library (native/recordio.cc prefetcher); "
    "pure-Python fallbacks are used.", _bool)
register_flag(
    "MXNET_TPU_COORDINATOR", None,
    "host:port of process 0 for jax.distributed.initialize; set by "
    "tools/launch.py (reference DMLC_PS_ROOT_URI/PORT).")
register_flag(
    "MXNET_TPU_NUM_PROCS", None,
    "World size for multi-process SPMD (reference DMLC_NUM_WORKER).", int)
register_flag(
    "MXNET_TPU_PROC_ID", None,
    "This process's rank (reference DMLC_WORKER_ID).", int)
register_flag(
    "MXNET_RNG_IMPL", "rbg",
    "JAX PRNG implementation (rbg / unsafe_rbg / threefry2x32). rbg "
    "drives the chip's hardware RNG for bulk bits (3x faster dropout "
    "masks on v5e); threefry2x32 restores bitwise key-stream "
    "reproducibility across backends. Read at import, before config "
    "is loadable.")
register_flag(
    "MXNET_LOCKDEP", False,
    "Runtime lock-order sanitizer (resilience.lockdep): instruments "
    "threading.Lock/RLock/Condition, records the acquisition-order "
    "graph, reports cycles and blocking-under-lock through the flight "
    "recorder. Off = nothing is patched (zero overhead).", _bool)
register_flag(
    "MXNET_PROFILER_AUTOSTART", False,
    "Start the telemetry event bus (mxnet_tpu.profiler) at import; "
    "reference MXNET_PROFILER_AUTOSTART contract.", _bool)
register_flag(
    "MXNET_PROFILER_IMPERATIVE", False,
    "Opt into per-op imperative dispatch counters "
    "(profiler.set_config(profile_imperative=True)).", _bool)
register_flag(
    "MXNET_CACHEDOP_SIG_LIMIT", 16,
    "Distinct-signature count above which one CachedOp warns about a "
    "recompile storm (varying shapes/dtypes/static args defeating the "
    "executable cache).", int)
register_flag(
    "MXNET_FAULT_PLAN", None,
    "Fault-injection plan for the resilience subsystem: inline JSON or "
    "@/path/to/plan.json (mxnet_tpu.resilience.faults docstring has the "
    "schema). Installed lazily on first use; unset disables injection.")
register_flag(
    "MXNET_COLLECTIVE_TIMEOUT", 0.0,
    "Seconds before the dist_tpu collective watchdog declares a hung "
    "collective and raises CollectiveTimeoutError (then the circuit "
    "breaker degrades to the eager fallback). 0 disables the watchdog "
    "(zero overhead).", float)
register_flag(
    "MXNET_COMPILE_MAX_RETRIES", 2,
    "Extra attempts for a transiently-failing XLA compile (CachedOp "
    "build, dist_tpu AOT lower().compile()).", int)
register_flag(
    "MXNET_COLLECTIVE_MAX_RETRIES", 2,
    "Extra attempts for a transiently-failing dist_tpu collective before "
    "it counts as a fast-path failure (degradation + breaker).", int)
register_flag(
    "MXNET_RETRY_BASE_DELAY_MS", 5.0,
    "First retry backoff delay in ms; doubles per attempt.", float)
register_flag(
    "MXNET_RETRY_MAX_DELAY_MS", 250.0,
    "Backoff delay ceiling in ms.", float)
register_flag(
    "MXNET_COLLECTIVE_BREAKER_THRESHOLD", 3,
    "Consecutive dist_tpu fast-path failures that trip the circuit "
    "breaker open (eager fallback only until cooldown).", int)
register_flag(
    "MXNET_COLLECTIVE_BREAKER_COOLDOWN", 8,
    "Fast-path queries the breaker stays open before letting one "
    "half-open probe re-test the collective path.", int)
register_flag(
    "MXNET_NAN_QUARANTINE", False,
    "Pre-collective non-finite sentinel in dist_tpu.allreduce: a gradient "
    "with NaN/Inf is caught BEFORE it poisons the whole mesh's allreduce. "
    "Costs one fused isfinite reduction + host sync per reduced tensor, "
    "so off by default.", _bool)
register_flag(
    "MXNET_NAN_QUARANTINE_MODE", "skip",
    "What the quarantine does on trip: 'skip' raises NonFiniteGradError "
    "(GuardrailHandler turns it into a skipped step); 'drop' excludes the "
    "poisoned replicas and sums the clean ones, rescaled by "
    "n_total/n_clean to keep the expected gradient magnitude.")
register_flag(
    "MXNET_GUARDRAIL_SPIKE_WINDOW", 32,
    "Rolling-window length for the guardrail loss-spike detector "
    "(resilience.guardrails.SpikeDetector).", int)
register_flag(
    "MXNET_GUARDRAIL_SPIKE_ZSCORE", 6.0,
    "Z-score over the rolling window above which a loss value counts as "
    "a spike (plus a 2x relative-jump floor for flat windows).", float)
register_flag(
    "MXNET_GUARDRAIL_WARMUP", 8,
    "Steps the spike detector only builds statistics for before it may "
    "flag (the initial loss cliff is expected, not an anomaly).", int)
register_flag(
    "MXNET_GUARDRAIL_MAX_SKIPS", 3,
    "Consecutive guardrail skip-steps before escalation to "
    "rewind-and-skip (GuardrailHandler).", int)
register_flag(
    "MXNET_GUARDRAIL_MAX_REWINDS", 2,
    "Rewind-and-skip recoveries before GuardrailHandler gives up and "
    "raises DivergenceError.", int)
register_flag(
    "MXNET_SERVE_BATCH_TIMEOUT_MS", 5.0,
    "DynamicBatcher flush deadline: an admitted request waits at most this "
    "long for batch-mates before the partial batch dispatches "
    "(mxnet_tpu.serve.batcher).", float)
register_flag(
    "MXNET_SERVE_MAX_BATCH", 8,
    "DynamicBatcher flush size: a batch dispatches immediately once this "
    "many requests are queued (should match the serving session's largest "
    "batch bucket).", int)
register_flag(
    "MXNET_SERVE_MAX_QUEUE", 64,
    "Admission-control cap on DynamicBatcher queue depth: submissions "
    "beyond it fast-reject with ServiceUnavailable (503) instead of "
    "building an unbounded backlog.", int)
register_flag(
    "MXNET_SERVE_TIMEOUT_MS", 0.0,
    "Per-execution watchdog for serve.InferenceSession: a hung executable "
    "becomes a fast ServiceUnavailable (503) after this many ms instead "
    "of wedging the serving thread. 0 disables (zero overhead).", float)
register_flag(
    "MXNET_SERVE_BREAKER_THRESHOLD", 3,
    "Consecutive InferenceSession execution failures that trip the "
    "session circuit breaker open (requests fast-reject until cooldown).",
    int)
register_flag(
    "MXNET_SERVE_BREAKER_COOLDOWN", 8,
    "Rejected calls the serve breaker stays open before letting one "
    "half-open probe re-test the session.", int)
register_flag(
    "MXNET_SERVE_METRICS_WINDOW", 2048,
    "Ring-buffer sample count backing the serve p50/p95/p99 latency "
    "percentiles (serve.metrics).", int)
register_flag(
    "MXNET_SERVE_DEADLINE_MS", 0.0,
    "Default request deadline attached at DynamicBatcher.submit when the "
    "caller passes none: expired requests are cancelled at every stage "
    "boundary (admission, queue sweep, post-execute settle) with "
    "DeadlineExceeded (504) instead of completing late. 0 disables — no "
    "deadline checks anywhere (the original semantics).", float)
register_flag(
    "MXNET_SERVE_DEADLINE_GRACE_MS", 0.0,
    "Slack past a request's deadline within which a completed result is "
    "still delivered (counted as a late_completion against goodput); "
    "beyond deadline+grace the result is discarded and the future "
    "settles with DeadlineExceeded.", float)
register_flag(
    "MXNET_SERVE_BATCH_QUEUE_SHARE", 1.0,
    "Fraction of MXNET_SERVE_MAX_QUEUE the batch priority class may "
    "occupy; batch-class submits beyond it shed with 503 so interactive "
    "traffic always finds queue headroom. 1.0 (default) reserves "
    "nothing.", float)
register_flag(
    "MXNET_SERVE_RATE_LIMIT", 0.0,
    "Token-bucket refill rate (requests/s) gating batch-class admission "
    "in DynamicBatcher.submit; interactive traffic is never rate-"
    "limited. 0 disables the bucket.", float)
register_flag(
    "MXNET_SERVE_RATE_BURST", 16,
    "Token-bucket capacity for MXNET_SERVE_RATE_LIMIT: the batch-class "
    "burst admitted from an idle bucket before the rate applies.", int)
register_flag(
    "MXNET_SERVE_STRICT_PARITY", False,
    "Pin serve.Generator to the PR-5 strict decode path: shape-stable "
    "mul+reduce ops on the deterministic runtime, bitwise prefill/decode "
    "parity, overriding any decode_path argument or "
    "MXNET_SERVE_DECODE_PATH. Off (default): the fast rungs carry a "
    "tolerance-based parity contract instead.", _bool)
register_flag(
    "MXNET_SERVE_DECODE_PATH", "auto",
    "Default decode rung for serve.Generator when the constructor passes "
    "none: auto (= pallas), baseline (strict PR-5 ops), pallas (fused "
    "decode-attention kernel), int8 (pallas + int8 KV-cache rings and "
    "weights).", str)
register_flag(
    "MXNET_SERVE_DECODE_INT8_WEIGHTS", "auto",
    "On the int8 decode rung, also pre-quantize the model's serving "
    "projection weights to per-channel int8 (ops.nn.quantized_dense). "
    "auto (default): only on backends with int8 matrix units (tpu/axon) "
    "— on CPU the per-step int8->f32 weight convert costs more than the "
    "f32 gemm saves, so auto keeps weights f32 there. 1/0 force it "
    "on/off; the KV-cache rings stay int8 either way.", str)
register_flag(
    "MXNET_SERVE_KV_PAGED", False,
    "Back serve.Generator KV state with the paged block pool "
    "(serve.kv_blocks.PagedKVPool, fully assigned) instead of contiguous "
    "per-bucket rings. serve.scheduler.ContinuousEngine is always paged "
    "regardless of this flag.", _bool)
register_flag(
    "MXNET_SERVE_KV_PAGE_SIZE", 0,
    "KV page width in tokens for the paged block allocator. 0 (default): "
    "the Pallas decode kernel's natural block (128) clamped to max_seq, "
    "so the kernel's block-skip masking skips whole unreached pages. "
    "max_seq must be a whole number of pages.", int)
register_flag(
    "MXNET_SERVE_KV_PAGES", 0,
    "Paged-KV pool capacity in pages (including the reserved null page). "
    "0 (default): auto-size to full capacity — every slot can hold "
    "max_seq and exhaustion is impossible. Smaller values oversubscribe: "
    "admission queues on PoolExhausted (503) until retirements recycle "
    "pages.", int)
register_flag(
    "MXNET_SERVE_SLOTS", 8,
    "Decode lanes for serve.scheduler.ContinuousEngine: the ONE compiled "
    "decode width. Requests are admitted into free lanes and retired "
    "from finished ones between decode steps; idle lanes ride along on "
    "the null KV page.", int)
register_flag(
    "MXNET_SERVE_PREFILL_CHUNK", 0,
    "Prompt tokens prefilled per continuous-batching scheduler iteration "
    "at the fixed (1, chunk) signature. 0 (default): one KV page. "
    "Bounds how long a long prompt can stall live decode streams (one "
    "chunk per iteration).", int)
register_flag(
    "MXNET_SERVE_PREFIX_CACHE", False,
    "Cross-request KV prefix reuse (serve.prefix_cache.PrefixCache): a "
    "radix trie over prompt token ids maps matched prefixes to "
    "refcounted pages in the paged KV pool, so admission skips the "
    "matched portion of chunked prefill. Shared pages are read-only "
    "(copy-on-extend at page granularity); LRU eviction reclaims cached "
    "prefixes only under pool pressure. Greedy outputs stay "
    "token-identical to a cache-off run.", _bool)
register_flag(
    "MXNET_COMPILE_CACHE_DIR", "",
    "Directory backing the persistent compile cache "
    "(mxnet_tpu.compile_cache, JAX persistent compilation cache "
    "substrate): executables keyed on the stable serialization of "
    "CachedOp signature keys + compiler options land on disk, so "
    "warmup() in a fresh process replays the bucket lattice from disk "
    "instead of recompiling (cache_stats() grows disk_hits/disk_misses). "
    "Empty (default) disables.", str)
register_flag(
    "MXNET_SERVE_MAX_MODELS", 4,
    "Resident-model budget for serve.tenancy.ModelRegistry: at most "
    "this many named models (executables + per-tenant KV pool + prefix "
    "trie) stay loaded per process; loading past the budget LRU-evicts "
    "the coldest idle tenant. Evicted models reload via load() — warm "
    "from the disk compile cache when MXNET_COMPILE_CACHE_DIR is "
    "set.", int)
register_flag(
    "MXNET_SERVE_SPEC_TOKENS", 4,
    "Draft tokens proposed per speculative-decoding round "
    "(serve.SpeculativeGenerator's default k): each round costs k draft "
    "steps plus one k+1-wide target verify step.", int)
register_flag(
    "MXNET_SERVE_MULTISTEP", False,
    "Run the decode loop as device-side multi-step super-steps: one "
    "compiled lax.while_loop executes up to MXNET_SERVE_DECODE_STEPS "
    "decode iterations (model forward + in-trace sampling + EOS/budget "
    "masking) per host visit, and the host settles the returned "
    "(slots, N) token block in one pass. Off (default): one host visit "
    "per token (the PR-10 behavior).", _bool)
register_flag(
    "MXNET_SERVE_DECODE_STEPS", 8,
    "Decode iterations per multi-step super-step (the compiled loop's "
    "static trip-count ceiling N). The host can lower the per-call "
    "limit down to 1 through the same executable — tight deadlines "
    "auto-degrade to single-step so 504 retirement latency stays "
    "bounded by one iteration.", int)
register_flag(
    "MXNET_FLEET_HEDGE_MS", 0.0,
    "Hedged-retry delay for serve.fleet.Router: an *interactive* request "
    "dispatched to a replica flagged straggling gets a second (hedge) "
    "dispatch to the next-best replica after this many ms unless it has "
    "already settled; first settle wins, the loser is cancelled and "
    "counted. Batch-class requests are never hedged, and a request is "
    "never hedged twice. 0 (default) disables hedging.", float)
register_flag(
    "MXNET_FLEET_STRAGGLER_MS", 150.0,
    "Per-replica latency-lag EWMA (vs the fleet median, "
    "resilience.elastic.StragglerMonitor) above which the Router flags a "
    "replica as straggling — the precondition for arming a hedge timer. "
    "0: track only, never flag (hedging never fires).", float)
register_flag(
    "MXNET_FLEET_MAX_FAILOVERS", 2,
    "Times the Router will re-dispatch one request to a surviving "
    "replica after replica deaths/quarantines before failing it with "
    "ServiceUnavailable (bounds the work a poisonous request can burn "
    "while the fleet is melting).", int)
register_flag(
    "MXNET_FLEET_PROBE_MS", 25.0,
    "Router supervisor probe interval: how often each replica's "
    "liveness (flusher thread) and session breaker are checked so a "
    "replica that died *between* dispatches is still detected and its "
    "in-flight work failed over. 0 disables the supervisor thread "
    "(detection then only happens at dispatch boundaries).", float)
register_flag(
    "MXNET_FLEET_BREAKER_THRESHOLD", 2,
    "Consecutive replica-attributed dispatch/settle failures that "
    "quarantine a replica behind the Router's per-replica circuit "
    "breaker (dispatch routes around it until a half-open probe "
    "heals it).", int)
register_flag(
    "MXNET_FLEET_BREAKER_COOLDOWN", 8,
    "Dispatch picks a quarantined replica sits out before the Router's "
    "per-replica breaker goes half-open and routes one probe request "
    "through it.", int)
register_flag(
    "MXNET_ELASTIC", False,
    "Elastic multichip training (resilience.elastic): dist_tpu classifies "
    "collective failures that look like a LOST DEVICE GROUP (injected "
    "chip_loss, dead-peer runtime errors) as MeshDegraded instead of "
    "degrading to the eager fallback, so an ElasticTrainingHandler can "
    "shrink the mesh and resume from a sharded checkpoint. Off (default): "
    "every failure keeps the PR-2 degrade/retry semantics bitwise.", _bool)
register_flag(
    "MXNET_ELASTIC_MAX_RESTARTS", 2,
    "Mesh-loss restarts an ElasticTrainingHandler absorbs before "
    "re-raising MeshDegraded (a mesh shedding chips repeatedly is a "
    "hardware incident, not a recoverable blip).", int)
register_flag(
    "MXNET_ELASTIC_MIN_REPLICAS", 1,
    "Fewest surviving data-parallel replicas an elastic restart will "
    "resume on; fewer survivors re-raises MeshDegraded.", int)
register_flag(
    "MXNET_ELASTIC_REBUILD", True,
    "Composed-mesh (dp×tp(×pp)) elasticity: on chip loss, "
    "ElasticTrainingHandler.recover_sharded rebuilds the mesh with "
    "parallel.mesh.rebuild_mesh (tp/pp extents pinned, touched dp-groups "
    "dropped) and reshards the newest layout-carrying sharded checkpoint "
    "onto the survivors. 0: composed-mesh losses re-raise (the pre-rebuild "
    "degrade path), pure-dp shrink_mesh elasticity is unaffected.", _bool)
register_flag(
    "MXNET_ELASTIC_MIN_DP_GROUPS", 1,
    "Fewest surviving data-parallel GROUPS (dp extent of the rebuilt "
    "composed mesh) recover_sharded will resume on; fewer survivors "
    "re-raises the mesh loss.", int)
register_flag(
    "MXNET_DESYNC_CHECK_STEPS", 0,
    "Cadence (in batches) of the cross-replica parameter-fingerprint "
    "desync audit (resilience.elastic.DesyncAuditHandler). 0 (default) "
    "disables the audit — one int compare per batch.", int)
register_flag(
    "MXNET_DESYNC_MAX_RESYNCS", 2,
    "Resync-from-peer repairs the desync audit performs before "
    "escalating to rewind (then DivergenceError).", int)
register_flag(
    "MXNET_STRAGGLER_THRESHOLD_MS", 0.0,
    "Per-replica collective-arrival-lag EWMA (ms) above which the "
    "straggler monitor flags a replica (resilience.stragglers counter + "
    "rate-limited warning). 0 (default): tracking-only, never flags.",
    float)
register_flag(
    "MXNET_CKPT_ASYNC", False,
    "Async checkpointing (resilience.checkpoint): CheckpointManager.save "
    "stalls only for the synchronous host snapshot of params/trainer/"
    "data state, then packs, CRCs and atomically writes on a background "
    "thread; the generation is advertised only after its commit lands, "
    "and every manager read fences on the in-flight write. Off "
    "(default): the whole save happens in the caller (PR-4 semantics).",
    _bool)
register_flag(
    "MXNET_CKPT_STALL_BUDGET_MS", 0.0,
    "Budget (ms) for an async save's synchronous stall (the host "
    "snapshot). Exceeding it counts resilience.ckpt_stall_overruns and "
    "warns, rate-limited — the stall is the part the step loop actually "
    "feels, so overruns mean the snapshot itself got too slow. 0 "
    "(default): unbudgeted.", float)
register_flag(
    "MXNET_PREEMPT_GRACE_S", 30.0,
    "Grace window (seconds) a preempted process has to drain "
    "(resilience.preemption): the serving-side drain (fleet Routers, "
    "registered batchers) is bounded by it; training uses it as the "
    "budget between the SIGTERM and the force-saved checkpoint's "
    "commit.", float)
register_flag(
    "MXNET_LOSS_SCALE_MIN", 1.0,
    "Lower clamp for the dynamic LossScaler (amp.py): repeated overflows "
    "can never drive the scale to 0.", float)
register_flag(
    "MXNET_LOSS_SCALE_MAX", 2.0 ** 24,
    "Upper clamp for the dynamic LossScaler: a long overflow-free run "
    "can never drive the scale to inf.", float)
register_flag(
    "MXNET_TRACE", False,
    "Enable request-scoped tracing (profiler.trace): serving submits and "
    "training steps get per-request Trace ids whose spans are emitted as "
    "chrome async/flow events when the profiler bus records. Off: one "
    "bool check per instrumented site.", _bool)
register_flag(
    "MXNET_TRACE_MAX", 1024,
    "Bounded in-process trace registry size (oldest traces evicted); the "
    "profiler.trace.summary(trace_id) lookback window.", int)
register_flag(
    "MXNET_FLIGHT_RECORDER", True,
    "Always-on flight recorder (profiler.recorder): a bounded ring of "
    "recent warnings/faults/escalations dumped to JSON automatically at "
    "DivergenceError / MeshDegraded / checkpoint quarantine / "
    "breaker-open / watchdog timeout. 0 disables (ring writes become one "
    "bool check).", _bool)
register_flag(
    "MXNET_FLIGHT_RECORDER_SIZE", 512,
    "Flight-recorder ring capacity (most recent N notes kept).", int)
register_flag(
    "MXNET_FLIGHT_RECORDER_DIR", None,
    "Directory for automatic flight-recorder dumps "
    "(flightrec-<utc>-<reason>.json). Default: the system tempdir.")
register_flag(
    "MXNET_FLIGHT_RECORDER_MAX_DUMPS", 16,
    "Per-process cap on automatic flight-recorder dump files (first "
    "escalations win; later ones only land in the ring).", int)
register_flag(
    "MXNET_KVSTORE_BUCKET_MB", 0.0,
    "Coalesce per-parameter collectives into flat fusion buffers of this "
    "many MB (kvstore.bucketing.GradBucketer): gradient pushpull in "
    "gluon.Trainer and the ZeRO param all-gathers in ShardedTrainer both "
    "collapse to one collective per bucket. 0 (default): per-parameter "
    "collectives, the pre-bucketing behavior.", float)
register_flag(
    "MXNET_KVSTORE_OVERLAP", True,
    "With bucketing on, dispatch every bucket's collective async "
    "(front-layer buckets first) and let the engine overlap them with "
    "compute; 0 blocks after each bucket flush — the ablation baseline, "
    "not a correctness knob (both settings are bitwise-identical).",
    _bool)
register_flag(
    "MXNET_GRADIENT_COMPRESSION", "",
    "Gradient compression for dist_tpu pushpull: '2bit' quantizes every "
    "pushed grad to {-threshold, 0, +threshold} with per-(key, replica) "
    "error-feedback residuals (kvstore.gradient_compression). Empty "
    "(default): off — compression is approximate; opt in per run.")
register_flag(
    "MXNET_METRICS_PORT", 0,
    "Serve the unified telemetry surface (profiler.export) over stdlib "
    "HTTP on this port: /metrics (Prometheus text), /healthz (serving "
    "health JSON), /snapshot (full JSON). Unset (default): no server. "
    "Explicitly set to 0: bind an EPHEMERAL port (no CI port-collision "
    "flakes) and report it back via a MXNET_METRICS_PORT_BOUND=<port> "
    "line on stderr + profiler.export.server_port().", int)
register_flag(
    "MXNET_ATTRIBUTION", False,
    "Decode critical-path attribution (profiler.attribution): split "
    "every decode iteration's wall time into host / dispatch / device / "
    "wait phases, tag engine:wait stalls with the active phase, and "
    "publish serve.<name>.host_overhead_fraction / device_ms_per_token "
    "gauges. Off: one bool check per instrumented site.", _bool)
register_flag(
    "MXNET_ATTRIBUTION_WINDOW", 512,
    "Rolling window (decode iterations) of the attribution ledger's "
    "steady-state gauges.", int)
register_flag(
    "MXNET_SLO_WINDOW_S", 60.0,
    "Default slow evaluation window (seconds) for SLO objectives "
    "(profiler.slo.SLO) constructed without an explicit window; the "
    "fast window defaults to 1/12 of it (the SRE 1h/5m shape).", float)
register_flag(
    "MXNET_SLO_BURN_THRESHOLD", 14.4,
    "Default error-budget burn-rate alert threshold: an objective burns "
    "only when BOTH its fast and slow windows exceed this (14.4 is the "
    "classic fast-page rate).", float)
register_flag(
    "MXNET_SLO_EVAL_INTERVAL_S", 0.25,
    "Minimum seconds between passive SLO burn-rate evaluations on the "
    "observing thread (amortizes the window walk).", float)
register_flag(
    "MXNET_SLO_MIN_EVENTS", 12,
    "Minimum fast-window events before an SLO objective may alert — a "
    "sparse healthy run cannot false-alarm.", int)
register_flag(
    "MXNET_IO_WORKERS", 4,
    "Default decode-pool width of io.pipeline.RecordPipeline: named "
    "daemon worker threads pulling record ranges, decoding and "
    "batchifying into the bounded output queue (the reference's "
    "iter_image_recordio_2.cc decode-thread pool).", int)
register_flag(
    "MXNET_IO_QUEUE_DEPTH", 8,
    "Bounded output-queue depth (batches) of the RecordPipeline decode "
    "pool — workers block (backpressure) once this many decoded batches "
    "are waiting for the consumer.", int)
register_flag(
    "MXNET_IO_SHUFFLE_BUFFER", 1024,
    "Window size of the seedable streaming shuffle in RecordPipeline "
    "and ShardedRecordDataset epoch-order draws: records are shuffled "
    "within a sliding window of this many entries (bounded-memory "
    "approximate shuffle; <= 1 disables shuffling beyond epoch seed "
    "order).", int)
register_flag(
    "MXNET_IO_DEVICE_BUFFERS", 2,
    "Batches the io.pipeline.DeviceFeeder keeps device-resident via "
    "async device_put — K=2 double-buffers H2D for batch k+1 under "
    "step k's compute.", int)
register_flag(
    "MXNET_IO_CHECK_INDEX", True,
    "Integrity-check every RecordIO .idx at open (4-byte-aligned, "
    "strictly increasing offsets that fit the .rec size); a corrupt "
    "index raises MXNetError naming the file instead of serving wrong "
    "records. 0 skips the check (e.g. for deliberately exotic "
    "hand-built indexes).", _bool)
