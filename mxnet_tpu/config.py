"""Environment-flag registry with introspection.

The reference configures itself through ~100 ``MXNET_*`` env vars read via
``dmlc::GetEnv`` at use sites, documented centrally in
``docs/.../env_var.md``, plus self-describing ``dmlc::Parameter`` structs.
This module is the TPU build's equivalent: every flag the framework reads
is registered here with its type, default, and doc, and
``mx.config.describe()`` prints the live table (value, source) the way
``__getdoc__`` exposes Parameter fields.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, NamedTuple


class Flag(NamedTuple):
    name: str
    default: Any
    doc: str
    parse: Callable[[str], Any]


_FLAGS: Dict[str, Flag] = {}


def _bool(s: str) -> bool:
    return s not in ("0", "false", "False", "")


def register_flag(name, default, doc, parse=str):
    _FLAGS[name] = Flag(name, default, doc, parse)
    return _FLAGS[name]


def get(name):
    """Typed value of a registered flag (env wins over default)."""
    flag = _FLAGS[name]
    raw = os.environ.get(name)
    if raw is None:
        return flag.default
    return flag.parse(raw)


def is_set(name) -> bool:
    return name in os.environ


def list_flags():
    """All registered flag names (env_var.md table analog)."""
    return sorted(_FLAGS)


def describe(file=None):
    """Print name / current value / default / doc for every flag."""
    import sys

    out = file or sys.stdout
    for name in list_flags():
        f = _FLAGS[name]
        cur = get(name)
        src = "env" if is_set(name) else "default"
        print(f"{name} = {cur!r} ({src}; default {f.default!r})\n"
              f"    {f.doc}", file=out)


# ---------------------------------------------------------------------------
# The flags this framework reads (each registered next to its semantics;
# reference: docs/static_site/src/pages/api/faq/env_var.md)
# ---------------------------------------------------------------------------

register_flag(
    "MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice",
    "Execution engine. 'NaiveEngine' blocks after every op (serialized "
    "debugging, reference src/engine/naive_engine.cc); the default maps to "
    "XLA async dispatch.")
register_flag(
    "MXNET_EAGER_JIT_CACHE", True,
    "Cache one jax.jit executable per (op, static config) for imperative "
    "dispatch (SURVEY §7 hard part 2). 0 disables.", _bool)
register_flag(
    "MXNET_WAITALL_FULL", False,
    "mx.npx.waitall() sweeps every live array (exhaustive, slow) instead "
    "of the recently-dispatched set.", _bool)
register_flag(
    "MXNET_TPU_PEAK_FLOPS", None,
    "Override the chip peak FLOP/s used as the MFU denominator in "
    "bench.py (default: by device_kind).",
    float)
register_flag(
    "MXNET_TPU_NO_NATIVE", False,
    "Disable the ctypes native library (native/recordio.cc prefetcher); "
    "pure-Python fallbacks are used.", _bool)
register_flag(
    "MXNET_TPU_COORDINATOR", None,
    "host:port of process 0 for jax.distributed.initialize; set by "
    "tools/launch.py (reference DMLC_PS_ROOT_URI/PORT).")
register_flag(
    "MXNET_TPU_NUM_PROCS", None,
    "World size for multi-process SPMD (reference DMLC_NUM_WORKER).", int)
register_flag(
    "MXNET_TPU_PROC_ID", None,
    "This process's rank (reference DMLC_WORKER_ID).", int)
register_flag(
    "MXNET_MODULE_SEED", None,
    "Base RNG seed for the test suite's per-test seeding (reference "
    "tests conftest.py reproduction flow).", int)
register_flag(
    "MXNET_PROFILER_AUTOSTART", False,
    "Start the telemetry event bus (mxnet_tpu.profiler) at import; "
    "reference MXNET_PROFILER_AUTOSTART contract.", _bool)
register_flag(
    "MXNET_PROFILER_IMPERATIVE", False,
    "Opt into per-op imperative dispatch counters "
    "(profiler.set_config(profile_imperative=True)).", _bool)
register_flag(
    "MXNET_CACHEDOP_SIG_LIMIT", 16,
    "Distinct-signature count above which one CachedOp warns about a "
    "recompile storm (varying shapes/dtypes/static args defeating the "
    "executable cache).", int)
register_flag(
    "MXNET_FAULT_PLAN", None,
    "Fault-injection plan for the resilience subsystem: inline JSON or "
    "@/path/to/plan.json (mxnet_tpu.resilience.faults docstring has the "
    "schema). Installed lazily on first use; unset disables injection.")
register_flag(
    "MXNET_COLLECTIVE_TIMEOUT", 0.0,
    "Seconds before the dist_tpu collective watchdog declares a hung "
    "collective and raises CollectiveTimeoutError (then the circuit "
    "breaker degrades to the eager fallback). 0 disables the watchdog "
    "(zero overhead).", float)
register_flag(
    "MXNET_COMPILE_MAX_RETRIES", 2,
    "Extra attempts for a transiently-failing XLA compile (CachedOp "
    "build, dist_tpu AOT lower().compile()).", int)
register_flag(
    "MXNET_COLLECTIVE_MAX_RETRIES", 2,
    "Extra attempts for a transiently-failing dist_tpu collective before "
    "it counts as a fast-path failure (degradation + breaker).", int)
register_flag(
    "MXNET_RETRY_BASE_DELAY_MS", 5.0,
    "First retry backoff delay in ms; doubles per attempt.", float)
register_flag(
    "MXNET_RETRY_MAX_DELAY_MS", 250.0,
    "Backoff delay ceiling in ms.", float)
register_flag(
    "MXNET_COLLECTIVE_BREAKER_THRESHOLD", 3,
    "Consecutive dist_tpu fast-path failures that trip the circuit "
    "breaker open (eager fallback only until cooldown).", int)
register_flag(
    "MXNET_COLLECTIVE_BREAKER_COOLDOWN", 8,
    "Fast-path queries the breaker stays open before letting one "
    "half-open probe re-test the collective path.", int)
