"""Weight initializers (reference ``python/mxnet/initializer.py``, 14 classes).

Each initializer fills an NDArray in place given a fresh RNG key; shapes are
interpreted with the reference's conventions (conv weight OIHW fan
computation etc.).
"""
from __future__ import annotations

import math

import numpy as _onp

from .base import MXNetError
from .ndarray.ndarray import NDArray

_REGISTRY = {}


def register(cls):
    _REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(name, **kwargs):
    if isinstance(name, Initializer):
        return name
    try:
        return _REGISTRY[name.lower()](**kwargs)
    except KeyError:
        raise MXNetError(f"unknown initializer {name!r}") from None


class Initializer:
    """Base initializer; call via ``init(name_or_desc, arr)`` like reference."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def __call__(self, name, arr: NDArray):
        self.init_weight(name, arr)

    def init_weight(self, name, arr):
        if name is None:
            name = ""
        if name.endswith("gamma"):
            self._init_one(arr)
        elif name.endswith("beta") or name.endswith("bias"):
            self._init_zero(arr)
        elif name.endswith("running_mean") or name.endswith("moving_mean"):
            self._init_zero(arr)
        elif name.endswith("running_var") or name.endswith("moving_var"):
            self._init_one(arr)
        else:
            self._init_weight(name, arr)

    @staticmethod
    def _init_zero(arr):
        import jax.numpy as jnp

        arr._set_data_internal(jnp.zeros(arr.shape, arr.dtype))

    @staticmethod
    def _init_one(arr):
        import jax.numpy as jnp

        arr._set_data_internal(jnp.ones(arr.shape, arr.dtype))

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def dumps(self):
        """JSON ``'["<name>", {<kwargs>}]'`` form consumed by
        update-on-kvstore optimizer shipping (reference
        ``initializer.py:99-118``)."""
        import json

        return json.dumps([type(self).__name__.lower(), self._kwargs])

    def __repr__(self):
        return f"{type(self).__name__}({self._kwargs})"


def _fans(shape):
    if len(shape) < 2:
        return (shape[0] if shape else 1,) * 2
    receptive = 1
    for s in shape[2:]:
        receptive *= s
    fan_in = shape[1] * receptive
    fan_out = shape[0] * receptive
    return fan_in, fan_out


def _fill_random(arr, sampler):
    from . import random as _rng
    import jax.random as jr

    key = _rng.next_key()
    arr._set_data_internal(sampler(jr, key))


@register
class Zero(Initializer):
    def _init_weight(self, name, arr):
        self._init_zero(arr)


@register
class One(Initializer):
    def _init_weight(self, name, arr):
        self._init_one(arr)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, name, arr):
        import jax.numpy as jnp

        v = self.value
        if isinstance(v, NDArray):
            arr._set_data_internal(jnp.broadcast_to(v._data, arr.shape).astype(arr.dtype))
        else:
            arr._set_data_internal(jnp.full(arr.shape, v, arr.dtype))


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, name, arr):
        _fill_random(arr, lambda jr, k: jr.uniform(
            k, arr.shape, arr.dtype if _onp.issubdtype(arr.dtype, _onp.floating) else _onp.float32,
            -self.scale, self.scale))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, name, arr):
        _fill_random(arr, lambda jr, k: jr.normal(k, arr.shape, arr.dtype) * self.sigma)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale

    def _init_weight(self, name, arr):
        import jax.random as jr
        from . import random as _rng

        key = _rng.next_key()
        flat = (arr.shape[0], int(_onp.prod(arr.shape[1:])) if len(arr.shape) > 1 else 1)
        q = jr.orthogonal(key, max(flat)).astype(arr.dtype)
        q = q[: flat[0], : flat[1]]
        arr._set_data_internal((self.scale * q).reshape(arr.shape))


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        fan_in, fan_out = _fans(arr.shape)
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError(f"bad factor_type {self.factor_type!r}")
        scale = math.sqrt(self.magnitude / max(factor, 1.0))
        if self.rnd_type == "uniform":
            _fill_random(arr, lambda jr, k: jr.uniform(k, arr.shape, arr.dtype,
                                                       -scale, scale))
        elif self.rnd_type == "gaussian":
            _fill_random(arr, lambda jr, k: jr.normal(k, arr.shape, arr.dtype) * scale)
        else:
            raise MXNetError(f"bad rnd_type {self.rnd_type!r}")


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        import jax.numpy as jnp

        weight = _onp.zeros(arr.shape, dtype="float32")
        shape = arr.shape
        f = shape[3] / 2.0
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(_onp.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight.flat[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr._set_data_internal(jnp.asarray(weight, arr.dtype))


@register
class LSTMBias(Initializer):
    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        import jax.numpy as jnp

        b = _onp.zeros(arr.shape, "float32")
        num_hidden = arr.shape[0] // 4
        b[num_hidden: 2 * num_hidden] = self.forget_bias
        arr._set_data_internal(jnp.asarray(b, arr.dtype))


@register
class Mixed(Initializer):
    """Dispatch to one of several initializers by parameter-name regex
    (reference ``initializer.py`` Mixed): first matching pattern wins.

    >>> init = mx.init.Mixed(['bias', '.*'],
    ...                      [mx.init.Zero(), mx.init.Uniform(0.1)])
    """

    def __init__(self, patterns, initializers, **kwargs):
        import re

        super().__init__(patterns=patterns, initializers=initializers,
                         **kwargs)
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed: len(patterns) != len(initializers)")
        self.map = [(re.compile(p), init) for p, init in
                    zip(patterns, initializers)]

    def __call__(self, name, arr):
        for pat, init in self.map:
            if pat.search(name or ""):
                # the matched initializer's own fill applies — NOT the
                # base class's role-suffix shortcuts (which would, e.g.,
                # zero a bias the user explicitly matched to Constant)
                init._init_weight(name, arr)
                return
        raise MXNetError(
            f"Parameter {name!r} matched no Mixed pattern; add '.*' as the "
            "last pattern for a default")

    # Mixed dispatches whole-name; the role-suffix shortcuts of the base
    # class must not pre-empt the user's patterns
    init_weight = __call__


@register
class InitDesc(str):  # pragma: no cover - reference API surface
    pass


# name-style aliases the reference accepts in create()
_REGISTRY.update(
    zeros=Zero,
    ones=One,
    xavier=Xavier,
    msra=MSRAPrelu,
    uniform=Uniform,
    normal=Normal,
    orthogonal=Orthogonal,
    bilinear=Bilinear,
    constant=Constant,
    lstmbias=LSTMBias,
)
