"""Samplers (reference: ``python/mxnet/gluon/data/sampler.py``)."""
from __future__ import annotations

import numpy as _onp

from ...base import MXNetError


class Sampler:
    def __len__(self):
        raise NotImplementedError

    def __iter__(self):
        raise NotImplementedError

    # resumable-iteration protocol: stateless samplers (sequential,
    # filter, interval — their order is a pure function of construction)
    # inherit these no-ops; samplers with draw/rollover state override
    def state_dict(self):
        return {}

    def load_state_dict(self, state):
        pass


class SequentialSampler(Sampler):
    def __init__(self, length, start=0):
        self._length = length
        self._start = start

    def __iter__(self):
        return iter(range(self._start, self._start + self._length))

    def __len__(self):
        return self._length


class RandomSampler(Sampler):
    def __init__(self, length):
        self._length = length
        self._draw_state = None    # RNG state that produced the CURRENT
        self._resume_state = None  # epoch's permutation / restore request

    def __iter__(self):
        if self._resume_state is not None:
            # resume path: replay the permutation the interrupted epoch
            # was drawn with, from a private RandomState — the GLOBAL
            # numpy RNG is left untouched (restoring it would silently
            # rewind every other consumer of the global stream)
            self._draw_state, self._resume_state = self._resume_state, None
            rs = _onp.random.RandomState()
            rs.set_state(self._draw_state)
            indices = rs.permutation(self._length)
        else:
            self._draw_state = _onp.random.get_state()
            indices = _onp.random.permutation(self._length)
        return iter(indices.tolist())

    def __len__(self):
        return self._length

    def state_dict(self):
        """The RNG state captured immediately BEFORE the current epoch's
        permutation was drawn — enough to redraw the identical order on
        resume (the order itself can be huge; the state is 2.5 KB)."""
        return {"type": "RandomSampler", "draw_state": self._draw_state}

    def load_state_dict(self, state):
        self._resume_state = state.get("draw_state")


class FilterSampler(Sampler):
    """Indices of samples where ``fn(sample)`` holds (reference
    ``sampler.py:84``)."""

    def __init__(self, fn, dataset):
        self._indices = [i for i, s in enumerate(dataset) if fn(s)]

    def __iter__(self):
        return iter(self._indices)

    def __len__(self):
        return len(self._indices)


class BatchSampler(Sampler):
    """Wrap a sampler into batches; ``last_batch`` in {keep, discard,
    rollover} (reference ``sampler.py:113``)."""

    def __init__(self, sampler, batch_size, last_batch="keep"):
        if last_batch not in ("keep", "discard", "rollover"):
            raise MXNetError(
                f"last_batch must be keep/discard/rollover, got {last_batch}")
        self._sampler = sampler
        self._batch_size = batch_size
        self._last_batch = last_batch
        self._prev = []

    def __iter__(self):
        batch, self._prev = self._prev, []
        for i in self._sampler:
            batch.append(i)
            if len(batch) == self._batch_size:
                yield batch
                batch = []
        if batch:
            if self._last_batch == "keep":
                yield batch
            elif self._last_batch == "rollover":
                self._prev = batch

    def state_dict(self):
        inner = getattr(self._sampler, "state_dict", None)
        return {"type": "BatchSampler", "prev": list(self._prev),
                "sampler": inner() if inner is not None else None}

    def load_state_dict(self, state):
        self._prev = list(state.get("prev", []))
        if state.get("sampler") is not None \
                and hasattr(self._sampler, "load_state_dict"):
            self._sampler.load_state_dict(state["sampler"])

    def __len__(self):
        n = len(self._sampler) + len(self._prev)
        if self._last_batch == "keep":
            return (n + self._batch_size - 1) // self._batch_size
        if self._last_batch == "discard":
            return n // self._batch_size
        if self._last_batch == "rollover":
            return n // self._batch_size
        raise MXNetError(f"invalid last_batch {self._last_batch}")


class IntervalSampler(Sampler):
    """index, index+interval, ... then rollover (reference
    ``sampler.py:186``)."""

    def __init__(self, length, interval, rollover=True):
        assert interval <= length
        self._length = length
        self._interval = interval
        self._rollover = rollover

    def __iter__(self):
        for i in range(self._interval if self._rollover else 1):
            yield from range(i, self._length, self._interval)

    def __len__(self):
        if self._rollover:
            return self._length
        return len(range(0, self._length, self._interval))
