"""Datasets (reference: ``python/mxnet/gluon/data/dataset.py``)."""
from __future__ import annotations

from ...base import MXNetError


class Dataset:
    """Abstract dataset: ``__getitem__`` + ``__len__``."""

    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError

    def filter(self, fn):
        """Keep samples where ``fn(sample)`` is truthy (eager scan)."""
        return _FilteredDataset(self, fn)

    def shard(self, num_shards, index):
        """Every ``num_shards``-th sample starting at ``index`` (the
        DataLoader-side analog of distributed data sharding)."""
        if not 0 <= index < num_shards:
            raise MXNetError(f"shard index {index} out of range "
                             f"[0, {num_shards})")
        return _ShardedDataset(self, num_shards, index)

    def take(self, count):
        return _TakenDataset(self, count)

    def sample(self, sampler):
        return _SampledDataset(self, sampler)

    def transform(self, fn, lazy=True):
        trans = _LazyTransformDataset(self, fn)
        if lazy:
            return trans
        return SimpleDataset([trans[i] for i in range(len(trans))])

    def transform_first(self, fn, lazy=True):
        return self.transform(_TransformFirstClosure(fn), lazy)


class SimpleDataset(Dataset):
    """Wraps any indexable (list, array...)."""

    def __init__(self, data):
        self._data = data

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        return self._data[idx]


class _LazyTransformDataset(Dataset):
    def __init__(self, data, fn):
        self._data = data
        self._fn = fn

    def __len__(self):
        return len(self._data)

    def __getitem__(self, idx):
        item = self._data[idx]
        if isinstance(item, tuple):
            return self._fn(*item)
        return self._fn(item)


class _TransformFirstClosure:
    """Picklable closure transforming only the first element."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, x, *args):
        if args:
            return (self._fn(x),) + args
        return self._fn(x)


class _FilteredDataset(SimpleDataset):
    def __init__(self, dataset, fn):
        super().__init__([i for i in range(len(dataset)) if fn(dataset[i])])
        self._dataset = dataset

    def __getitem__(self, idx):
        return self._dataset[self._data[idx]]


class _ShardedDataset(Dataset):
    def __init__(self, dataset, num_shards, index):
        self._dataset = dataset
        self._num = num_shards
        self._index = index
        # ceil split so all shards have equal length (shorter ones wrap),
        # keeping SPMD steps in lockstep across processes
        self._len = (len(dataset) + num_shards - 1) // num_shards

    def __len__(self):
        return self._len

    def __getitem__(self, idx):
        if idx >= self._len:
            raise IndexError(idx)
        i = idx * self._num + self._index
        return self._dataset[i % len(self._dataset)]


class _TakenDataset(Dataset):
    def __init__(self, dataset, count):
        self._dataset = dataset
        self._count = min(count, len(dataset))

    def __len__(self):
        return self._count

    def __getitem__(self, idx):
        if idx >= self._count:
            raise IndexError(idx)
        return self._dataset[idx]


class _SampledDataset(Dataset):
    def __init__(self, dataset, sampler):
        self._dataset = dataset
        self._indices = list(sampler)

    def __len__(self):
        return len(self._indices)

    def __getitem__(self, idx):
        return self._dataset[self._indices[idx]]


class ArrayDataset(Dataset):
    """Zips N equal-length indexables (reference ``dataset.py:316``)."""

    def __init__(self, *args):
        assert len(args) > 0
        self._length = len(args[0])
        self._data = []
        for i, data in enumerate(args):
            assert len(data) == self._length, (
                f"All arrays must have the same length; arg {i} has "
                f"{len(data)} vs {self._length}")
            if isinstance(data, (list, tuple)):
                data = SimpleDataset(data)
            self._data.append(data)

    def __len__(self):
        return self._length

    def __getitem__(self, idx):
        if len(self._data) == 1:
            return self._data[0][idx]
        return tuple(d[idx] for d in self._data)


class RecordFileDataset(Dataset):
    """Each sample is one raw record from a RecordIO file (reference
    ``dataset.py:355`` over ``src/io/dataset.cc:117``)."""

    def __init__(self, filename):
        from ...recordio import MXIndexedRecordIO
        import os

        idx_file = os.path.splitext(filename)[0] + ".idx"
        self._record = MXIndexedRecordIO(idx_file, filename, "r")

    def __len__(self):
        return len(self._record.keys)

    def __getitem__(self, idx):
        return self._record.read_idx(self._record.keys[idx])
