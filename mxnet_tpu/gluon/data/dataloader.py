"""DataLoader (reference: ``python/mxnet/gluon/data/dataloader.py``).

The reference forks worker processes that allocate batches in POSIX shared
memory (``cpu_shared`` context, ``src/storage/cpu_shared_storage_manager.h``)
and ship NDArray FDs through a ForkingPickler. Here workers produce **numpy**
batches (host memory is where decode/augment happens either way) via
``multiprocessing.Pool``; the main process wraps them as NDArrays — the
host→TPU transfer is the same single ``device_put`` either way, and XLA
overlaps it with compute. ``pin_memory`` is accepted for API parity (no-op:
TPU transfers stage through page-locked buffers managed by the runtime).
"""
from __future__ import annotations

import multiprocessing
import pickle

import numpy as _onp

from ...base import MXNetError
from .dataset import Dataset
from .sampler import BatchSampler, RandomSampler, Sampler, SequentialSampler


def default_batchify_fn(data):
    """Stack samples into a batch (reference ``dataloader.py:145``)."""
    from ...ndarray.ndarray import NDArray

    elem = data[0]
    if isinstance(elem, NDArray):
        from ... import numpy as mnp

        return mnp.stack(data)
    if isinstance(elem, tuple):
        return tuple(default_batchify_fn(list(x)) for x in zip(*data))
    if isinstance(elem, _onp.ndarray):
        return _onp.stack(data)
    return _onp.asarray(data)


def default_mp_batchify_fn(data):
    """Worker-side batchify: keep numpy (cheap pickling)."""
    elem = data[0]
    if isinstance(elem, tuple):
        return tuple(default_mp_batchify_fn(list(x)) for x in zip(*data))
    from ...ndarray.ndarray import NDArray

    if isinstance(elem, NDArray):
        return _onp.stack([e.asnumpy() for e in data])
    return _onp.stack(data) if isinstance(elem, _onp.ndarray) \
        else _onp.asarray(data)


def _as_ndarray(batch, pin_memory=False):  # pylint: disable=unused-argument
    from ... import numpy as mnp
    from ...ndarray.ndarray import NDArray

    if isinstance(batch, tuple):
        return tuple(_as_ndarray(b) for b in batch)
    if isinstance(batch, NDArray):
        return batch
    return mnp.array(batch)


_worker_dataset = None
_worker_batchify = None


def _worker_init(dataset_bytes, batchify):
    global _worker_dataset, _worker_batchify
    _worker_dataset = pickle.loads(dataset_bytes)
    _worker_batchify = batchify


def _worker_fn(indices):
    return _worker_batchify([_worker_dataset[i] for i in indices])


class DataLoader:
    """Mini-batch loader with optional multiprocessing workers.

    Mirrors the reference API: ``batch_size``, ``shuffle``, ``sampler``,
    ``batch_sampler``, ``last_batch``, ``num_workers``, ``batchify_fn``,
    ``prefetch`` (in-flight async batches per worker pool).
    """

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0, pin_memory=False, pin_device_id=0,
                 prefetch=None, thread_pool=False, timeout=120):
        self._dataset = dataset
        self._pin_memory = pin_memory
        self._timeout = timeout
        if batch_sampler is None:
            if batch_size is None:
                raise MXNetError(
                    "batch_size is required when batch_sampler is not given")
            if sampler is None:
                sampler = (RandomSampler(len(dataset)) if shuffle
                           else SequentialSampler(len(dataset)))
            elif shuffle:
                raise MXNetError("shuffle must be False with explicit sampler")
            batch_sampler = BatchSampler(sampler, batch_size,
                                         last_batch or "keep")
        elif (batch_size is not None or shuffle or sampler is not None
              or last_batch is not None):
            raise MXNetError(
                "batch_size/shuffle/sampler/last_batch are mutually "
                "exclusive with batch_sampler")
        self._batch_sampler = batch_sampler
        self._epoch_count = 0
        self._batches_served = 0
        self._resume = None
        self._num_workers = max(0, num_workers)
        self._thread_pool = thread_pool
        self._prefetch = max(0, prefetch if prefetch is not None
                             else 2 * self._num_workers)
        if batchify_fn is None:
            self._batchify_fn = (default_mp_batchify_fn if self._num_workers
                                 else default_batchify_fn)
        else:
            self._batchify_fn = batchify_fn
        self._pool = None
        if self._num_workers > 0:
            if thread_pool:
                from multiprocessing.pool import ThreadPool

                self._pool = ThreadPool(self._num_workers)
            else:
                # forkserver, not fork: the parent has JAX's thread pool
                # running and forking a multithreaded process can deadlock a
                # worker; the forkserver process is clean, and the dataset
                # ships via pickle either way (the reference instead forks +
                # relies on pthread_atfork handlers, src/initialize.cc:73-83)
                ctx = multiprocessing.get_context("forkserver")
                self._pool = ctx.Pool(
                    self._num_workers, initializer=_worker_init,
                    initargs=(pickle.dumps(dataset), self._batchify_fn))

    def __len__(self):
        return len(self._batch_sampler)

    def state_dict(self):
        """Resumable position: epoch count, batches served this epoch,
        and the batch sampler's own state (permutation RNG anchor,
        rollover tail). Checkpoint this between batches and a fresh
        DataLoader over the same dataset resumes on the exact next batch
        of the SAME shuffled order — no replayed or skipped samples."""
        sd = getattr(self._batch_sampler, "state_dict", None)
        return {"type": "DataLoader", "epoch": int(self._epoch_count),
                "batches": int(self._batches_served),
                "sampler": sd() if sd is not None else None}

    def load_state_dict(self, state):
        """Arm resumption: the NEXT ``__iter__`` restores the sampler
        state (redrawing the interrupted epoch's permutation) and skips
        the already-served batches by consuming their sampler indices —
        skipped batches are never materialized or dispatched to workers."""
        if state.get("type") != "DataLoader":
            raise MXNetError(
                f"DataLoader.load_state_dict: state is for "
                f"{state.get('type')!r}, not DataLoader")
        self._epoch_count = int(state.get("epoch", 0))
        self._resume = dict(state)

    def _begin_epoch(self):
        """Skip count for this epoch: non-zero only on the first epoch
        after :meth:`load_state_dict`."""
        if self._resume is None:
            self._batches_served = 0
            return 0
        state, self._resume = self._resume, None
        if state.get("sampler") is not None \
                and hasattr(self._batch_sampler, "load_state_dict"):
            self._batch_sampler.load_state_dict(state["sampler"])
        skip = max(0, int(state.get("batches", 0)))
        self._batches_served = skip
        return skip

    def __iter__(self):
        skip = self._begin_epoch()
        if self._pool is None:
            for indices in self._batch_sampler:
                if skip > 0:
                    skip -= 1
                    continue
                batch = self._batchify_fn(
                    [self._dataset[i] for i in indices])
                self._batches_served += 1
                yield _as_ndarray(batch, self._pin_memory)
            self._epoch_count += 1
            return

        # async map with bounded in-flight queue (reference prefetch depth)
        import collections

        if self._thread_pool:
            # thread workers share the process: close over this loader's own
            # dataset/batchify rather than the forkserver globals so two
            # thread-pool loaders never clobber each other
            dataset, batchify = self._dataset, self._batchify_fn

            def work(indices):
                return batchify([dataset[i] for i in indices])
        else:
            work = _worker_fn

        inflight = collections.deque()
        it = iter(self._batch_sampler)
        # resume skip: consume the already-served batches' indices before
        # anything is dispatched — skipped batches cost no worker time
        for _ in range(skip):
            if next(it, None) is None:
                break
        try:
            for _ in range(self._prefetch or 1):
                indices = next(it, None)
                if indices is None:
                    break
                inflight.append(self._pool.apply_async(work, (indices,)))
            while inflight:
                res = inflight.popleft()
                batch = res.get(self._timeout)
                indices = next(it, None)
                if indices is not None:
                    inflight.append(self._pool.apply_async(work, (indices,)))
                self._batches_served += 1
                yield _as_ndarray(batch, self._pin_memory)
            self._epoch_count += 1
        except multiprocessing.TimeoutError:
            raise MXNetError(
                f"DataLoader worker timed out after {self._timeout}s; "
                "raise timeout= or reduce transform cost") from None

    def __del__(self):
        if self._pool is not None:
            self._pool.terminate()
