"""Gluon data API (reference: ``python/mxnet/gluon/data/``)."""
from . import vision
from .dataloader import DataLoader, default_batchify_fn, default_mp_batchify_fn
from .dataset import (
    ArrayDataset,
    Dataset,
    RecordFileDataset,
    SimpleDataset,
)
from .sampler import (
    BatchSampler,
    FilterSampler,
    IntervalSampler,
    RandomSampler,
    Sampler,
    SequentialSampler,
)
