"""Composable batchify functions for DataLoader (reference
``python/mxnet/gluon/data/batchify.py``): ``Stack`` (dense stacking),
``Pad`` (ragged samples padded to the longest then stacked), ``Append``
(no batching — each sample kept, optionally expanded), ``Group`` (one
function per tuple element), ``AsList`` (passthrough nesting).

TPU note: padding happens host-side with numpy (one device transfer for
the final batch) — the reference issues the same warning when handed
device NDArrays sample-by-sample.
"""
from __future__ import annotations

import warnings

import numpy as _onp

from ...ndarray.ndarray import NDArray

__all__ = ["Stack", "Pad", "Append", "Group", "AsList"]


def _to_host(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _onp.asarray(x)


class Stack:
    """Stack samples along a new batch axis (reference batchify.Stack)."""

    def __call__(self, data):
        return NDArray(_onp.stack([_to_host(d) for d in data]))

    def __repr__(self):
        return "Stack()"


class Pad:
    """Pad ragged samples to the longest along each axis with ``val``,
    then stack; ``round_to`` rounds the padded length up to a multiple
    (static-shape friendliness — one compiled bucket per rounded length
    instead of one per raw length)."""

    def __init__(self, val=None, dtype=None, round_to=None,
                 use_shared_mem=False):  # pylint: disable=unused-argument
        self._pad_val = 0 if val is None else val
        self._dtype = dtype
        self._round_to = round_to
        self._warned = False

    def __call__(self, data):
        if isinstance(data[0], NDArray) and not self._warned:
            self._warned = True
            warnings.warn(
                "Using Pad with NDArrays is discouraged for speed reasons. "
                "Pad while the data is still a list/numpy array.")
        if not isinstance(data[0], (NDArray, _onp.ndarray, list)):
            raise NotImplementedError(
                "Pad() does not support multiple items, use "
                "Group(Pad(), Pad(), ...) instead")
        arrs = [_to_host(d) for d in data]
        dims = max(a.ndim for a in arrs)
        arrs = [a.reshape(a.shape + (1,) * (dims - a.ndim)) for a in arrs]
        max_shape = [max(a.shape[i] for a in arrs) for i in range(dims)]
        if self._round_to is not None:
            max_shape = [-(-s // self._round_to) * self._round_to
                         for s in max_shape]
        dtype = self._dtype or arrs[0].dtype
        out = _onp.full((len(arrs),) + tuple(max_shape), self._pad_val,
                        dtype=dtype)
        for i, a in enumerate(arrs):
            out[(i,) + tuple(slice(0, s) for s in a.shape)] = a
        return NDArray(out)

    def __repr__(self):
        return f"Pad(val={self._pad_val})"


class Append:
    """Keep samples as a list of arrays (no stacking); ``expand`` adds a
    leading batch axis of 1 to each (reference batchify.Append)."""

    def __init__(self, expand=True, batch_axis=0, use_shared_mem=False):  # pylint: disable=unused-argument
        self._expand = expand
        self._batch_axis = batch_axis

    def __call__(self, data):
        out = []
        for d in data:
            h = _to_host(d)
            if self._expand:
                h = _onp.expand_dims(h, self._batch_axis)
            out.append(NDArray(h))
        return out

    def __repr__(self):
        return "Append()"


class Group:
    """Apply one batchify function per element of the sample tuple
    (reference batchify.Group: ``Group(Stack(), Pad())`` for
    (data, ragged-label) pairs)."""

    def __init__(self, *fn):
        if len(fn) == 1 and isinstance(fn[0], (list, tuple)):
            fn = tuple(fn[0])
        self._fn = fn

    def __call__(self, data):
        if len(data[0]) != len(self._fn):
            raise ValueError(
                f"the number of attributes in each data sample should "
                f"contain {len(self._fn)} elements, got {len(data[0])}")
        return tuple(f(list(items))
                     for f, items in zip(self._fn, zip(*data)))

    def __repr__(self):
        return f"Group({', '.join(repr(f) for f in self._fn)})"


class AsList:
    """Return the unchanged list of samples (reference batchify.AsList,
    for string fields and other non-tensor payloads)."""

    def __call__(self, data):
        return list(data)

    def __repr__(self):
        return "AsList()"
