"""Vision transforms (reference:
``python/mxnet/gluon/data/vision/transforms.py`` over the C++ kernels in
``src/operator/image/``).

Transforms run on the host (numpy) inside DataLoader workers — the
reference's image kernels are CPU-side too; TPU time is spent on the model,
not the augmentation. Inputs/outputs are HWC uint8/float numpy arrays or
NDArrays; ``ToTensor`` produces CHW float32 in [0, 1].
"""
from __future__ import annotations

import numbers

import numpy as _onp

from ....base import MXNetError


def _to_numpy(x):
    from ....ndarray.ndarray import NDArray

    if isinstance(x, NDArray):
        return x.asnumpy()
    return _onp.asarray(x)


class Compose:
    """Chain transforms (reference ``transforms.py:51``)."""

    def __init__(self, transforms):
        self._transforms = list(transforms)

    def __call__(self, x, *args):
        for t in self._transforms:
            x = t(x)
        if args:
            return (x,) + args
        return x


class Cast:
    def __init__(self, dtype="float32"):
        self._dtype = dtype

    def __call__(self, x):
        return _to_numpy(x).astype(self._dtype)


class ToTensor:
    """HWC uint8 [0,255] -> CHW float32 [0,1] (reference
    ``transforms.py:91``)."""

    def __call__(self, x):
        x = _to_numpy(x)
        if x.ndim == 2:
            x = x[..., None]
        x = x.astype(_onp.float32) / 255.0
        if x.ndim == 3:
            return x.transpose(2, 0, 1)
        return x.transpose(0, 3, 1, 2)


class Normalize:
    """(x - mean) / std per channel on CHW float input (reference
    ``transforms.py:126``)."""

    def __init__(self, mean=0.0, std=1.0):
        self._mean = _onp.asarray(mean, dtype=_onp.float32)
        self._std = _onp.asarray(std, dtype=_onp.float32)

    def __call__(self, x):
        x = _to_numpy(x).astype(_onp.float32)
        mean = self._mean.reshape(-1, 1, 1)
        std = self._std.reshape(-1, 1, 1)
        return (x - mean) / std


def _resize_img(x, size, interpolation):
    from PIL import Image

    if isinstance(size, numbers.Number):
        h, w = x.shape[:2]
        if h < w:
            size = (int(size * w / h), int(size))
        else:
            size = (int(size), int(size * h / w))
    # PIL wants (W, H)
    squeeze = x.shape[-1] == 1
    img = Image.fromarray(x.squeeze(-1) if squeeze else x)
    resample = {0: Image.NEAREST, 1: Image.BILINEAR, 2: Image.BICUBIC,
                3: Image.LANCZOS}.get(interpolation, Image.BILINEAR)
    out = _onp.asarray(img.resize(tuple(size), resample))
    if squeeze:
        out = out[..., None]
    return out


class Resize:
    """Resize to (w, h) or shorter-side int (reference
    ``transforms.py:225``)."""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        if isinstance(size, numbers.Number) and not keep_ratio:
            size = (int(size), int(size))  # reference: int + keep_ratio=False
        self._size = size                  # means a square output
        self._interp = interpolation

    def __call__(self, x):
        return _resize_img(_to_numpy(x), self._size, self._interp)


class CenterCrop:
    def __init__(self, size, interpolation=1):
        self._size = ((size, size) if isinstance(size, numbers.Number)
                      else tuple(size))
        self._interp = interpolation

    def __call__(self, x):
        x = _to_numpy(x)
        w_t, h_t = self._size
        h, w = x.shape[:2]
        if h < h_t or w < w_t:
            x = _resize_img(x, (max(w, w_t), max(h, h_t)), self._interp)
            h, w = x.shape[:2]
        y0 = (h - h_t) // 2
        x0 = (w - w_t) // 2
        return x[y0:y0 + h_t, x0:x0 + w_t]


class RandomResizedCrop:
    """Random area/aspect crop resized to target (reference
    ``transforms.py:398``)."""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation=1):
        self._size = ((size, size) if isinstance(size, numbers.Number)
                      else tuple(size))
        self._scale = scale
        self._ratio = ratio
        self._interp = interpolation

    def __call__(self, x):
        x = _to_numpy(x)
        h, w = x.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = _onp.random.uniform(*self._scale) * area
            aspect = _onp.random.uniform(*self._ratio)
            w_c = int(round((target_area * aspect) ** 0.5))
            h_c = int(round((target_area / aspect) ** 0.5))
            if w_c <= w and h_c <= h:
                x0 = _onp.random.randint(0, w - w_c + 1)
                y0 = _onp.random.randint(0, h - h_c + 1)
                crop = x[y0:y0 + h_c, x0:x0 + w_c]
                return _resize_img(crop, self._size, self._interp)
        return CenterCrop(self._size, self._interp)(x)


class RandomCrop:
    def __init__(self, size, pad=None, interpolation=1):
        self._size = ((size, size) if isinstance(size, numbers.Number)
                      else tuple(size))
        self._pad = pad
        self._interp = interpolation

    def __call__(self, x):
        x = _to_numpy(x)
        if self._pad:
            p = self._pad
            x = _onp.pad(x, ((p, p), (p, p), (0, 0)), mode="constant")
        w_t, h_t = self._size
        h, w = x.shape[:2]
        if h < h_t or w < w_t:
            x = _resize_img(x, (max(w, w_t), max(h, h_t)), self._interp)
            h, w = x.shape[:2]
        y0 = _onp.random.randint(0, h - h_t + 1)
        x0 = _onp.random.randint(0, w - w_t + 1)
        return x[y0:y0 + h_t, x0:x0 + w_t]


class RandomFlipLeftRight:
    def __call__(self, x):
        x = _to_numpy(x)
        if _onp.random.rand() < 0.5:
            x = x[:, ::-1]
        return x


class RandomFlipTopBottom:
    def __call__(self, x):
        x = _to_numpy(x)
        if _onp.random.rand() < 0.5:
            x = x[::-1]
        return x


def _blend(a, b, alpha):
    return (alpha * a.astype(_onp.float32)
            + (1 - alpha) * b.astype(_onp.float32))


class RandomBrightness:
    def __init__(self, brightness):
        self._b = brightness

    def __call__(self, x):
        x = _to_numpy(x).astype(_onp.float32)
        alpha = 1.0 + _onp.random.uniform(-self._b, self._b)
        return x * alpha


class RandomContrast:
    def __init__(self, contrast):
        self._c = contrast

    def __call__(self, x):
        x = _to_numpy(x).astype(_onp.float32)
        alpha = 1.0 + _onp.random.uniform(-self._c, self._c)
        gray = x.mean()
        return _blend(x, _onp.full_like(x, gray), alpha)


class RandomSaturation:
    def __init__(self, saturation):
        self._s = saturation

    def __call__(self, x):
        x = _to_numpy(x).astype(_onp.float32)
        alpha = 1.0 + _onp.random.uniform(-self._s, self._s)
        gray = x.mean(axis=-1, keepdims=True)
        return _blend(x, _onp.broadcast_to(gray, x.shape), alpha)


class RandomHue:
    def __init__(self, hue):
        self._h = hue

    def __call__(self, x):
        x = _to_numpy(x).astype(_onp.float32)
        alpha = _onp.random.uniform(-self._h, self._h)
        # approximate hue rotation via the YIQ rotation matrix
        u = _onp.cos(alpha * _onp.pi)
        w = _onp.sin(alpha * _onp.pi)
        t_yiq = _onp.array([[0.299, 0.587, 0.114],
                            [0.596, -0.274, -0.321],
                            [0.211, -0.523, 0.311]], dtype=_onp.float32)
        t_rgb = _onp.linalg.inv(t_yiq)
        rot = _onp.array([[1, 0, 0], [0, u, -w], [0, w, u]],
                         dtype=_onp.float32)
        m = t_rgb @ rot @ t_yiq
        return x @ m.T


class RandomColorJitter:
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self._ts = []
        if brightness:
            self._ts.append(RandomBrightness(brightness))
        if contrast:
            self._ts.append(RandomContrast(contrast))
        if saturation:
            self._ts.append(RandomSaturation(saturation))
        if hue:
            self._ts.append(RandomHue(hue))

    def __call__(self, x):
        order = _onp.random.permutation(len(self._ts))
        for i in order:
            x = self._ts[i](x)
        return x


class RandomLighting:
    """AlexNet-style PCA noise (reference ``transforms.py:820``)."""

    _eigval = _onp.array([55.46, 4.794, 1.148], dtype=_onp.float32)
    _eigvec = _onp.array([[-0.5675, 0.7192, 0.4009],
                          [-0.5808, -0.0045, -0.8140],
                          [-0.5836, -0.6948, 0.4203]], dtype=_onp.float32)

    def __init__(self, alpha):
        self._alpha = alpha

    def __call__(self, x):
        x = _to_numpy(x).astype(_onp.float32)
        alpha = _onp.random.normal(0, self._alpha, 3).astype(_onp.float32)
        rgb = (self._eigvec * alpha * self._eigval).sum(axis=1)
        return x + rgb


class RandomGray:
    def __init__(self, p=0.5):
        self._p = p

    def __call__(self, x):
        x = _to_numpy(x)
        if _onp.random.rand() < self._p:
            gray = (_to_numpy(x).astype(_onp.float32)
                    @ _onp.array([0.299, 0.587, 0.114], dtype=_onp.float32))
            x = _onp.repeat(gray[..., None], 3, axis=-1)
        return x


class CropResize:
    """Crop a fixed region then optionally resize (reference
    ``transforms.CropResize``): x[y:y+h, x:x+w] -> (size)."""

    def __init__(self, x, y, width, height, size=None, interpolation=1):
        self._x0 = x
        self._y0 = y
        self._w = width
        self._h = height
        self._size = ((size, size) if isinstance(size, numbers.Number)
                      else tuple(size)) if size is not None else None
        self._interp = interpolation

    def __call__(self, x):
        img = _to_numpy(x)
        h, w = img.shape[:2]
        if (self._x0 < 0 or self._y0 < 0 or self._w <= 0 or self._h <= 0
                or self._x0 + self._w > w or self._y0 + self._h > h):
            # reference errors on invalid regions — silent clamping would
            # hand back wrong content at the right shape
            raise MXNetError(
                f"CropResize region (x={self._x0}, y={self._y0}, "
                f"w={self._w}, h={self._h}) out of bounds for a "
                f"{w}x{h} image")
        crop = img[self._y0:self._y0 + self._h,
                   self._x0:self._x0 + self._w]
        if self._size is not None:
            crop = _resize_img(crop, self._size, self._interp)
        return crop


class RandomRotation:
    """Random rotation within ``angle_limits`` degrees (reference
    ``transforms/image.py:174`` RandomRotation over ``image.imrotate``).

    NOTE the reference's own layout asymmetry, kept here: unlike the rest
    of this module (HWC uint8/float), RandomRotation is a POST-ToTensor
    transform taking float32 **(C, H, W)** (or (N, C, H, W)) — compose it
    after ``ToTensor``."""

    def __init__(self, angle_limits, zoom_in=False, zoom_out=False,
                 rotate_with_proba=1.0):
        lo, hi = angle_limits
        if lo >= hi:
            raise MXNetError("angle_limits must be (low, high) with low<high")
        self._limits = (lo, hi)
        self._zoom_in = zoom_in
        self._zoom_out = zoom_out
        self._p = rotate_with_proba

    def __call__(self, x):
        from ....image import imrotate

        img = _to_numpy(x)
        if img.dtype != _onp.float32:
            raise MXNetError(
                "RandomRotation only supports float32 (C, H, W) inputs — "
                "compose it after ToTensor (reference contract)")
        if _onp.random.rand() > self._p:
            return img
        deg = float(_onp.random.uniform(*self._limits))
        return _to_numpy(imrotate(img, deg, zoom_in=self._zoom_in,
                                  zoom_out=self._zoom_out))
