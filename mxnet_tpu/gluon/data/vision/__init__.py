"""Vision data (reference: ``python/mxnet/gluon/data/vision/``)."""
from . import transforms
from .datasets import (
    CIFAR10,
    CIFAR100,
    FashionMNIST,
    ImageFolderDataset,
    ImageRecordDataset,
    MNIST,
)
