"""Vision datasets (reference:
``python/mxnet/gluon/data/vision/datasets.py``).

Same file formats as the reference (MNIST idx / CIFAR binary batches /
RecordIO packs / image folders) read from a local ``root`` — there is no
download path in this environment (zero egress); point ``root`` at existing
data or use ``ArrayDataset`` with synthetic arrays.
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct

import numpy as _onp

from ....base import MXNetError
from ..dataset import ArrayDataset, Dataset, RecordFileDataset


def _require(path, what):
    if not os.path.exists(path):
        raise MXNetError(
            f"{what} not found at {path!r}. Downloads are disabled in this "
            "build; place the files there manually.")
    return path


def _read_idx(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        data = f.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    if zeros != 0:
        raise MXNetError(f"bad idx magic in {path}")
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    dtype = {8: _onp.uint8, 9: _onp.int8, 11: _onp.int16, 12: _onp.int32,
             13: _onp.float32, 14: _onp.float64}[dtype_code]
    return _onp.frombuffer(data[4 + 4 * ndim:],
                           dtype=dtype).reshape(dims)


class MNIST(ArrayDataset):
    """MNIST from idx files (reference ``datasets.py:37``); samples are
    (HWC uint8 image, int32 label)."""

    _files = {
        True: ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
        False: ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
    }

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "mnist"),
                 train=True, transform=None):
        root = os.path.expanduser(root)
        imgf, lblf = self._files[train]
        for cand in (imgf, imgf + ".gz"):
            p = os.path.join(root, cand)
            if os.path.exists(p):
                imgf = p
                break
        else:
            _require(os.path.join(root, imgf), type(self).__name__)
        for cand in (lblf, lblf + ".gz"):
            p = os.path.join(root, cand)
            if os.path.exists(p):
                lblf = p
                break
        else:
            _require(os.path.join(root, lblf), type(self).__name__)
        data = _read_idx(imgf)[..., None]  # HWC (C=1)
        labels = _read_idx(lblf).astype(_onp.int32)
        self._transform = transform
        super().__init__(data, labels)

    def __getitem__(self, idx):
        img, lbl = self._data[0][idx], self._data[1][idx]
        if self._transform is not None:
            return self._transform(img, lbl)
        return img, lbl


class FashionMNIST(MNIST):
    """Fashion-MNIST (same idx format, reference ``datasets.py:113``)."""

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "fashion-mnist"), train=True,
                 transform=None):
        super().__init__(root=root, train=train, transform=transform)


class CIFAR10(ArrayDataset):
    """CIFAR-10 from the python pickle batches (reference
    ``datasets.py:141``); samples are (HWC uint8, int32)."""

    _train_batches = [f"data_batch_{i}" for i in range(1, 6)]
    _test_batches = ["test_batch"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets", "cifar10"),
                 train=True, transform=None):
        root = os.path.expanduser(root)
        sub = os.path.join(root, "cifar-10-batches-py")
        base = sub if os.path.isdir(sub) else root
        batches = self._train_batches if train else self._test_batches
        fine = getattr(self, "_fine", True)
        label_keys = [b"labels", b"fine_labels" if fine else b"coarse_labels"]
        imgs, lbls = [], []
        for b in batches:
            with open(_require(os.path.join(base, b), "CIFAR batch"),
                      "rb") as f:
                d = pickle.load(f, encoding="bytes")
            imgs.append(d[b"data"])
            for k in label_keys:
                if k in d:
                    lbls.extend(d[k])
                    break
        data = (_onp.concatenate(imgs).reshape(-1, 3, 32, 32)
                .transpose(0, 2, 3, 1))
        labels = _onp.asarray(lbls, dtype=_onp.int32)
        self._transform = transform
        super().__init__(data, labels)

    def __getitem__(self, idx):
        img, lbl = self._data[0][idx], self._data[1][idx]
        if self._transform is not None:
            return self._transform(img, lbl)
        return img, lbl


class CIFAR100(CIFAR10):
    _train_batches = ["train"]
    _test_batches = ["test"]

    def __init__(self, root=os.path.join("~", ".mxnet", "datasets",
                                         "cifar100"), fine_label=True,
                 train=True, transform=None):
        self._fine = fine_label
        super().__init__(root=root, train=train, transform=transform)


class ImageRecordDataset(RecordFileDataset):
    """Images packed in a RecordIO file (reference ``datasets.py:183``)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from ....recordio import unpack_img

        record = super().__getitem__(idx)
        header, img = unpack_img(record, iscolor=self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(Dataset):
    """``root/class_x/*.jpg`` layout (reference ``datasets.py:223``)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = (".jpg", ".jpeg", ".png", ".bmp")
        self.synsets = []
        self.items = []
        _require(self._root, "image folder")
        for folder in sorted(os.listdir(self._root)):
            path = os.path.join(self._root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for fname in sorted(os.listdir(path)):
                if fname.lower().endswith(self._exts):
                    self.items.append((os.path.join(path, fname), label))

    def __len__(self):
        return len(self.items)

    def __getitem__(self, idx):
        from PIL import Image

        path, label = self.items[idx]
        img = Image.open(path)
        img = img.convert("RGB" if self._flag else "L")
        arr = _onp.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        if self._transform is not None:
            return self._transform(arr, label)
        return arr, label
