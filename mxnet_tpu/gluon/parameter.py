"""Gluon Parameter / ParameterDict.

Reference: ``python/mxnet/gluon/parameter.py`` (759 LoC: deferred init,
``grad_req``, per-context replicas, ``row_sparse`` params).

TPU redesign notes:
  * a Parameter's payload is one NDArray per Context — but on TPU the
    multi-device story is a *single sharded* ``jax.Array`` over a mesh
    (SURVEY.md §2.3), so multi-context replica lists exist for API parity
    (``list_data``) while ``shard_spec`` + ``mxnet_tpu.parallel`` provide the
    native path.
  * gradients attach through the autograd tape (``mark_variables``), exactly
    the reference contract (``Parameter._init_grad`` →
    ``autograd.mark_variables``, reference ``parameter.py``).
"""
from __future__ import annotations

import threading as _threading
from collections import OrderedDict

import numpy as _onp

from .. import autograd, initializer as _init_mod
from ..base import MXNetError
from ..device import Context, cpu, current_context
from ..ndarray.ndarray import NDArray


class DeferredInitializationError(MXNetError):
    """Parameter accessed before its shape is fully known."""


_REPLICA = _threading.local()


class replica_context:
    """``with replica_context(ctx):`` — within the scope, ``p.data()`` /
    ``p.grad()`` with no explicit context resolve to the replica on
    ``ctx`` (when the parameter has one) instead of the first replica.

    This is the reference's per-device forward convention (classic gluon
    blocks call ``param.data(x.context)``) expressed as a scope, so every
    existing ``p.data()`` call site — Dense/Conv forwards, the v1
    ``hybrid_forward`` binding — becomes replica-aware without threading
    a context argument through each one. The elastic data-parallel batch
    processor (``resilience.elastic``) wraps each per-replica
    forward/backward in one. Zero cost outside a scope beyond a
    thread-local attribute probe; parameters without a replica on ``ctx``
    fall back to their first replica unchanged."""

    def __init__(self, ctx):
        self._ctx = ctx
        self._prev = None

    def __enter__(self):
        self._prev = getattr(_REPLICA, "ctx", None)
        _REPLICA.ctx = self._ctx
        return self._ctx

    def __exit__(self, *exc):
        _REPLICA.ctx = self._prev
        return False


def _active_replica_ctx():
    return getattr(_REPLICA, "ctx", None)


def _shape_complete(shape):
    return shape is not None and all(isinstance(s, int) and s > 0 for s in shape)


class Parameter:
    """A weight/state tensor of a Block."""

    def __init__(self, name="param", grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=True,
                 differentiable=True, stype="default", grad_stype="default"):
        self._name = name
        self._shape = tuple(shape) if shape is not None else None
        self.dtype = _onp.dtype(dtype) if dtype is not None else None
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.init = init
        self.allow_deferred_init = allow_deferred_init
        self._differentiable = differentiable
        if not differentiable:
            grad_req = "null"
        self._grad_req = grad_req
        self._stype = stype
        self._grad_stype = grad_stype
        self._ctx_list = None
        self._data = None  # OrderedDict[Context, NDArray]
        self._grad = None  # OrderedDict[Context, NDArray]
        self._deferred_init = None  # (init, ctx_list, default_init)
        self.shard_spec = None  # optional jax PartitionSpec for mesh sharding
        self._structure = None  # (block, attr-name) backref set by Block

    # -- identity ---------------------------------------------------------
    @property
    def name(self):
        return self._name

    def __repr__(self):
        return f"Parameter {self._name} (shape={self._shape}, dtype={self.dtype})"

    # -- shape ------------------------------------------------------------
    @property
    def shape(self):
        return self._shape

    @shape.setter
    def shape(self, new_shape):
        if new_shape is None:
            return
        if self._shape is not None:
            if len(self._shape) != len(new_shape):
                raise MXNetError(
                    f"{self._name}: cannot change ndim {self._shape}->{new_shape}")
            merged = []
            for old, new in zip(self._shape, new_shape):
                if old and old > 0 and new and new > 0 and old != new:
                    raise MXNetError(
                        f"{self._name}: inconsistent shape {self._shape} vs {new_shape}")
                merged.append(old if (old and old > 0) else new)
            self._shape = tuple(merged)
        else:
            self._shape = tuple(new_shape)
        if _shape_complete(self._shape) and self._deferred_init is not None:
            self._finish_deferred_init()

    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise MXNetError(f"invalid grad_req {req!r}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data:
                for arr in self._data.values():
                    arr._leaf = None
        elif self._data is not None:
            self._init_grad()

    # -- initialization ---------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=None, force_reinit=False):
        if getattr(self, "_abstract_placeholder", False):
            # placeholder installed by functionalize_abstract (compile-only
            # proofs): silently "already initialized" would leave 0-element
            # weights in play — a real init must be explicit
            if not force_reinit:
                raise MXNetError(
                    f"Parameter {self._name} holds an abstract (compile-only)"
                    " placeholder from functionalize_abstract; pass "
                    "force_reinit=True to materialize real weights")
            self._abstract_placeholder = False
            self._data = None
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        self._ctx_list = list(ctx)
        if init is None:
            init = self.init if self.init is not None else (default_init or _init_mod.Uniform())
        if not _shape_complete(self._shape):
            if not self.allow_deferred_init:
                raise MXNetError(
                    f"{self._name}: shape {self._shape} incomplete and deferred "
                    "init not allowed")
            self._deferred_init = (init, list(ctx))
            return
        self._init_impl(init, ctx)

    def _init_impl(self, init, ctx_list):
        import jax

        initializer = _init_mod.create(init) if not isinstance(init, _init_mod.Initializer) else init
        # materialize once on host-side default device, then replicate
        proto = NDArray(_onp.zeros(self._shape, self.dtype))
        initializer(self._name, proto)
        self._data = OrderedDict()
        for ctx in ctx_list:
            data = jax.device_put(proto._data, ctx.jax_device())
            self._data[ctx] = NDArray(data)
        self._deferred_init = None
        if self._grad_req != "null":
            self._init_grad()

    def _finish_deferred_init(self):
        if self._deferred_init is None:
            return
        init, ctx_list = self._deferred_init
        self._init_impl(init, ctx_list)

    def _init_grad(self):
        import jax.numpy as jnp

        self._grad = OrderedDict()
        for ctx, data in self._data.items():
            import jax

            if self._grad_stype == "row_sparse":
                # O(nnz) gradient buffer: starts with zero stored rows;
                # each backward adopts the produced (indices, values)
                # without ever materializing the (vocab, dim) dense grad
                from ..ndarray.sparse import RowSparseNDArray

                g = RowSparseNDArray(
                    NDArray(jnp.zeros((0,) + data.shape[1:], data.dtype)),
                    NDArray(jnp.zeros((0,), jnp.int64)), data.shape)
            else:
                g = NDArray(jax.device_put(jnp.zeros(data.shape, data.dtype),
                                           ctx.jax_device()))
            self._grad[ctx] = g
            autograd.mark_variables([data], [g], self._grad_req)

    # -- access -----------------------------------------------------------
    def _check_initialized(self, ctx=None):
        if self._data is None:
            if self._deferred_init is not None:
                raise DeferredInitializationError(
                    f"Parameter {self._name} has not been initialized yet: "
                    "shape is incomplete (deferred init pending first forward)")
            raise MXNetError(
                f"Parameter {self._name} has not been initialized. "
                "Call .initialize() on the Block first")
        if ctx is not None and ctx not in self._data:
            raise MXNetError(
                f"Parameter {self._name} was not initialized on {ctx}; "
                f"it lives on {list(self._data)}")

    def data(self, ctx=None):
        if getattr(self, "_abstract_placeholder", False):
            from ..cachedop import in_trace

            # inside a functionalized trace the slot is rebound to the
            # trace's tracer (that is its whole job); anywhere else the
            # 0-element placeholder must not masquerade as weights
            if not in_trace():
                raise MXNetError(
                    f"Parameter {self._name} belongs to an abstract "
                    "(compile-only) functionalization and has no real "
                    "data; re-initialize with force_reinit=True to train")
        self._check_initialized(ctx)
        if ctx is None:
            act = _active_replica_ctx()
            if act is not None and act in self._data:
                return self._data[act]
            return next(iter(self._data.values()))
        return self._data[ctx]

    def list_data(self):
        self._check_initialized()
        return list(self._data.values())

    def grad(self, ctx=None):
        if self._grad is None:
            raise MXNetError(
                f"Parameter {self._name} has no gradient (grad_req={self._grad_req!r})")
        if ctx is None:
            act = _active_replica_ctx()
            if act is not None and act in self._grad:
                return self._grad[act]
            return next(iter(self._grad.values()))
        return self._grad[ctx]

    def list_grad(self):
        if self._grad is None:
            raise MXNetError(f"Parameter {self._name} has no gradient")
        return list(self._grad.values())

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init is not None:
                return self._deferred_init[1]
            raise MXNetError(f"Parameter {self._name} not initialized")
        return list(self._data)

    def set_data(self, data):
        """Overwrite the value on every context (reference ``set_data``)."""
        import jax

        # real data cures an abstract (compile-only) placeholder
        self._abstract_placeholder = False
        self.shape = tuple(data.shape)
        if self._data is None:
            if self._deferred_init is not None:
                self._finish_deferred_init()
            else:
                self._ctx_list = [current_context()]
                self._data = OrderedDict({self._ctx_list[0]: NDArray(_onp.zeros(data.shape, self.dtype))})
                if self._grad_req != "null":
                    self._init_grad()
        src = data._data if isinstance(data, NDArray) else None
        for ctx, arr in self._data.items():
            val = src if src is not None else _onp.asarray(data)
            arr._set_data_internal(
                jax.device_put(val.astype(arr.dtype) if val.dtype != arr.dtype else val,
                               ctx.jax_device()),
                keep_tape=False)

    def zero_grad(self):
        if self._grad is None:
            return
        import jax.numpy as jnp

        from ..ndarray.sparse import RowSparseNDArray

        for g in self._grad.values():
            if isinstance(g, RowSparseNDArray):
                # reset to zero stored rows — never a (vocab, dim) dense
                g._set_sparse(RowSparseNDArray(
                    NDArray(jnp.zeros((0,) + g.shape[1:], g.dtype)),
                    NDArray(jnp.zeros((0,), jnp.int64)), g.shape))
            else:
                g._set_data_internal(jnp.zeros(g.shape, g.dtype))

    def reset_ctx(self, ctx):
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            import jax

            proto = next(iter(self._data.values()))
            self._data = OrderedDict(
                (c, NDArray(jax.device_put(proto._data, c.jax_device()))) for c in ctx)
            self._ctx_list = list(ctx)
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init is not None:
            init, _ = self._deferred_init
            self._deferred_init = (init, list(ctx))

    def cast(self, dtype):
        self.dtype = _onp.dtype(dtype)
        if self._data is None:
            return
        for arr in self._data.values():
            arr._set_data_internal(arr._data.astype(dtype))
        if self._grad is not None:
            for ctx, g in self._grad.items():
                g._set_data_internal(g._data.astype(dtype))
                autograd.mark_variables([self._data[ctx]], [g], self._grad_req)

    # row_sparse API parity ------------------------------------------------
    def row_sparse_data(self, row_id):
        if self._stype != "row_sparse":
            raise MXNetError(f"Parameter {self._name} is not row_sparse")
        return self.data().tostype("row_sparse").retain(row_id)

    def var(self):  # legacy symbol API surface
        from ..symbol import var

        return var(self._name, shape=self._shape, dtype=self.dtype)


class Constant(Parameter):
    """Non-differentiable constant parameter (reference ``gluon.Constant``)."""

    def __init__(self, value, name="const"):
        if not isinstance(value, NDArray):
            value = NDArray(_onp.asarray(value))
        super().__init__(name=name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_init_mod.Constant(value),
                         differentiable=False)
        self._value = value


class ParameterDict(OrderedDict):
    """Dict of name->Parameter with batched ops (reference ParameterDict)."""

    def initialize(self, init=None, ctx=None, verbose=False, force_reinit=False):  # pylint: disable=unused-argument
        for p in self.values():
            p.initialize(init=None, ctx=ctx, default_init=init, force_reinit=force_reinit)

    def zero_grad(self):
        for p in self.values():
            p.zero_grad()

    def reset_ctx(self, ctx):
        for p in self.values():
            p.reset_ctx(ctx)

    def setattr(self, name, value):
        for p in self.values():
            setattr(p, name, value)

    def save(self, fname, strip_prefix=""):
        from ..ndarray.utils import save as nd_save

        arg = {}
        for name, p in self.items():
            if strip_prefix and name.startswith(strip_prefix):
                name = name[len(strip_prefix):]
            arg[name] = p.data()
        nd_save(fname, arg)

    def load(self, fname, ctx=None, allow_missing=False, ignore_extra=False,
             restore_prefix=""):
        from ..ndarray.utils import load as nd_load

        loaded = nd_load(fname)
        if restore_prefix:
            loaded = {restore_prefix + k: v for k, v in loaded.items()}
        for name, p in self.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"parameter {name} missing in file {fname}")
        if not ignore_extra:
            extra = set(loaded) - set(self)
            if extra:
                raise MXNetError(f"file {fname} has extra parameters {sorted(extra)}")
