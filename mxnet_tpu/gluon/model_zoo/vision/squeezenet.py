"""SqueezeNet 1.0/1.1 (reference:
``python/mxnet/gluon/model_zoo/vision/squeezenet.py``)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ....base import MXNetError
from ....ops import nn as _ops


class _Fire(HybridBlock):
    def __init__(self, squeeze_channels, expand1x1_channels,
                 expand3x3_channels, **kwargs):
        super().__init__(**kwargs)
        self.squeeze = nn.Conv2D(squeeze_channels, kernel_size=1,
                                 activation="relu")
        self.expand1x1 = nn.Conv2D(expand1x1_channels, kernel_size=1,
                                   activation="relu")
        self.expand3x3 = nn.Conv2D(expand3x3_channels, kernel_size=3,
                                   padding=1, activation="relu")

    def forward(self, x):
        x = self.squeeze(x)
        return _ops.concat(self.expand1x1(x), self.expand3x3(x), dim=1)


class SqueezeNet(HybridBlock):
    def __init__(self, version, classes=1000, **kwargs):
        super().__init__(**kwargs)
        if version not in ("1.0", "1.1"):
            raise MXNetError(f"unsupported SqueezeNet version {version}")
        self.features = nn.HybridSequential()
        if version == "1.0":
            self.features.add(nn.Conv2D(96, kernel_size=7, strides=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(64, 256, 256))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(64, 256, 256))
        else:
            self.features.add(nn.Conv2D(64, kernel_size=3, strides=2,
                                        activation="relu"))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(_Fire(16, 64, 64))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(_Fire(32, 128, 128))
            self.features.add(nn.MaxPool2D(pool_size=3, strides=2,
                                           ceil_mode=True))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(48, 192, 192))
            self.features.add(_Fire(64, 256, 256))
            self.features.add(_Fire(64, 256, 256))
        self.features.add(nn.Dropout(0.5))

        self.output = nn.HybridSequential()
        self.output.add(nn.Conv2D(classes, kernel_size=1, activation="relu"))
        self.output.add(nn.GlobalAvgPool2D())
        self.output.add(nn.Flatten())

    def forward(self, x):
        return self.output(self.features(x))


def get_squeezenet(version, pretrained=False, ctx=None, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights are not bundled; use "
                         "load_parameters")
    return SqueezeNet(version, **kwargs)


def squeezenet1_0(**kwargs):
    return get_squeezenet("1.0", **kwargs)


def squeezenet1_1(**kwargs):
    return get_squeezenet("1.1", **kwargs)
