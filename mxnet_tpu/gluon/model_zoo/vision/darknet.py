"""Darknet-53 (YOLOv3 backbone; Redmon & Farhadi, "YOLOv3: An Incremental
Improvement", 1804.02767 Table 1).

The reference repo names "GluonCV: ResNet-50 / YOLOv3" as its flagship
detection config (BASELINE.json); the backbone lives in GluonCV
(``gluoncv/model_zoo/yolo/darknet.py``) rather than in-tree, so this is a
from-scratch TPU-native build of the published architecture: every conv is
Conv-BN-LeakyReLU(0.1) which XLA fuses into one MXU pass; residual blocks
are 1x1 (half channels) → 3x3; five stride-2 stages give the 8/16/32
feature pyramid YOLOv3 taps.
"""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn

__all__ = ["DarknetV3", "darknet53", "get_darknet"]


def _conv2d(channels, kernel, padding, strides=1):
    """conv-bn-leaky(0.1) — the only conv motif darknet uses."""
    cell = nn.HybridSequential()
    cell.add(nn.Conv2D(channels, kernel_size=kernel, strides=strides,
                       padding=padding, use_bias=False))
    cell.add(nn.BatchNorm(epsilon=1e-5, momentum=0.9))
    cell.add(nn.LeakyReLU(0.1))
    return cell


class DarknetBasicBlockV3(HybridBlock):
    """Residual: 1x1 conv (channels//2) → 3x3 conv (channels) + identity."""

    def __init__(self, channels, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        self.body.add(_conv2d(channels // 2, 1, 0))
        self.body.add(_conv2d(channels, 3, 1))

    def forward(self, x):
        return x + self.body(x)


class DarknetV3(HybridBlock):
    """Darknet-53 trunk + classifier head.

    ``layers``/``channels``: residual-block counts and output channels per
    stage; darknet53 = layers [1,2,8,8,4], channels [64,128,256,512,1024].
    """

    def __init__(self, layers, channels, classes=1000, **kwargs):
        super().__init__(**kwargs)
        assert len(layers) == len(channels), (layers, channels)
        self.features = nn.HybridSequential()
        # stem: 3x3 stride 1, 32 channels
        self.features.add(_conv2d(channels[0] // 2, 3, 1))
        for nlayer, channel in zip(layers, channels):
            # downsample 3x3 stride 2 then nlayer residual blocks
            self.features.add(_conv2d(channel, 3, 1, strides=2))
            for _ in range(nlayer):
                self.features.add(DarknetBasicBlockV3(channel))
        self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        x = x.mean(axis=(2, 3))  # global average pool
        return self.output(x)


darknet_versions = {"v3": DarknetV3}
darknet_spec = {
    "v3": {53: ([1, 2, 8, 8, 4], [64, 128, 256, 512, 1024])},
}


def get_darknet(darknet_version, num_layers, **kwargs):
    layers, channels = darknet_spec[darknet_version][num_layers]
    return darknet_versions[darknet_version](layers, channels, **kwargs)


def darknet53(**kwargs):
    """Darknet-53 classifier (1804.02767 Table 1)."""
    return get_darknet("v3", 53, **kwargs)
