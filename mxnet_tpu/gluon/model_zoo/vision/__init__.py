"""Vision model zoo (reference:
``python/mxnet/gluon/model_zoo/vision/__init__.py``): same model names and
``get_model`` entry point; ``pretrained=True`` is gated (no weight store in
this environment) — train or ``load_parameters`` instead.
"""
from __future__ import annotations

from ....base import MXNetError
from .alexnet import *
from .darknet import *
from .densenet import *
from .inception import *
from .mobilenet import *
from .resnet import *
from .squeezenet import *
from .vgg import *

from .alexnet import AlexNet
from .darknet import DarknetV3, darknet53
from .densenet import DenseNet
from .inception import Inception3
from .mobilenet import MobileNet, MobileNetV2
from .resnet import (BasicBlockV1, BasicBlockV2, BottleneckV1, BottleneckV2,
                     ResNetV1, ResNetV2, get_resnet)
from .squeezenet import SqueezeNet
from .vgg import VGG, get_vgg
from .yolo import YOLOV3, YOLOV3Loss, yolo3_darknet53, yolo3_targets


def get_model(name, **kwargs):
    """Return a model by name (reference ``vision/__init__.py:89-150``)."""
    models = {
        "resnet18_v1": resnet18_v1, "resnet34_v1": resnet34_v1,
        "resnet50_v1": resnet50_v1, "resnet101_v1": resnet101_v1,
        "resnet152_v1": resnet152_v1,
        "resnet18_v2": resnet18_v2, "resnet34_v2": resnet34_v2,
        "resnet50_v2": resnet50_v2, "resnet101_v2": resnet101_v2,
        "resnet152_v2": resnet152_v2,
        "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
        "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn, "vgg16_bn": vgg16_bn,
        "vgg19_bn": vgg19_bn,
        "alexnet": alexnet,
        "densenet121": densenet121, "densenet161": densenet161,
        "densenet169": densenet169, "densenet201": densenet201,
        "squeezenet1.0": squeezenet1_0, "squeezenet1.1": squeezenet1_1,
        "inceptionv3": inception_v3,
        "mobilenet1.0": mobilenet1_0, "mobilenet0.75": mobilenet0_75,
        "mobilenet0.5": mobilenet0_5, "mobilenet0.25": mobilenet0_25,
        "mobilenetv2_1.0": mobilenet_v2_1_0,
        "mobilenetv2_0.75": mobilenet_v2_0_75,
        "mobilenetv2_0.5": mobilenet_v2_0_5,
        "mobilenetv2_0.25": mobilenet_v2_0_25,
        "darknet53": darknet53,
        "yolo3_darknet53": yolo3_darknet53,
    }
    name = name.lower()
    if name not in models:
        raise MXNetError(
            f"model {name!r} is not in the zoo; options: {sorted(models)}")
    return models[name](**kwargs)
