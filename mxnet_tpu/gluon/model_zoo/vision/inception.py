"""Inception V3 (reference:
``python/mxnet/gluon/model_zoo/vision/inception.py``)."""
from __future__ import annotations

from ...block import HybridBlock
from ... import nn
from ....base import MXNetError
from ....ops import nn as _ops


def _make_basic_conv(**kwargs):
    out = nn.HybridSequential()
    out.add(nn.Conv2D(use_bias=False, **kwargs))
    out.add(nn.BatchNorm(epsilon=0.001))
    out.add(nn.Activation("relu"))
    return out


def _make_branch(use_pool, *conv_settings):
    out = nn.HybridSequential()
    if use_pool == "avg":
        out.add(nn.AvgPool2D(pool_size=3, strides=1, padding=1))
    elif use_pool == "max":
        out.add(nn.MaxPool2D(pool_size=3, strides=2))
    for setting in conv_settings:
        kwargs = {}
        channels, kernel, stride, padding = setting
        kwargs["channels"] = channels
        kwargs["kernel_size"] = kernel
        if stride is not None:
            kwargs["strides"] = stride
        if padding is not None:
            kwargs["padding"] = padding
        out.add(_make_basic_conv(**kwargs))
    return out


class _Concurrent(HybridBlock):
    """Run child branches on the same input and concat on channels."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._branches = []

    def add(self, block):
        self._branches.append(block)
        self.register_child(block, str(len(self._branches) - 1))

    def forward(self, x):
        return _ops.concat(*[b(x) for b in self._branches], dim=1)


def _make_A(pool_features):
    out = _Concurrent()
    out.add(_make_branch(None, (64, 1, None, None)))
    out.add(_make_branch(None, (48, 1, None, None), (64, 5, None, 2)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                         (96, 3, None, 1)))
    out.add(_make_branch("avg", (pool_features, 1, None, None)))
    return out


def _make_B():
    out = _Concurrent()
    out.add(_make_branch(None, (384, 3, 2, None)))
    out.add(_make_branch(None, (64, 1, None, None), (96, 3, None, 1),
                         (96, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


def _make_C(channels_7x7):
    out = _Concurrent()
    out.add(_make_branch(None, (192, 1, None, None)))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0))))
    out.add(_make_branch(None, (channels_7x7, 1, None, None),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (channels_7x7, (1, 7), None, (0, 3)),
                         (channels_7x7, (7, 1), None, (3, 0)),
                         (192, (1, 7), None, (0, 3))))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


def _make_D():
    out = _Concurrent()
    out.add(_make_branch(None, (192, 1, None, None), (320, 3, 2, None)))
    out.add(_make_branch(None, (192, 1, None, None),
                         (192, (1, 7), None, (0, 3)),
                         (192, (7, 1), None, (3, 0)),
                         (192, 3, 2, None)))
    out.add(_make_branch("max"))
    return out


class _SplitConcat(HybridBlock):
    """Branch whose tail splits into parallel convs concat'd back (E block)."""

    def __init__(self, head_settings, tails, **kwargs):
        super().__init__(**kwargs)
        self.head = (_make_branch(None, *head_settings) if head_settings
                     else None)
        self._tails = []
        for i, t in enumerate(tails):
            blk = _make_branch(None, t)
            self._tails.append(blk)
            self.register_child(blk, f"tail{i}")

    def forward(self, x):
        if self.head is not None:
            x = self.head(x)
        return _ops.concat(*[t(x) for t in self._tails], dim=1)


def _make_E():
    out = _Concurrent()
    out.add(_make_branch(None, (320, 1, None, None)))
    out.add(_SplitConcat([(384, 1, None, None)],
                         [(384, (1, 3), None, (0, 1)),
                          (384, (3, 1), None, (1, 0))]))
    out.add(_SplitConcat([(448, 1, None, None), (384, 3, None, 1)],
                         [(384, (1, 3), None, (0, 1)),
                          (384, (3, 1), None, (1, 0))]))
    out.add(_make_branch("avg", (192, 1, None, None)))
    return out


class Inception3(HybridBlock):
    def __init__(self, classes=1000, **kwargs):
        super().__init__(**kwargs)
        self.features = nn.HybridSequential()
        self.features.add(_make_basic_conv(channels=32, kernel_size=3,
                                           strides=2))
        self.features.add(_make_basic_conv(channels=32, kernel_size=3))
        self.features.add(_make_basic_conv(channels=64, kernel_size=3,
                                           padding=1))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_basic_conv(channels=80, kernel_size=1))
        self.features.add(_make_basic_conv(channels=192, kernel_size=3))
        self.features.add(nn.MaxPool2D(pool_size=3, strides=2))
        self.features.add(_make_A(32))
        self.features.add(_make_A(64))
        self.features.add(_make_A(64))
        self.features.add(_make_B())
        self.features.add(_make_C(128))
        self.features.add(_make_C(160))
        self.features.add(_make_C(160))
        self.features.add(_make_C(192))
        self.features.add(_make_D())
        self.features.add(_make_E())
        self.features.add(_make_E())
        self.features.add(nn.AvgPool2D(pool_size=8))
        self.features.add(nn.Dropout(0.5))
        self.output = nn.Dense(classes)

    def forward(self, x):
        x = self.features(x)
        x = x.reshape(x.shape[0], -1)
        return self.output(x)


def inception_v3(pretrained=False, ctx=None, **kwargs):
    if pretrained:
        raise MXNetError("pretrained weights are not bundled; use "
                         "load_parameters")
    return Inception3(**kwargs)
