"""YOLOv3 on the Gluon API (Redmon & Farhadi 1804.02767).

The reference names "GluonCV: ResNet-50 / YOLOv3" as its flagship
detection pairing (BASELINE.json); the implementation lives out-of-tree in
GluonCV (``gluoncv/model_zoo/yolo/yolo3.py``), so this is a from-scratch
TPU-first build of the published architecture, re-using the in-tree
detection op family (``ops/detection.py``: IoU, box_nms).

TPU-first design decisions (vs the GluonCV original):

- **Static shapes end to end.** Anchor/offset grids are baked per feature
  shape as trace constants; labels are fixed-width ``(B, M, 5)`` with -1
  padding; NMS is the static-shape ``box_nms`` (pruned rows = -1), so the
  whole inference path jits into one XLA program.
- **Target assignment is host-side numpy** (``yolo3_targets``): the
  matching scatter (one cell per gt) is data-dependent — on-device it
  would be a serialized scatter chain; in the input pipeline it
  overlaps with device compute, the same split the reference makes by
  running label processing in its DataIter workers.
- **The pred-dependent "ignore" mask is on-device** in ``YOLOV3Loss``: it
  depends on decoded predictions, so it must live in the jitted loss —
  one (B, N, M) IoU einsum, MXU-friendly, no host sync.
- Upsampling is nearest ``repeat`` (fuses); route convs are 1x1.

Scale order everywhere is [stride 8, stride 16, stride 32].
"""
from __future__ import annotations

import numpy as onp

from ....base import MXNetError
from ....ops import detection as _det
from ....ops import nn as _ops
from ... import nn
from ...block import HybridBlock
from .darknet import _conv2d, darknet53

__all__ = ["YOLOV3", "YOLOV3Loss", "yolo3_darknet53", "yolo3_targets"]

# canonical COCO anchors (1804.02767 §2.3), pixels at 416 input,
# grouped per scale [stride8, stride16, stride32]
_DEFAULT_ANCHORS = [
    [(10, 13), (16, 30), (33, 23)],
    [(30, 61), (62, 45), (59, 119)],
    [(116, 90), (156, 198), (373, 326)],
]
_DEFAULT_STRIDES = [8, 16, 32]


def _upsample2x(x):
    """Nearest 2x upsample, NCHW: two repeats XLA fuses into one copy."""
    return x.repeat(2, axis=2).repeat(2, axis=3)


class YOLODetectionBlockV3(HybridBlock):
    """5 alternating 1x1(c)/3x3(2c) convs ("body") + a 3x3(2c) "tip".

    The body output routes laterally (and, through a 1x1 transition, up
    to the next-shallower scale); the tip feeds this scale's output conv.
    """

    def __init__(self, channel, **kwargs):
        super().__init__(**kwargs)
        self.body = nn.HybridSequential()
        for _ in range(2):
            self.body.add(_conv2d(channel, 1, 0))
            self.body.add(_conv2d(channel * 2, 3, 1))
        self.body.add(_conv2d(channel, 1, 0))
        self.tip = _conv2d(channel * 2, 3, 1)

    def forward(self, x):
        route = self.body(x)
        return route, self.tip(route)


class YOLOOutputV3(HybridBlock):
    """Per-scale 1x1 output conv + raw-prediction unpacking/decoding."""

    def __init__(self, num_class, anchors, stride, **kwargs):
        super().__init__(**kwargs)
        self._classes = num_class
        self._num_pred = 4 + 1 + num_class
        self._anchors = onp.asarray(anchors, onp.float32)  # (A, 2)
        self._na = self._anchors.shape[0]
        self._stride = stride
        self.prediction = nn.Conv2D(self._na * self._num_pred, 1, padding=0)
        self._grid_cache = {}

    def _grids(self, h, w):
        """(1, H*W*A, 2) cell offsets + tiled anchors, cached per shape —
        trace constants under jit, uploaded once in eager mode."""
        key = (h, w)
        if key not in self._grid_cache:
            from .... import np as mnp

            ys, xs = onp.meshgrid(onp.arange(h), onp.arange(w),
                                  indexing="ij")
            off = onp.stack([xs, ys], axis=-1).astype(onp.float32)  # x,y
            off = onp.repeat(off.reshape(h * w, 1, 2), self._na, axis=1)
            anc = onp.tile(self._anchors[None], (h * w, 1, 1))
            self._grid_cache[key] = (
                mnp.array(off.reshape(1, h * w * self._na, 2)),
                mnp.array(anc.reshape(1, h * w * self._na, 2)))
        return self._grid_cache[key]

    def forward(self, x):
        from .... import np as mnp

        pred = self.prediction(x)  # (B, A*K, H, W)
        b = pred.shape[0]
        h, w = pred.shape[2], pred.shape[3]
        k = self._num_pred
        pred = pred.reshape(b, self._na, k, h, w)
        pred = pred.transpose(0, 3, 4, 1, 2).reshape(b, h * w * self._na, k)
        offsets, anchors = self._grids(h, w)
        raw_center = pred[:, :, 0:2]
        raw_scale = pred[:, :, 2:4]
        objness = pred[:, :, 4:5]
        cls_pred = pred[:, :, 5:]
        strides = mnp.full((1, h * w * self._na, 1), float(self._stride))
        return (raw_center, raw_scale, objness, cls_pred, anchors, offsets,
                strides)


def _decode_boxes(raw_center, raw_scale, anchors, offsets, strides):
    """Raw predictions -> corner boxes in input pixels (1804.02767 §2.1):
    b_xy = (σ(t_xy) + cell) * stride ; b_wh = anchor * exp(t_wh)."""
    from .... import np as mnp

    center = (_ops.sigmoid(raw_center) + offsets) * strides
    # clip exp input: an untrained/diverged net must not overflow fp32
    wh = anchors * mnp.exp(mnp.clip(raw_scale, -20.0, 8.0))
    half = wh * 0.5
    return mnp.concatenate([center - half, center + half], axis=-1)


class YOLOV3(HybridBlock):
    """Full detector: backbone stages → top-down detection blocks → three
    ``YOLOOutputV3`` heads.

    ``stages``: list of 3 blocks emitting stride-8/16/32 features.
    Training-mode forward returns the raw tensors the loss consumes;
    predict-mode returns ``(ids, scores, boxes)`` after per-class
    expansion + NMS, everything static-shape.
    """

    def __init__(self, stages, channels=(128, 256, 512), classes=20,
                 anchors=None, strides=None, nms_thresh=0.45, nms_topk=100,
                 **kwargs):
        super().__init__(**kwargs)
        anchors = anchors or _DEFAULT_ANCHORS
        strides = strides or _DEFAULT_STRIDES
        if not (len(stages) == len(anchors) == len(strides) == 3):
            raise MXNetError("YOLOV3 wants exactly 3 stages/anchor "
                             "groups/strides")
        self.classes = classes
        self.nms_thresh = nms_thresh
        self.nms_topk = nms_topk
        # scale-ordered [stride8, stride16, stride32] — yolo_outputs below
        # is built deepest-FIRST, so iterating the heads reverses this;
        # target generation must use these, not the head order
        self.anchors = [list(map(tuple, grp)) for grp in anchors]
        self.strides = list(strides)
        self.stages = nn.HybridSequential()
        for s in stages:
            self.stages.add(s)
        # deepest-first construction (stride 32 -> 8)
        self.yolo_blocks = nn.HybridSequential()
        self.yolo_outputs = nn.HybridSequential()
        self.transitions = nn.HybridSequential()
        for i, ch in enumerate(reversed(channels)):     # 512, 256, 128
            scale = len(channels) - 1 - i               # 2, 1, 0
            self.yolo_blocks.add(YOLODetectionBlockV3(ch))
            self.yolo_outputs.add(
                YOLOOutputV3(classes, anchors[scale], strides[scale]))
            if i < len(channels) - 1:
                self.transitions.add(_conv2d(ch // 2, 1, 0))

    def forward(self, x):
        from .... import autograd
        from .... import np as mnp

        feats = []
        for stage in self.stages:
            x = stage(x)
            feats.append(x)
        # top-down pass, deepest first
        outputs = []
        route = None
        for i, (block, head) in enumerate(zip(self.yolo_blocks,
                                              self.yolo_outputs)):
            feat = feats[len(feats) - 1 - i]
            if route is not None:
                feat = mnp.concatenate(
                    [_upsample2x(self.transitions[i - 1](route)), feat],
                    axis=1)
            route, tip = block(feat)
            outputs.append(head(tip))
        outputs = outputs[::-1]  # back to [stride8, stride16, stride32]

        cat = [mnp.concatenate([o[j] for o in outputs], axis=1)
               for j in range(7)]
        (raw_center, raw_scale, objness, cls_pred, anchors, offsets,
         strides) = cat
        if autograd.is_training():
            return (raw_center, raw_scale, objness, cls_pred, anchors,
                    offsets, strides)

        boxes = _decode_boxes(raw_center, raw_scale, anchors, offsets,
                              strides)                       # (B, N, 4)
        scores = (_ops.sigmoid(cls_pred)
                  * _ops.sigmoid(objness))                   # (B, N, C)
        b, n = boxes.shape[0], boxes.shape[1]
        c = self.classes
        # per-class rows [id, score, x1, y1, x2, y2] -> (B, N*C, 6)
        ids = mnp.broadcast_to(
            mnp.arange(c).reshape(1, 1, c, 1), (b, n, c, 1))
        sc = scores.reshape(b, n, c, 1)
        bx = mnp.broadcast_to(boxes.reshape(b, n, 1, 4), (b, n, c, 4))
        dets = mnp.concatenate([ids, sc, bx], axis=-1).reshape(b, n * c, 6)
        dets = _det.box_nms(dets, overlap_thresh=self.nms_thresh,
                            valid_thresh=0.01, topk=self.nms_topk,
                            coord_start=2, score_index=1, id_index=0)
        return dets[:, :, 0:1], dets[:, :, 1:2], dets[:, :, 2:6]


def yolo3_targets(labels, input_size, num_class, anchors=None,
                  strides=None):
    """Host-side static target assignment (the GluonCV
    ``YOLOV3PrefetchTargetGenerator`` role, run in the data pipeline).

    ``labels``: (B, M, 5) numpy, rows [cls, x1, y1, x2, y2] normalized to
    [0, 1], padded with -1. Each valid gt matches the ONE anchor (of 9)
    whose shape-IoU at the origin is highest (1804.02767 §2.2), landing in
    that anchor's scale at the gt center's cell.

    Returns numpy arrays over the concatenated anchor axis N:
    ``objness (B,N,1)``, ``center_t (B,N,2)``, ``scale_t (B,N,2)``,
    ``weight (B,N,2)`` (2 - w*h box-size weighting, zero on unmatched),
    ``cls_t (B,N,C)`` one-hot, ``gt_boxes (B,M,4)`` in pixels for the
    loss's dynamic ignore mask.
    """
    anchors = onp.asarray(anchors or _DEFAULT_ANCHORS,
                          onp.float32)            # (3, A, 2)
    strides = onp.asarray(strides or _DEFAULT_STRIDES, onp.int64)
    labels = onp.asarray(labels, onp.float32)
    b, m, _ = labels.shape
    na = anchors.shape[1]
    sizes = [int(input_size // s) for s in strides]
    n_per = [h * h * na for h in sizes]
    n = sum(n_per)
    starts = onp.cumsum([0] + n_per[:-1])

    objness = onp.zeros((b, n, 1), onp.float32)
    center_t = onp.zeros((b, n, 2), onp.float32)
    scale_t = onp.zeros((b, n, 2), onp.float32)
    weight = onp.zeros((b, n, 2), onp.float32)
    cls_t = onp.zeros((b, n, num_class), onp.float32)
    gt_boxes = onp.full((b, m, 4), -1.0, onp.float32)

    flat_anchors = anchors.reshape(-1, 2)         # (9, 2)
    for bi in range(b):
        for mi in range(m):
            cls, x1, y1, x2, y2 = labels[bi, mi]
            if cls < 0:
                continue
            px1, py1, px2, py2 = (v * input_size for v in (x1, y1, x2, y2))
            gt_boxes[bi, mi] = [px1, py1, px2, py2]
            gw, gh = max(px2 - px1, 1e-6), max(py2 - py1, 1e-6)
            # shape-only IoU at origin vs all 9 anchors
            iw = onp.minimum(flat_anchors[:, 0], gw)
            ih = onp.minimum(flat_anchors[:, 1], gh)
            inter = iw * ih
            iou = inter / (flat_anchors[:, 0] * flat_anchors[:, 1]
                           + gw * gh - inter)
            best = int(onp.argmax(iou))
            scale_i, anchor_i = best // na, best % na
            grid = sizes[scale_i]
            cx = (px1 + px2) / 2 / strides[scale_i]
            cy = (py1 + py2) / 2 / strides[scale_i]
            ci = min(int(cx), grid - 1)
            cj = min(int(cy), grid - 1)
            idx = starts[scale_i] + (cj * grid + ci) * na + anchor_i
            objness[bi, idx, 0] = 1.0
            center_t[bi, idx] = [cx - ci, cy - cj]
            scale_t[bi, idx] = [
                onp.log(gw / flat_anchors[best, 0]),
                onp.log(gh / flat_anchors[best, 1])]
            weight[bi, idx] = 2.0 - gw * gh / input_size / input_size
            cls_t[bi, idx, int(cls)] = 1.0
    return objness, center_t, scale_t, weight, cls_t, gt_boxes


def _sigmoid_bce(logits, targets, weight=None):
    """Numerically stable elementwise sigmoid cross-entropy."""
    from .... import np as mnp

    loss = (mnp.maximum(logits, 0.0) - logits * targets
            + mnp.log1p(mnp.exp(-mnp.abs(logits))))
    if weight is not None:
        loss = loss * weight
    return loss


class YOLOV3Loss(HybridBlock):
    """Four-part YOLOv3 loss (GluonCV ``YOLOV3Loss`` semantics):
    objectness BCE (with the dynamic IoU ignore mask), center BCE, scale
    L2 (in t-space), class BCE — each normalized by batch positives."""

    def __init__(self, ignore_iou_thresh=0.7, **kwargs):
        super().__init__(**kwargs)
        self._ignore = ignore_iou_thresh

    def forward(self, raw_center, raw_scale, objness, cls_pred, anchors,
                offsets, strides, obj_t, center_t, scale_t, weight, cls_t,
                gt_boxes):
        from .... import np as mnp

        npos = mnp.maximum(obj_t.sum(), 1.0)

        # dynamic part: decoded predictions overlapping ANY gt above the
        # threshold are exempt from the negative-objectness loss
        pred_boxes = _decode_boxes(raw_center, raw_scale, anchors, offsets,
                                   strides)                    # (B,N,4)
        iou = _det.box_iou(pred_boxes, gt_boxes,
                           fmt="corner")                       # (B,N,M)
        best_iou = iou.max(axis=-1, keepdims=True)             # (B,N,1)
        obj_mask = obj_t + (1.0 - obj_t) * (best_iou < self._ignore)

        obj_loss = _sigmoid_bce(objness, obj_t, obj_mask).sum() / npos
        ctr_loss = _sigmoid_bce(raw_center, center_t,
                                weight * obj_t).sum() / npos
        diff = (raw_scale - scale_t)
        scl_loss = (0.5 * diff * diff * weight * obj_t).sum() / npos
        cls_loss = _sigmoid_bce(cls_pred, cls_t, obj_t).sum() / npos
        return obj_loss + ctr_loss + scl_loss + cls_loss


def yolo3_darknet53(classes=20, pretrained_base=False, **kwargs):
    """YOLOv3 with a Darknet-53 backbone (the BASELINE.json flagship
    detection config). ``classes`` excludes background (YOLO has none)."""
    if pretrained_base:
        raise MXNetError("no pretrained weight store in this environment; "
                         "train from scratch or load_parameters")
    base = darknet53()
    feats = base.features
    # stage split: stem+s1+s2+s3 = stride 8 (256ch) | s4 = stride 16
    # (512ch) | s5 = stride 32 (1024ch); block counts per DarknetV3:
    # 1 + (1+1) + (1+2) + (1+8) = 15, then 1+8 = 9, then 1+4 = 5
    stages = [feats[:15], feats[15:24], feats[24:29]]
    return YOLOV3(stages, channels=(128, 256, 512), classes=classes,
                  **kwargs)
