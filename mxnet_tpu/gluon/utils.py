"""Gluon utilities (reference ``python/mxnet/gluon/utils.py``):
``split_data``/``split_and_load`` for multi-device DP, grad clipping."""
from __future__ import annotations

import numpy as _onp

from ..base import MXNetError
from ..device import Context
from ..ndarray.ndarray import NDArray


def split_data(data, num_slice, batch_axis=0, even_split=True):
    size = data.shape[batch_axis]
    if even_split and size % num_slice != 0:
        raise MXNetError(
            f"cannot evenly split batch of {size} into {num_slice} slices")
    step = size // num_slice
    slices = []
    for i in range(num_slice):
        lo = i * step
        hi = (i + 1) * step if i < num_slice - 1 else size
        idx = [slice(None)] * data.ndim
        idx[batch_axis] = slice(lo, hi)
        slices.append(data[tuple(idx)])
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split a batch along batch_axis and load one slice per context."""
    if not isinstance(data, NDArray):
        data = NDArray(_onp.asarray(data))
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm, check_isfinite=True):
    """Rescale arrays so their global L2 norm <= max_norm (reference util).

    Thin wrapper over ``resilience.guardrails.clip_by_global_norm`` — the
    same fused-reduction implementation ``Trainer(clip_global_norm=...)``
    uses, so the manual and the trainer-integrated paths cannot drift.
    A non-finite norm leaves the arrays untouched (scaling can't fix it)
    and warns when ``check_isfinite``.
    """
    from ..resilience.guardrails import clip_by_global_norm

    _, norm = clip_by_global_norm(arrays, max_norm)
    if check_isfinite and not _onp.isfinite(norm):
        import warnings

        warnings.warn("nan or inf in clip_global_norm")
    return norm


def download(url, path=None, overwrite=False, sha1_hash=None,
             retries=5, verify_ssl=True):  # pragma: no cover
    raise MXNetError(
        "download() is unavailable in this zero-egress build; place files "
        "locally and pass their path instead")


def check_sha1(filename, sha1_hash):
    import hashlib

    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


class HookHandle:
    """Compat alias (reference gluon.utils.HookHandle)."""

    def __init__(self, table=None, hid=None):
        self._table = table
        self._hid = hid

    def detach(self):
        if self._table is not None:
            self._table.pop(self._hid, None)
