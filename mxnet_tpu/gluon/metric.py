"""Evaluation metrics (reference ``python/mxnet/gluon/metric.py``, 21 classes)."""
from __future__ import annotations

import math

import numpy as _onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray

_METRIC_REGISTRY = {}

# short names the reference registers via @alias (gluon/metric.py:238,
# 368, 441, 1333, 1492) so ``metric.create('acc')``-era scripts resolve
_ALIASES = {
    "composite": "compositeevalmetric",
    "acc": "accuracy",
    "top_k_accuracy": "topkaccuracy",
    "top_k_acc": "topkaccuracy",
    "ce": "crossentropy",
    "pearsonr": "pearsoncorrelation",
}


def register(cls):
    _METRIC_REGISTRY[cls.__name__.lower()] = cls
    return cls


def create(metric, *args, **kwargs):
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        composite = CompositeEvalMetric()
        for m in metric:
            composite.add(create(m))
        return composite
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    name = metric.lower()
    name = _ALIASES.get(name, name)
    try:
        return _METRIC_REGISTRY[name](*args, **kwargs)
    except KeyError:
        raise MXNetError(f"unknown metric {metric!r}") from None


def _to_numpy(x):
    if isinstance(x, NDArray):
        return x.asnumpy()
    return _onp.asarray(x)


class EvalMetric:
    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = name
        self.output_names = output_names
        self.label_names = label_names
        self._kwargs = kwargs
        self.reset()

    def reset(self):
        self.num_inst = 0
        self.sum_metric = 0.0

    def update(self, labels, preds):
        raise NotImplementedError

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        name, value = self.get()
        if not isinstance(name, list):
            name = [name]
        if not isinstance(value, list):
            value = [value]
        return list(zip(name, value))

    def update_dict(self, label, pred):
        self.update(list(label.values()), list(pred.values()))

    def __str__(self):
        return f"EvalMetric: {dict(self.get_name_value())}"


class CompositeEvalMetric(EvalMetric):
    def __init__(self, metrics=None, name="composite", **kwargs):
        self.metrics = [create(m) for m in (metrics or [])]
        super().__init__(name, **kwargs)

    def add(self, metric):
        self.metrics.append(create(metric))

    def reset(self):
        for m in getattr(self, "metrics", []):
            m.reset()

    def update(self, labels, preds):
        for m in self.metrics:
            m.update(labels, preds)

    def get(self):
        names, values = [], []
        for m in self.metrics:
            n, v = m.get()
            names.append(n)
            values.append(v)
        return names, values


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]


@register
class Accuracy(EvalMetric):
    def __init__(self, axis=1, name="accuracy", **kwargs):
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label)
            if pred.ndim > label.ndim:
                pred = pred.argmax(axis=self.axis)
            pred = pred.astype(_onp.int64).ravel()
            label = label.astype(_onp.int64).ravel()
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(label)


@register
class TopKAccuracy(EvalMetric):
    def __init__(self, top_k=1, name="top_k_accuracy", **kwargs):
        self.top_k = top_k
        super().__init__(f"{name}_{top_k}", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(_onp.int64).ravel()
            topk = _onp.argsort(-pred, axis=-1)[:, : self.top_k]
            hit = (topk == label[:, None]).any(axis=1)
            self.sum_metric += float(hit.sum())
            self.num_inst += len(label)


class _BinaryClassificationBase(EvalMetric):
    def reset(self):
        super().reset()
        self.tp = self.fp = self.tn = self.fn = 0

    def _count(self, labels, preds, threshold=0.5):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).ravel().astype(_onp.int64)
            if pred.ndim > 1 and pred.shape[-1] > 1:
                pred = pred.argmax(-1).ravel()
            else:
                pred = (pred.ravel() > threshold).astype(_onp.int64)
            self.tp += int(((pred == 1) & (label == 1)).sum())
            self.fp += int(((pred == 1) & (label == 0)).sum())
            self.tn += int(((pred == 0) & (label == 0)).sum())
            self.fn += int(((pred == 0) & (label == 1)).sum())


@register
class Fbeta(_BinaryClassificationBase):
    """F-beta score of a binary classification problem (reference
    ``python/mxnet/gluon/metric.py:815-871``):
    ``(1 + beta^2) * P * R / (beta^2 * P + R)``."""

    def __init__(self, name="fbeta", beta=1, threshold=0.5,
                 average="micro", **kwargs):
        self.beta = beta
        self.threshold = threshold
        self.average = average
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        self._count(labels, preds, threshold=self.threshold)
        self.num_inst = 1
        prec = self.tp / max(self.tp + self.fp, 1)
        rec = self.tp / max(self.tp + self.fn, 1)
        b2 = self.beta ** 2
        self.sum_metric = ((1 + b2) * prec * rec
                           / max(b2 * prec + rec, 1e-12))


@register
class F1(Fbeta):
    """F1 is F-beta at beta=1 (the reference derives Fbeta from F1;
    sharing one update either way)."""

    def __init__(self, name="f1", average="macro", **kwargs):
        super().__init__(name, beta=1, average=average, **kwargs)


@register
class BinaryAccuracy(EvalMetric):
    """Accuracy of a binary / multilabel problem at a confidence
    ``threshold`` (reference ``metric.py:876-934``)."""

    def __init__(self, name="binary_accuracy", threshold=0.5, **kwargs):
        self.threshold = threshold
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            thr = (_to_numpy(self.threshold)
                   if isinstance(self.threshold, NDArray)
                   else self.threshold)
            pred = (_to_numpy(pred) > thr).astype(_onp.int64).ravel()
            label = _to_numpy(label).astype(_onp.int64).ravel()
            if len(label) != len(pred):
                raise ValueError(
                    f"shape mismatch: {len(label)} labels vs "
                    f"{len(pred)} predictions")
            self.sum_metric += float((pred == label).sum())
            self.num_inst += len(pred)


@register
class MeanPairwiseDistance(EvalMetric):
    """Mean p-norm distance between label and prediction rows
    (reference ``metric.py:1197-1258``)."""

    def __init__(self, name="mpd", p=2, **kwargs):
        self.p = p
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype(_onp.float64)
            pred = _to_numpy(pred).astype(_onp.float64)
            label = label.reshape(label.shape[0], -1)
            pred = pred.reshape(pred.shape[0], -1)
            dis = (((label - pred) ** self.p).sum(axis=-1)) ** (1. / self.p)
            self.sum_metric += float(dis.sum())
            self.num_inst += label.shape[0]


@register
class MeanCosineSimilarity(EvalMetric):
    """Mean cosine similarity along the last axis
    (reference ``metric.py:1263-1329``)."""

    def __init__(self, name="cos_sim", eps=1e-8, **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype(_onp.float64)
            pred = _to_numpy(pred).astype(_onp.float64)
            if label.ndim == 1:
                label = label.reshape(1, -1)
            if pred.ndim == 1:
                pred = pred.reshape(1, -1)
            sim = (label * pred).sum(axis=-1)
            n_p = _onp.linalg.norm(pred, axis=-1)
            n_l = _onp.linalg.norm(label, axis=-1)
            sim = sim / _onp.maximum(n_l * n_p, self.eps)
            self.sum_metric += float(sim.sum())
            self.num_inst += int(
                _onp.prod(label.shape[:-1], dtype=_onp.int64))


@register
class MCC(_BinaryClassificationBase):
    def __init__(self, name="mcc", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        self._count(labels, preds)
        self.num_inst = 1
        num = self.tp * self.tn - self.fp * self.fn
        den = math.sqrt(
            (self.tp + self.fp) * (self.tp + self.fn)
            * (self.tn + self.fp) * (self.tn + self.fn)) or 1.0
        self.sum_metric = num / den


@register
class Perplexity(EvalMetric):
    def __init__(self, ignore_label=None, axis=-1, name="perplexity", **kwargs):
        self.ignore_label = ignore_label
        self.axis = axis
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            pred = _to_numpy(pred)
            label = _to_numpy(label).astype(_onp.int64)
            flat = label.ravel()
            probs = pred.reshape(-1, pred.shape[-1])[
                _onp.arange(flat.size), flat]
            if self.ignore_label is not None:
                keep = flat != self.ignore_label
                probs = probs[keep]
            self.sum_metric += float(-_onp.log(_onp.maximum(probs, 1e-12)).sum())
            self.num_inst += probs.size

    def get(self):
        if self.num_inst == 0:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@register
class MAE(EvalMetric):
    def __init__(self, name="mae", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred).reshape(label.shape)
            self.sum_metric += float(_onp.abs(label - pred).mean() * label.shape[0])
            self.num_inst += label.shape[0]


@register
class MSE(EvalMetric):
    def __init__(self, name="mse", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label)
            pred = _to_numpy(pred).reshape(label.shape)
            self.sum_metric += float(((label - pred) ** 2).mean() * label.shape[0])
            self.num_inst += label.shape[0]


@register
class RMSE(MSE):
    def __init__(self, name="rmse", **kwargs):
        super().__init__(name, **kwargs)

    def get(self):
        name, value = super().get()
        return name, math.sqrt(value) if not math.isnan(value) else value


@register
class CrossEntropy(EvalMetric):
    def __init__(self, eps=1e-12, name="cross-entropy", **kwargs):
        self.eps = eps
        super().__init__(name, **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype(_onp.int64).ravel()
            pred = _to_numpy(pred).reshape(label.size, -1)
            prob = pred[_onp.arange(label.size), label]
            self.sum_metric += float(-_onp.log(prob + self.eps).sum())
            self.num_inst += label.size


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", **kwargs):
        super().__init__(eps, name, **kwargs)


@register
class PearsonCorrelation(EvalMetric):
    def __init__(self, name="pearsonr", **kwargs):
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._labels = []
        self._preds = []

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            self._labels.append(_to_numpy(label).ravel())
            self._preds.append(_to_numpy(pred).ravel())

    def get(self):
        if not self._labels:
            return self.name, float("nan")
        l = _onp.concatenate(self._labels)
        p = _onp.concatenate(self._preds)
        return self.name, float(_onp.corrcoef(l, p)[0, 1])


@register
class PCC(EvalMetric):
    """Polychoric-style multiclass PCC (reference PCC metric)."""

    def __init__(self, name="pcc", **kwargs):
        self._conf = None
        super().__init__(name, **kwargs)

    def reset(self):
        super().reset()
        self._conf = None

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            label = _to_numpy(label).astype(_onp.int64).ravel()
            pred = _to_numpy(pred)
            if pred.ndim > 1:
                pred = pred.argmax(-1)
            pred = pred.astype(_onp.int64).ravel()
            k = int(max(label.max(), pred.max())) + 1
            if self._conf is None or self._conf.shape[0] < k:
                newc = _onp.zeros((k, k), _onp.int64)
                if self._conf is not None:
                    newc[: self._conf.shape[0], : self._conf.shape[1]] = self._conf
                self._conf = newc
            for li, pi in zip(label, pred):
                self._conf[pi, li] += 1
            self.num_inst += len(label)

    def get(self):
        if self._conf is None:
            return self.name, float("nan")
        c = self._conf.astype(_onp.float64)
        n = c.sum()
        pk = c.sum(0)
        tk = c.sum(1)
        num = n * _onp.trace(c) - (pk * tk).sum()
        den = math.sqrt((n * n - (pk * pk).sum()) * (n * n - (tk * tk).sum())) or 1.0
        return self.name, float(num / den)


@register
class Loss(EvalMetric):
    def __init__(self, name="loss", **kwargs):
        super().__init__(name, **kwargs)

    def update(self, _, preds):
        for pred in _as_list(preds):
            loss = _to_numpy(pred)
            self.sum_metric += float(loss.sum())
            self.num_inst += loss.size


@register
class Torch(Loss):  # pragma: no cover - reference legacy alias
    def __init__(self, name="torch", **kwargs):
        super().__init__(name, **kwargs)


@register
class Caffe(Loss):  # pragma: no cover - reference legacy alias
    def __init__(self, name="caffe", **kwargs):
        super().__init__(name, **kwargs)


@register
class CustomMetric(EvalMetric):
    def __init__(self, feval, name="custom", allow_extra_outputs=False, **kwargs):
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs
        super().__init__(f"custom({name})", **kwargs)

    def update(self, labels, preds):
        for label, pred in zip(_as_list(labels), _as_list(preds)):
            out = self._feval(_to_numpy(label), _to_numpy(pred))
            if isinstance(out, tuple):
                s, n = out
                self.sum_metric += s
                self.num_inst += n
            else:
                self.sum_metric += out
                self.num_inst += 1


def np_metric(name=None, allow_extra_outputs=False):
    """Decorator creating a CustomMetric from a numpy function."""

    def deco(f):
        return CustomMetric(f, name or f.__name__, allow_extra_outputs)

    return deco


def np(numpy_feval, name=None, allow_extra_outputs=False):  # pylint: disable=invalid-name
    """Create a CustomMetric from a ``feval(label, pred)`` numpy function
    (reference ``gluon/metric.py:1824``; numpy itself is ``_onp`` in this
    module, so the reference's unfortunate name is safe to mirror)."""
    def feval(label, pred):
        return numpy_feval(label, pred)

    feval.__name__ = getattr(numpy_feval, "__name__", "feval")
    return CustomMetric(feval, name or feval.__name__, allow_extra_outputs)
