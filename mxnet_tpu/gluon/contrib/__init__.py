"""Gluon contrib (reference: ``python/mxnet/gluon/contrib/``)."""
from . import estimator
from . import nn
