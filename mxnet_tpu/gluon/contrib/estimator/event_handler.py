"""Estimator event handlers (reference:
``python/mxnet/gluon/contrib/estimator/event_handler.py``)."""
from __future__ import annotations

import logging
import time

import numpy as _onp

from ....base import MXNetError


class TrainBegin:
    def train_begin(self, estimator, *args, **kwargs):
        pass


class TrainEnd:
    def train_end(self, estimator, *args, **kwargs):
        pass


class EpochBegin:
    def epoch_begin(self, estimator, *args, **kwargs):
        pass


class EpochEnd:
    def epoch_end(self, estimator, *args, **kwargs):
        pass


class BatchBegin:
    def batch_begin(self, estimator, *args, **kwargs):
        pass


class BatchEnd:
    def batch_end(self, estimator, *args, **kwargs):
        pass


class PreStep:
    """Handlers judging a batch BETWEEN backward and the optimizer step.

    ``pre_step`` runs after ``fit_batch`` computed the loss and gradients
    but before ``trainer.step`` applies them; returning ``False`` vetoes
    the update for this batch (the fit loop still runs ``batch_end``, so
    metrics/checkpoints observe the skipped batch). ``step_error`` is
    offered any exception ``trainer.step`` raises; returning ``True``
    absorbs it (the batch becomes a skip), ``False`` lets it propagate.
    The numerical guardrails (``resilience.guardrails.GuardrailHandler``)
    are the canonical implementation.
    """

    def pre_step(self, estimator, batch=None, loss=None):  # pylint: disable=unused-argument
        return True

    def step_error(self, estimator, exc):  # pylint: disable=unused-argument
        return False


class StoppingHandler(TrainBegin, BatchEnd, EpochEnd):
    """Stop at max_epoch/max_batch (reference ``event_handler.py:94``)."""

    def __init__(self, max_epoch=None, max_batch=None):
        self.max_epoch = max_epoch
        self.max_batch = max_batch
        self.current_batch = 0
        self.current_epoch = 0
        self.stop_training = False

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.max_batch and self.current_batch >= self.max_batch:
            self.stop_training = True

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.max_epoch and self.current_epoch >= self.max_epoch:
            self.stop_training = True


class MetricHandler(EpochBegin, BatchEnd):
    """Reset metrics per epoch, update per batch (reference
    ``event_handler.py:135``)."""

    def __init__(self, metrics, priority=-1000):
        self.metrics = metrics or []
        self.priority = priority

    def epoch_begin(self, estimator, *args, **kwargs):
        for metric in self.metrics:
            metric.reset()

    def batch_end(self, estimator, *args, **kwargs):
        pred = kwargs.get("pred")
        label = kwargs.get("label")
        loss = kwargs.get("loss")
        for metric in self.metrics:
            if getattr(metric, "_is_loss_metric", False):
                metric.update(0, loss)
            else:
                metric.update(label, pred)


class ValidationHandler(TrainBegin, BatchEnd, EpochEnd):
    """Run validation every ``epoch_period`` epochs (reference
    ``event_handler.py:182``)."""

    def __init__(self, val_data, eval_fn, epoch_period=1, batch_period=None,
                 priority=-1000):
        self.val_data = val_data
        self.eval_fn = eval_fn
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.priority = priority
        self.current_batch = 0
        self.current_epoch = 0

    def train_begin(self, estimator, *args, **kwargs):
        self.current_batch = 0
        self.current_epoch = 0

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self.eval_fn(val_data=self.val_data)

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self.eval_fn(val_data=self.val_data)


class LoggingHandler(TrainBegin, TrainEnd, EpochBegin, EpochEnd, BatchBegin,
                     BatchEnd):
    """Periodic logging (reference ``event_handler.py:250``)."""

    def __init__(self, log_interval="epoch", metrics=None, priority=_onp.inf):
        if log_interval != "epoch" and not isinstance(log_interval, int):
            raise MXNetError("log_interval must be 'epoch' or an int")
        self.log_interval = log_interval
        self.metrics = metrics or []
        self.priority = priority
        self.batch_index = 0
        self.current_epoch = 0
        self.processed_samples = 0
        self.logger = logging.getLogger("mxnet_tpu.estimator")

    def train_begin(self, estimator, *args, **kwargs):
        self.train_start = time.time()
        self.logger.info("Training begin")

    def train_end(self, estimator, *args, **kwargs):
        t = time.time() - self.train_start
        self.logger.info("Train finished in %.3fs: %s", t,
                         self._metrics_str())

    def _metrics_str(self):
        parts = []
        for m in self.metrics:
            name, val = m.get()
            parts.append(f"{name}={val:.4f}" if isinstance(val, float)
                         else f"{name}={val}")
        return " ".join(parts)

    def epoch_begin(self, estimator, *args, **kwargs):
        self.epoch_start = time.time()
        self.batch_index = 0

    def epoch_end(self, estimator, *args, **kwargs):
        t = time.time() - self.epoch_start
        self.logger.info("Epoch %d finished in %.3fs: %s",
                         self.current_epoch, t, self._metrics_str())
        self.current_epoch += 1

    def batch_end(self, estimator, *args, **kwargs):
        self.batch_index += 1
        if isinstance(self.log_interval, int) \
                and self.batch_index % self.log_interval == 0:
            self.logger.info("Epoch %d batch %d: %s", self.current_epoch,
                             self.batch_index, self._metrics_str())


class CheckpointHandler(TrainBegin, BatchEnd, EpochEnd):
    """Save params (+trainer states) periodically; keeps best by monitored
    metric (reference ``event_handler.py:383``)."""

    def __init__(self, model_dir, model_prefix="model", monitor=None,
                 verbose=0, save_best=False, mode="auto", epoch_period=1,
                 batch_period=None, max_checkpoints=5,
                 resume_from_checkpoint=False):
        import os

        self.model_dir = model_dir
        os.makedirs(model_dir, exist_ok=True)
        self.model_prefix = model_prefix
        self.monitor = monitor
        self.save_best = save_best
        self.epoch_period = epoch_period
        self.batch_period = batch_period
        self.max_checkpoints = max_checkpoints
        self.current_epoch = 0
        self.current_batch = 0
        self.saved = []
        if mode == "auto" and monitor is not None:
            name = monitor.get()[0]
            mode = "max" if "acc" in name or "f1" in name else "min"
        self.mode = mode
        self.best = -_onp.inf if mode == "max" else _onp.inf

    def _save(self, estimator, tag, rotate=True):
        import os

        from ....resilience.checkpoint import _atomic_write

        from ....ndarray.utils import save_parameters_buffer

        path = os.path.join(self.model_dir, f"{self.model_prefix}-{tag}")
        # atomic per file (write-temp + fsync + rename): a crash mid-save
        # can't leave a torn .params behind. The two files can still be
        # from different saves after a crash between them — the
        # single-container ResilientCheckpointHandler is the crash-safe
        # upgrade; this keeps the reference's two-file layout readable.
        _atomic_write(path + ".params",
                      save_parameters_buffer(estimator.net._params_data()))
        if estimator.trainer is not None:
            _atomic_write(path + ".states",
                          estimator.trainer.states_to_bytes())
        if not rotate:
            return  # the 'best' checkpoint never enters the rotation
        self.saved.append(path)
        while len(self.saved) > self.max_checkpoints:
            old = self.saved.pop(0)
            for ext in (".params", ".states"):
                try:
                    os.remove(old + ext)
                except OSError:
                    pass

    def batch_end(self, estimator, *args, **kwargs):
        self.current_batch += 1
        if self.batch_period and self.current_batch % self.batch_period == 0:
            self._save(estimator, f"batch{self.current_batch}")

    def epoch_end(self, estimator, *args, **kwargs):
        self.current_epoch += 1
        if self.epoch_period and self.current_epoch % self.epoch_period == 0:
            self._save(estimator, f"epoch{self.current_epoch}")
        if self.save_best and self.monitor is not None:
            _, val = self.monitor.get()
            better = val > self.best if self.mode == "max" else val < self.best
            if better:
                self.best = val
                self._save(estimator, "best", rotate=False)


class EarlyStoppingHandler(TrainBegin, EpochEnd, TrainEnd):
    """Stop when the monitored metric stops improving (reference
    ``event_handler.py:598``)."""

    def __init__(self, monitor, min_delta=0, patience=0, mode="auto",
                 baseline=None):
        self.monitor = monitor
        self.min_delta = min_delta
        self.patience = patience
        self.wait = 0
        self.stopped_epoch = 0
        self.current_epoch = 0
        self.stop_training = False
        if mode == "auto":
            name = monitor.get()[0]
            mode = "max" if "acc" in name or "f1" in name else "min"
        self.mode = mode
        self.best = (baseline if baseline is not None
                     else (-_onp.inf if mode == "max" else _onp.inf))

    def epoch_end(self, estimator, *args, **kwargs):
        _, val = self.monitor.get()
        improved = (val > self.best + self.min_delta if self.mode == "max"
                    else val < self.best - self.min_delta)
        if improved:
            self.best = val
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.stopped_epoch = self.current_epoch
                self.stop_training = True
        self.current_epoch += 1

    def train_end(self, estimator, *args, **kwargs):
        if self.stopped_epoch > 0:
            logging.getLogger("mxnet_tpu.estimator").info(
                "Early stop at epoch %d", self.stopped_epoch)
