"""Estimator fit-loop (reference:
``python/mxnet/gluon/contrib/estimator/estimator.py``)."""
from __future__ import annotations

from ....base import MXNetError
from ... import Trainer, loss as gloss, metric as gmetric
from .batch_processor import BatchProcessor
from .event_handler import (BatchBegin, BatchEnd, EpochBegin, EpochEnd,
                            LoggingHandler, MetricHandler, PreStep,
                            StoppingHandler, TrainBegin, TrainEnd,
                            ValidationHandler)


class _LossMetric(gmetric.Loss):
    _is_loss_metric = True


class Estimator:
    """Compact fit abstraction: ``Estimator(net, loss, ...).fit(train_data,
    epochs=N)`` with composable event handlers."""

    def __init__(self, net, loss, train_metrics=None, val_metrics=None,
                 initializer=None, trainer=None, device=None, context=None,
                 batch_processor=None):
        self.net = net
        self.loss = loss
        self.device = device or context
        if batch_processor is not None \
                and not isinstance(batch_processor, BatchProcessor):
            raise MXNetError(
                "batch_processor must be a BatchProcessor instance")
        self.batch_processor = (batch_processor if batch_processor
                                is not None else BatchProcessor())
        if initializer is not None:
            net.initialize(init=initializer, ctx=self.device,
                           force_reinit=False)
        self.trainer = trainer or Trainer(
            net.collect_params(), "adam", {"learning_rate": 1e-3})
        self.train_metrics = train_metrics or [gmetric.Accuracy()]
        self.val_metrics = val_metrics or [
            type(m)() for m in self.train_metrics]
        self.train_loss_metric = _LossMetric(name="train_loss")
        self.val_loss_metric = _LossMetric(name="val_loss")

    def evaluate(self, val_data=None, **kwargs):
        if val_data is None:
            return
        for m in self.val_metrics:
            m.reset()
        self.val_loss_metric.reset()
        for batch in val_data:
            _data, label, pred, l = \
                self.batch_processor.evaluate_batch(self, batch)
            for m in self.val_metrics:
                m.update(label, pred)
            self.val_loss_metric.update(0, l)

    def fit(self, train_data, val_data=None, epochs=None, event_handlers=None,
            batches=None, batch_size=None):
        if epochs is None and batches is None:
            epochs = 1
        handlers = self._init_handlers(val_data, event_handlers,
                                       epochs, batches)
        train_begin, epoch_begin, batch_begin, pre_step, batch_end, \
            epoch_end, train_end = self._categorize(handlers)

        from ....profiler import attribution as _attr
        from ....profiler import trace as _trace

        # request-scoped tracing (MXNET_TRACE=1): the whole fit is one
        # trace whose train::step spans carry the global step id —
        # dist_tpu tags its collective events with the same id, so a
        # dumped trace correlates a slow step with its collectives
        fit_trace = _trace.start_trace(
            f"train.fit[{type(self.net).__name__}]")
        step_n = 0
        for h in train_begin:
            h.train_begin(self)
        stop = False
        while not stop:
            for h in epoch_begin:
                h.epoch_begin(self)
            for batch in train_data:
                if fit_trace is not None:
                    step_n += 1
                    _trace.set_step(step_n)
                # the train phase scope tags any engine:wait stall
                # inside the step as train-phase (the decode-phase
                # "near zero" query needs train waits filterable out)
                with _attr.phase_scope("train"), \
                        _trace.activate(fit_trace), \
                        _trace.span("train::step", {"step": step_n}):
                    for h in batch_begin:
                        h.batch_begin(self, batch=batch)
                    _data, label, pred, l = \
                        self.batch_processor.fit_batch(self, batch)
                    # pre-step vetting (numerical guardrails): any PreStep
                    # handler returning False vetoes the optimizer update
                    # for this batch — the weights never see it
                    step_ok = True
                    for h in pre_step:
                        if h.pre_step(self, batch=batch, loss=l) is False:
                            step_ok = False
                    if step_ok:
                        try:
                            self.trainer.step(1)
                        except MXNetError as e:
                            # e.g. the dist_tpu pre-collective NaN
                            # quarantine: a PreStep handler may absorb it
                            # as a skip-step
                            if not any(h.step_error(self, e)
                                       for h in pre_step):
                                raise
                    for h in batch_end:
                        h.batch_end(self, batch=batch, pred=pred,
                                    label=label, loss=l)
                stop = any(getattr(h, "stop_training", False)
                           for h in handlers)
                if stop:
                    break
            for h in epoch_end:
                h.epoch_end(self)
            stop = stop or any(getattr(h, "stop_training", False)
                               for h in handlers)
        for h in train_end:
            h.train_end(self)
        if fit_trace is not None:
            fit_trace.finish()

    def _init_handlers(self, val_data, event_handlers, epochs, batches):
        handlers = list(event_handlers or [])
        if not any(isinstance(h, StoppingHandler) for h in handlers):
            handlers.append(StoppingHandler(max_epoch=epochs,
                                            max_batch=batches))
        if not any(isinstance(h, MetricHandler) for h in handlers):
            handlers.append(MetricHandler(
                [self.train_loss_metric] + list(self.train_metrics)))
        if val_data is not None and not any(
                isinstance(h, ValidationHandler) for h in handlers):
            handlers.append(ValidationHandler(val_data, self.evaluate))
        if not any(isinstance(h, LoggingHandler) for h in handlers):
            handlers.append(LoggingHandler(
                metrics=[self.train_loss_metric] + list(self.train_metrics)))
        # ascending priority: metric/validation handlers (priority -1000)
        # must run before logging (priority +inf) sees their values
        handlers.sort(key=lambda h: getattr(h, "priority", 0))
        return handlers

    @staticmethod
    def _categorize(handlers):
        return ([h for h in handlers if isinstance(h, TrainBegin)],
                [h for h in handlers if isinstance(h, EpochBegin)],
                [h for h in handlers if isinstance(h, BatchBegin)],
                [h for h in handlers if isinstance(h, PreStep)],
                [h for h in handlers if isinstance(h, BatchEnd)],
                [h for h in handlers if isinstance(h, EpochEnd)],
                [h for h in handlers if isinstance(h, TrainEnd)])
