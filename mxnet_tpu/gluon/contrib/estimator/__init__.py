"""Estimator (reference: ``python/mxnet/gluon/contrib/estimator/``)."""
from .batch_processor import BatchProcessor
from .estimator import Estimator
from .event_handler import (
    BatchBegin,
    BatchEnd,
    CheckpointHandler,
    EarlyStoppingHandler,
    EpochBegin,
    EpochEnd,
    LoggingHandler,
    MetricHandler,
    PreStep,
    StoppingHandler,
    TrainBegin,
    TrainEnd,
    ValidationHandler,
)


def __getattr__(name):
    # lazy: resilience.checkpoint/guardrails subclass the event-handler
    # bases above, so an eager import here would be circular
    if name == "ResilientCheckpointHandler":
        from ....resilience.checkpoint import ResilientCheckpointHandler

        return ResilientCheckpointHandler
    if name == "GuardrailHandler":
        from ....resilience.guardrails import GuardrailHandler

        return GuardrailHandler
    raise AttributeError(
        f"module {__name__!r} has no attribute {name!r}")
