"""Estimator (reference: ``python/mxnet/gluon/contrib/estimator/``)."""
from .batch_processor import BatchProcessor
from .estimator import Estimator
from .event_handler import (
    BatchBegin,
    BatchEnd,
    CheckpointHandler,
    EarlyStoppingHandler,
    EpochBegin,
    EpochEnd,
    LoggingHandler,
    MetricHandler,
    StoppingHandler,
    TrainBegin,
    TrainEnd,
    ValidationHandler,
)
