"""Batch processor: the composable batch-level fit/evaluate override
point (reference
``python/mxnet/gluon/contrib/estimator/batch_processor.py``, 105 LoC).

Users subclass :class:`BatchProcessor` and override ``fit_batch`` /
``evaluate_batch`` to customize what happens per minibatch (custom loss
composition, multi-output nets, gradient surgery) without subclassing
``Estimator`` itself.

TPU redesign note: the reference's ``_get_data_and_label`` shards the
batch across a device list with ``split_and_load``; here a batch runs on
one logical device (data parallelism is the ShardedTrainer/pjit path, not
the fit loop), so the hook simply unpacks — overriding it still lets a
user reshape/cast/shard however they need.
"""
from __future__ import annotations

__all__ = ["BatchProcessor"]


class BatchProcessor:
    """Plug-and-play ``fit_batch`` & ``evaluate_batch`` for Estimator."""

    def _get_data_and_label(self, batch, ctx, batch_axis=0):  # pylint: disable=unused-argument
        data, label = batch[0], batch[1]
        return data, label

    def evaluate_batch(self, estimator, val_batch, batch_axis=0):
        """Evaluate on one validation batch.

        Returns ``(data, label, pred, loss)`` like the reference
        (``batch_processor.py:49-67``)."""
        from .... import autograd

        data, label = self._get_data_and_label(
            val_batch, estimator.device, batch_axis)
        with autograd.predict_mode():
            pred = estimator.net(data)
            loss = estimator.loss(pred, label)
        return data, label, pred, loss

    def fit_batch(self, estimator, train_batch, batch_axis=0):
        """Forward + backward on one training batch; the Estimator runs
        the optimizer step. Returns ``(data, label, pred, loss)``
        (reference ``batch_processor.py:69-105``)."""
        from .... import autograd

        data, label = self._get_data_and_label(
            train_batch, estimator.device, batch_axis)
        with autograd.record():
            pred = estimator.net(data)
            loss = estimator.loss(pred, label).mean()
            # backward through the trainer's loss scaler (identity when no
            # scaler is attached): step() unscales, so skipping the scale
            # here would silently divide every update by loss_scale
            scale = getattr(estimator.trainer, "scale_loss", None)
            scaled = loss if scale is None else scale(loss)
        scaled.backward()
        # grads exist NOW: evaluate the trainer:grad fault site here so an
        # injected 'nan' is visible to the pre-step guardrail sentinels
        # (inside step() it would corrupt after the veto point)
        check = getattr(estimator.trainer, "check_grad_faults", None)
        if check is not None:
            check()
        return data, label, pred, loss
