"""``gluon.contrib.nn`` (reference
``python/mxnet/gluon/contrib/nn/basic_layers.py``): Concurrent branches,
Identity, SparseEmbedding, PixelShuffle upsamplers, SyncBatchNorm.
"""
from __future__ import annotations

from ...base import MXNetError
from .. import nn as _nn
from ..block import HybridBlock
from ..nn import HybridSequential


class HybridConcurrent(HybridSequential):
    """Apply every child to the SAME input and concatenate the outputs
    along ``axis`` (reference contrib HybridConcurrent — the Inception
    branch combinator)."""

    def __init__(self, axis=-1, **kwargs):
        super().__init__(**kwargs)
        self.axis = axis

    def forward(self, x):
        from ... import np as mnp

        outs = [child(x) for child in self._children.values()]
        return mnp.concatenate(outs, axis=self.axis)


class Concurrent(HybridConcurrent):
    """Alias (the reference keeps both imperative/hybrid names)."""


class Identity(HybridBlock):
    def forward(self, x):
        return x


class SparseEmbedding(_nn.Embedding):
    """Embedding whose weight gradient is row_sparse (reference contrib
    SparseEmbedding); on this stack that is ``Embedding(sparse_grad=True)``
    — the O(nnz) gradient/update path in ndarray/sparse.py."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, **kwargs):
        super().__init__(input_dim, output_dim, dtype=dtype,
                         weight_initializer=weight_initializer,
                         sparse_grad=True, **kwargs)


def _pixel_shuffle(x, factors, ndim):
    import jax.numpy as jnp

    from ...ops.registry import apply as _apply

    if isinstance(factors, int):
        factors = (factors,) * ndim
    f = tuple(int(v) for v in factors)

    def fn(a):
        b, c = a.shape[0], a.shape[1]
        spatial = a.shape[2:]
        import numpy as _np

        cw = c // int(_np.prod(f))
        # (B, C', f1..fn, s1..sn) -> interleave f_i after s_i
        a = a.reshape((b, cw) + f + spatial)
        perm = [0, 1]
        for i in range(ndim):
            perm += [2 + ndim + i, 2 + i]
        a = a.transpose(perm)
        out_sp = tuple(s * fi for s, fi in zip(spatial, f))
        return a.reshape((b, cw) + out_sp)

    return _apply(fn, (x,), name="pixel_shuffle")


class PixelShuffle1D(HybridBlock):
    """(B, C·f, W) → (B, C, W·f) sub-pixel upsampling (reference contrib
    PixelShuffle1D)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = factor

    def forward(self, x):
        return _pixel_shuffle(x, self._factor, 1)


class PixelShuffle2D(HybridBlock):
    """(B, C·f1·f2, H, W) → (B, C, H·f1, W·f2)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = factor

    def forward(self, x):
        return _pixel_shuffle(x, self._factor, 2)


class PixelShuffle3D(HybridBlock):
    """(B, C·f1·f2·f3, D, H, W) → (B, C, D·f1, H·f2, W·f3)."""

    def __init__(self, factor, **kwargs):
        super().__init__(**kwargs)
        self._factor = factor

    def forward(self, x):
        return _pixel_shuffle(x, self._factor, 3)


SyncBatchNorm = _nn.SyncBatchNorm

__all__ = ["Concurrent", "HybridConcurrent", "Identity", "SparseEmbedding",
           "PixelShuffle1D", "PixelShuffle2D", "PixelShuffle3D",
           "SyncBatchNorm"]
