"""Gluon Block / HybridBlock / SymbolBlock.

Reference: ``python/mxnet/gluon/block.py`` (1,776 LoC): parameter
registration via ``__setattr__``, forward hooks, ``hybridize()`` building a
CachedOp from a deferred-compute trace (``_build_cache:994-1085``), ``export``
(``:1300``) and ``SymbolBlock.imports`` (``:1500``).

TPU redesign: ``hybridize`` swaps the call path to
:class:`mxnet_tpu.cachedop.CachedOp` — jax tracing of ``forward`` compiled to
one XLA executable per input signature (SURVEY.md §3.2 mapping). ``export``
serializes the traced computation with ``jax.export`` (StableHLO) plus a
parameter archive, and ``SymbolBlock.imports`` reloads it without the Python
definition — the role of ``model-symbol.json`` + ``model-0000.params``.
"""
from __future__ import annotations

import json
import re
from collections import OrderedDict

from .. import autograd
from ..base import MXNetError
from ..cachedop import CachedOp, in_trace
from ..device import Context, cpu, current_context
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict


class Block:
    """Base class for all neural-network layers and models."""

    def __init__(self):
        self._children = OrderedDict()
        self._reg_params = OrderedDict()
        self._forward_hooks = OrderedDict()
        self._forward_pre_hooks = OrderedDict()
        self._hook_id = 0

    # -- attribute registration ------------------------------------------
    def __setattr__(self, name, value):
        if isinstance(value, Block):
            existing = self.__dict__.get("_children")
            if existing is not None:
                existing[name] = value
        elif isinstance(value, Parameter):
            reg = self.__dict__.get("_reg_params")
            if reg is not None:
                reg[name] = value
                value._structure = (self, name)
        super().__setattr__(name, value)

    def register_child(self, block, name=None):
        if name is None:
            name = str(len(self._children))
        self._children[name] = block
        super().__setattr__(f"_child_{name}", block)
        return block

    def register_forward_hook(self, hook):
        self._hook_id += 1
        self._forward_hooks[self._hook_id] = hook
        return _HookHandle(self._forward_hooks, self._hook_id)

    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return _HookHandle(self._forward_pre_hooks, self._hook_id)

    def register_op_hook(self, callback, monitor_all=False):  # pragma: no cover
        raise NotImplementedError(
            "per-op monitoring inside compiled graphs is exposed via "
            "mxnet_tpu.profiler instead")

    # -- parameter access -------------------------------------------------
    @property
    def params(self):
        return ParameterDict(self._reg_params)

    def collect_params(self, select=None) -> ParameterDict:
        out = ParameterDict()
        self._collect_params(out, prefix="")
        if select is not None:
            pat = re.compile(select)
            out = ParameterDict(
                (k, v) for k, v in out.items() if pat.search(k))
        return out

    def _collect_params(self, out, prefix):
        for name, p in self._reg_params.items():
            out[prefix + name] = p
        for cname, child in self._children.items():
            child._collect_params(out, prefix + cname + ".")

    def initialize(self, init=None, device=None, ctx=None, verbose=False,
                   force_reinit=False):  # pylint: disable=unused-argument
        self.collect_params().initialize(init=init, ctx=ctx or device,
                                         force_reinit=force_reinit)
        return self

    def setattr(self, name, value):
        for p in self.collect_params().values():
            setattr(p, name, value)

    def apply(self, fn):
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def cast(self, dtype):
        for child in self._children.values():
            child.cast(dtype)
        for p in self._reg_params.values():
            p.cast(dtype)
        return self

    def zero_grad(self):
        self.collect_params().zero_grad()

    def reset_ctx(self, ctx):
        self.collect_params().reset_ctx(ctx)

    reset_device = reset_ctx

    # -- persistence ------------------------------------------------------
    def _params_data(self):
        """name -> NDArray dict of every parameter's current buffer — THE
        serialization view of this block, shared by save_parameters, the
        estimator CheckpointHandler and resilience.checkpoint so the three
        on-disk params payloads can never diverge."""
        return {k: v.data() for k, v in self.collect_params().items()}

    def save_parameters(self, filename, deduplicate=False):  # pylint: disable=unused-argument
        from ..ndarray.utils import save

        save(filename, self._params_data())

    def load_parameters(self, filename, device=None, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False, dtype_source="current"):  # pylint: disable=unused-argument
        from ..ndarray.utils import load

        loaded = load(filename)
        params = self.collect_params()
        for name, p in params.items():
            if name in loaded:
                p.set_data(loaded[name])
            elif not allow_missing:
                raise MXNetError(f"parameter {name!r} missing in {filename}")
        if not ignore_extra:
            extra = set(loaded) - set(params)
            if extra:
                raise MXNetError(f"{filename} contains extra params {sorted(extra)}")

    save = save_parameters
    load = load_parameters

    def share_parameters(self, shared: dict):
        params = self.collect_params()
        for name, p in shared.items():
            if name in params:
                holder, attr = params[name]._structure or (None, None)
                if holder is not None:
                    setattr(holder, attr, p)
        return self

    # -- call -------------------------------------------------------------
    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            hook(self, args)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_hooks.values():
            hook(self, args, out)
        return out

    def forward(self, *args):
        raise NotImplementedError

    def hybridize(self, active=True, **kwargs):
        """Recursively activate compiled execution on HybridBlock children."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    def summary(self, *inputs):
        """Print a per-layer summary by running a forward with hooks."""
        rows = []

        def add_hooks(block, prefix):
            def hook(b, _in, out):
                shape = out.shape if isinstance(out, NDArray) else "-"
                nparam = sum(
                    int(p.data().size) for p in b._reg_params.values()
                    if p._data is not None)
                rows.append((prefix or type(b).__name__, type(b).__name__,
                             shape, nparam))
            handles.append(block.register_forward_hook(hook))
            for name, c in block._children.items():
                add_hooks(c, f"{prefix}.{name}" if prefix else name)

        handles = []
        add_hooks(self, "")
        try:
            with autograd.predict_mode():
                self(*inputs)
        finally:
            for h in handles:
                h.detach()
        header = f"{'Layer':<40}{'Type':<20}{'Output':<24}{'Params':<12}"
        lines = [header, "-" * len(header)]
        for name, typ, shape, nparam in rows:
            lines.append(f"{name:<40}{typ:<20}{str(shape):<24}{nparam:<12}")
        print("\n".join(lines))
        return "\n".join(lines)

    def __repr__(self):
        lines = [type(self).__name__ + "("]
        for name, child in self._children.items():
            child_repr = repr(child).replace("\n", "\n  ")
            lines.append(f"  ({name}): {child_repr}")
        lines.append(")")
        return "\n".join(lines) if len(lines) > 2 else type(self).__name__ + "()"


class _HookHandle:
    def __init__(self, table, hid):
        self._table = table
        self._hid = hid

    def detach(self):
        self._table.pop(self._hid, None)


class HybridBlock(Block):
    """Block that can be compiled to a single XLA executable per signature."""

    def __init__(self):
        super().__init__()
        self._active = False
        self._cached_op = None
        self._flags = {}

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):  # pylint: disable=unused-argument
        self._active = active
        self._flags = dict(static_alloc=static_alloc, static_shape=static_shape)
        self._cached_op = None
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def infer_shape(self, *args):
        """Finalize deferred parameter shapes from example inputs.

        The reference runs symbolic shape inference; here layers resolve
        their own shapes at first forward, so a single paused eager forward
        is the inference pass.
        """
        with autograd.pause():
            self.forward(*args)

    def optimize_for(self, x, *args, backend=None, clear=True, partition_if_dynamic=True,
                     static_alloc=False, static_shape=False, **kwargs):
        """Reference ``optimize_for`` (subgraph backend partition + build,
        ``subgraph_property.h:86-385`` / ``MXOptimizeForBackend``).

        TPU redesign: a backend is a named bundle of function-transform
        passes from :mod:`mxnet_tpu.subgraph` (``remat``, ``bf16``, or
        user-registered via ``subgraph.register_backend``). The passes wrap
        the traced forward before jit; then one warm-up call builds the
        executable.
        """
        del partition_if_dynamic, kwargs
        changed = False
        if clear and getattr(self, "_graph_passes", None):
            # reference semantics: clear=True drops prior backend state
            # even when no new backend is given
            self._graph_passes = []
            changed = True
        if backend is not None:
            from ..subgraph import get_backend_passes

            passes = get_backend_passes(backend)  # validate + fetch
            self._graph_passes = list(
                getattr(self, "_graph_passes", ()) or ()) + passes
            changed = True
        if changed and getattr(self, "_cached_op", None) is not None:
            self._cached_op = None  # rebuild with the new pass set
        self.hybridize(True, static_alloc=static_alloc, static_shape=static_shape)
        self(x, *args)

    # -- export -----------------------------------------------------------
    def export(self, path, epoch=0, remove_amp_cast=True):  # pylint: disable=unused-argument
        """Serialize compiled graph + params: ``path-symbol.mxir`` +
        ``path-%04d.params`` (reference writes symbol.json + params)."""
        import jax
        import jax.export as jexport

        if not getattr(self, "_example_args", None):
            raise MXNetError(
                "export requires at least one forward call (to fix the input "
                "signature) before exporting")
        args = self._example_args
        params = self.collect_params()
        names = list(params)
        datas = [params[n].data()._data for n in names]

        def fn(param_datas, *arg_datas):
            from ..cachedop import _ParamBinding

            arrays = [params[n].data() for n in names]
            wrapped = [NDArray(a) for a in arg_datas]
            with _ParamBinding(arrays, list(param_datas)):
                prev = autograd.set_recording(False)
                try:
                    out = self.forward(*wrapped)
                finally:
                    autograd.set_recording(prev)
            flat, _ = jax.tree_util.tree_flatten(
                out, is_leaf=lambda x: isinstance(x, NDArray))
            return [o._data for o in flat]

        exported = jexport.export(jax.jit(fn))(
            tuple(datas), *[a._data for a in args])
        blob = exported.serialize()
        with open(f"{path}-symbol.mxir", "wb") as f:
            f.write(blob)
        from ..ndarray.utils import save

        save(f"{path}-{epoch:04d}.params", {n: params[n].data() for n in names})
        meta = {
            "format": "mxnet_tpu-export-v1",
            "param_names": names,
            "input_sig": [(list(a.shape), str(a.dtype)) for a in args],
        }
        with open(f"{path}-meta.json", "w") as f:
            json.dump(meta, f)
        return f"{path}-symbol.mxir", f"{path}-{epoch:04d}.params"

    def forward(self, *args):
        # Gluon-v1 compatibility (reference block.py:574 "v1 style"):
        # subclasses that define hybrid_forward(self, F, x, <param>...)
        # get it called with F = the legacy nd op namespace (which works
        # identically eager and under trace — tracing lives inside
        # NDArray) and this block's registered Parameters passed by name,
        # the reference's weight-forwarding convention.
        hf = getattr(type(self), "hybrid_forward", None)
        if hf is not None:
            from ..gluon.parameter import DeferredInitializationError
            from .. import ndarray as F

            try:
                params = {n: p.data() for n, p in self._reg_params.items()}
            except DeferredInitializationError:
                # deferred-shape params: the reference 2.x contract
                # (gluon/block.py _deferred_infer_shape) — the block's
                # infer_shape(*args) sets param shapes from the inputs,
                # then init completes and the forward retries
                infer = getattr(type(self), "infer_shape", None)
                if infer is None or infer is HybridBlock.infer_shape:
                    # the base infer_shape runs a paused forward — for a
                    # hybrid_forward block that recurses right back here
                    raise MXNetError(
                        f"{type(self).__name__} has deferred-shape "
                        "parameters; implement infer_shape(self, *args) "
                        "to derive them from the inputs, or construct "
                        "the Parameters with complete shapes") from None
                infer(self, *args)
                for p in self._reg_params.values():
                    p._finish_deferred_init()
                params = {n: p.data() for n, p in self._reg_params.items()}
            return hf(self, F, *args, **params)
        raise NotImplementedError(
            f"{type(self).__name__} defines neither forward() nor the "
            "legacy hybrid_forward()")

    def __call__(self, *args, **kwargs):  # noqa: F811 - final definition above
        # remember example args for export
        if args and all(isinstance(a, NDArray) for a in args):
            self._example_args = args
        if self._active and not in_trace() and not kwargs:
            params = self.collect_params().values()
            if all(p._data is not None for p in params):
                for hook in self._forward_pre_hooks.values():
                    hook(self, args)
                if self._cached_op is None:
                    self._cached_op = CachedOp(self, **self._flags)
                out = self._cached_op(*args)
                for hook in self._forward_hooks.values():
                    hook(self, args, out)
                return out
        return Block.__call__(self, *args, **kwargs)


def _register_param_arrays(block, param_arrays):
    """Bind a name->NDArray dict as initialized Parameters p0..pN on a
    block (shared by SymbolBlock and _LegacySymbolBlock)."""
    out = {}
    for i, (name, arr) in enumerate(param_arrays.items()):
        p = Parameter(name=name, shape=arr.shape, dtype=arr.dtype)
        p.initialize(init="zeros", ctx=getattr(arr, "ctx", None))
        p.set_data(arr)
        block._reg_params[f"p{i}"] = p
        object.__setattr__(block, f"p{i}", p)
        out[name] = p
    return out


class SymbolBlock(Block):
    """Runs a previously exported compiled graph (reference SymbolBlock)."""

    def __init__(self, exported, param_arrays, input_sig):
        super().__init__()
        self._exported = exported
        self._param_names = list(param_arrays)
        _register_param_arrays(self, param_arrays)
        self._input_sig = input_sig

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None, ctx=None,
                allow_missing=False, ignore_extra=False):  # pylint: disable=unused-argument
        import jax.export as jexport

        from ..ndarray.utils import load

        if str(symbol_file).endswith(".json"):
            # REFERENCE artifact pair (model-symbol.json +
            # model-0000.params): replay the nnvm graph through the
            # legacy Symbol DAG (symbol.fromjson upgrade path) with the
            # arg:/aux:-prefixed reference checkpoint bound as params
            return _LegacySymbolBlock.imports(symbol_file, input_names,
                                              param_file)

        with open(symbol_file, "rb") as f:
            exported = jexport.deserialize(f.read())
        meta_file = symbol_file.replace("-symbol.mxir", "-meta.json")
        with open(meta_file) as f:
            meta = json.load(f)
        params = load(param_file) if param_file else {}
        ordered = OrderedDict((n, params[n]) for n in meta["param_names"])
        return SymbolBlock(exported, ordered, meta["input_sig"])

    def forward(self, *args):
        datas = tuple(
            self._reg_params[f"p{i}"].data()._data
            for i in range(len(self._param_names)))
        arg_datas = [a._data if isinstance(a, NDArray) else a for a in args]
        outs = self._exported.call(datas, *arg_datas)
        wrapped = [NDArray(o) for o in outs]
        return wrapped[0] if len(wrapped) == 1 else tuple(wrapped)


class _LegacySymbolBlock(Block):
    """SymbolBlock over a REFERENCE model-symbol.json: replays the nnvm
    graph through the legacy Symbol DAG. The reference loads such pairs
    via ``SymbolBlock.imports`` (gluon/block.py:1500 there); this is the
    same user contract on the TPU build's replay executor."""

    def __init__(self, sym, params, input_names):
        super().__init__()
        self._sym = sym
        self._input_names = list(input_names)
        self._sym_params = _register_param_arrays(self, params)

    @staticmethod
    def imports(symbol_file, input_names=None, param_file=None):
        from .. import symbol as sym_mod
        from ..ndarray.utils import load

        sym = sym_mod.load(symbol_file)
        raw = load(param_file) if param_file else {}
        if isinstance(raw, list):
            raise MXNetError(
                "reference param file has no names; save with keys "
                "(arg:<name>/aux:<name>) to bind into a SymbolBlock")
        # reference checkpoints prefix arg:/aux: (ndarray.cc Save via
        # mx.model save_checkpoint); strip to the graph's variable names
        params = {k.split(":", 1)[1] if k.startswith(("arg:", "aux:"))
                  else k: v for k, v in raw.items()}
        if input_names is None:
            input_names = ["data"]
        input_names = [str(n) for n in (
            input_names if isinstance(input_names, (list, tuple))
            else [input_names])]
        free = [n for n in sym.list_arguments()
                if n not in params and n not in input_names]
        if free:
            raise MXNetError(
                f"symbol arguments {free} have no parameter in "
                f"{param_file!r} and are not inputs {input_names}")
        return _LegacySymbolBlock(sym, params, input_names)

    def forward(self, *args):
        bindings = {n: p.data() for n, p in self._sym_params.items()}
        for name, arr in zip(self._input_names, args):
            bindings[name] = arr
        return self._sym._eval_with(bindings)
