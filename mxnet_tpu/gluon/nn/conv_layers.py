"""Convolution / pooling Gluon layers.

Reference: ``python/mxnet/gluon/nn/conv_layers.py`` over
``src/operator/nn/{convolution,deconvolution,pooling}.cc``. NCHW-family
layouts at the API; XLA picks internal layouts for the MXU.
"""
from __future__ import annotations

from ...base import MXNetError
from ...ops import nn as _nn
from ..block import HybridBlock
from ..parameter import Parameter


def _pair(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(v)


class _Conv(HybridBlock):
    def __init__(self, channels, kernel_size, strides, padding, dilation,
                 groups, layout, in_channels=0, activation=None, use_bias=True,
                 weight_initializer=None, bias_initializer="zeros",
                 op_name="convolution", adj=None, dtype="float32", **kwargs):
        super().__init__(**kwargs)
        ndim = len(kernel_size) if isinstance(kernel_size, (tuple, list)) else None
        self._channels = channels
        self._in_channels = in_channels
        self._kernel = kernel_size
        self._strides = strides
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._layout = layout
        self._act_type = activation
        self._op_name = op_name
        self._adj = adj
        ndim = len(self._kernel)
        if op_name == "convolution":
            wshape = (channels, in_channels // groups if in_channels else 0) + tuple(self._kernel)
        else:  # deconvolution weight is (in, out//groups, *k)
            wshape = (in_channels if in_channels else 0, channels // groups) + tuple(self._kernel)
        self.weight = Parameter("weight", shape=wshape, dtype=dtype,
                                init=weight_initializer)
        self.bias = (Parameter("bias", shape=(channels,), dtype=dtype,
                               init=bias_initializer) if use_bias else None)

    def forward(self, x):
        if 0 in self.weight.shape:
            cin = x.shape[1]
            if self._op_name == "convolution":
                self.weight.shape = (self._channels, cin // self._groups) + tuple(self._kernel)
            else:
                self.weight.shape = (cin, self._channels // self._groups) + tuple(self._kernel)
        bias = self.bias.data() if self.bias is not None else None
        if self._op_name == "convolution":
            out = _nn.convolution(
                x, self.weight.data(), bias, kernel=self._kernel,
                stride=self._strides, dilate=self._dilation, pad=self._padding,
                num_filter=self._channels, num_group=self._groups,
                no_bias=bias is None, layout=self._layout)
        else:
            out = _nn.deconvolution(
                x, self.weight.data(), bias, kernel=self._kernel,
                stride=self._strides, dilate=self._dilation, pad=self._padding,
                adj=self._adj, num_filter=self._channels,
                num_group=self._groups, no_bias=bias is None,
                layout=self._layout)
        if self._act_type:
            out = _nn.activation(out, self._act_type)
        return out

    def __repr__(self):
        return (f"{type(self).__name__}({self._channels}, kernel={self._kernel}, "
                f"stride={self._strides})")


class Conv1D(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0, dilation=1,
                 groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups, layout,
                         **kwargs)


class Conv2D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 dilation=(1, 1), groups=1, layout="NCHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups, layout,
                         **kwargs)


class Conv3D(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), dilation=(1, 1, 1), groups=1,
                 layout="NCDHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups, layout,
                         **kwargs)


class Conv1DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=1, padding=0,
                 output_padding=0, dilation=1, groups=1, layout="NCW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 1), _pair(strides, 1),
                         _pair(padding, 1), _pair(dilation, 1), groups, layout,
                         op_name="deconvolution", adj=_pair(output_padding, 1),
                         **kwargs)


class Conv2DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1), padding=(0, 0),
                 output_padding=(0, 0), dilation=(1, 1), groups=1,
                 layout="NCHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 2), _pair(strides, 2),
                         _pair(padding, 2), _pair(dilation, 2), groups, layout,
                         op_name="deconvolution", adj=_pair(output_padding, 2),
                         **kwargs)


class Conv3DTranspose(_Conv):
    def __init__(self, channels, kernel_size, strides=(1, 1, 1),
                 padding=(0, 0, 0), output_padding=(0, 0, 0),
                 dilation=(1, 1, 1), groups=1, layout="NCDHW", **kwargs):
        super().__init__(channels, _pair(kernel_size, 3), _pair(strides, 3),
                         _pair(padding, 3), _pair(dilation, 3), groups, layout,
                         op_name="deconvolution", adj=_pair(output_padding, 3),
                         **kwargs)


class _Pooling(HybridBlock):
    def __init__(self, pool_size, strides, padding, ceil_mode=False,
                 global_pool=False, pool_type="max", layout="NCHW",
                 count_include_pad=True, **kwargs):
        super().__init__(**kwargs)
        self._kernel = pool_size
        self._strides = strides if strides is not None else pool_size
        self._padding = padding
        self._ceil = ceil_mode
        self._global = global_pool
        self._pool_type = pool_type
        self._count_include_pad = count_include_pad

    def forward(self, x):
        return _nn.pooling(
            x, kernel=self._kernel, pool_type=self._pool_type,
            global_pool=self._global, stride=self._strides, pad=self._padding,
            pooling_convention="full" if self._ceil else "valid",
            count_include_pad=self._count_include_pad)

    def __repr__(self):
        return (f"{type(self).__name__}(size={self._kernel}, "
                f"stride={self._strides}, padding={self._padding})")


class MaxPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 1), _pair(strides, 1) if strides is not None else None,
                         _pair(padding, 1), ceil_mode, pool_type="max", **kwargs)


class MaxPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0, layout="NCHW",
                 ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 2), _pair(strides, 2) if strides is not None else None,
                         _pair(padding, 2), ceil_mode, pool_type="max", **kwargs)


class MaxPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, **kwargs):
        super().__init__(_pair(pool_size, 3), _pair(strides, 3) if strides is not None else None,
                         _pair(padding, 3), ceil_mode, pool_type="max", **kwargs)


class AvgPool1D(_Pooling):
    def __init__(self, pool_size=2, strides=None, padding=0, layout="NCW",
                 ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 1), _pair(strides, 1) if strides is not None else None,
                         _pair(padding, 1), ceil_mode, pool_type="avg",
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool2D(_Pooling):
    def __init__(self, pool_size=(2, 2), strides=None, padding=0,
                 layout="NCHW", ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 2), _pair(strides, 2) if strides is not None else None,
                         _pair(padding, 2), ceil_mode, pool_type="avg",
                         count_include_pad=count_include_pad, **kwargs)


class AvgPool3D(_Pooling):
    def __init__(self, pool_size=(2, 2, 2), strides=None, padding=0,
                 layout="NCDHW", ceil_mode=False, count_include_pad=True, **kwargs):
        super().__init__(_pair(pool_size, 3), _pair(strides, 3) if strides is not None else None,
                         _pair(padding, 3), ceil_mode, pool_type="avg",
                         count_include_pad=count_include_pad, **kwargs)


class GlobalMaxPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), global_pool=True, pool_type="max",
                         **kwargs)


class GlobalMaxPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), global_pool=True,
                         pool_type="max", **kwargs)


class GlobalMaxPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), global_pool=True,
                         pool_type="max", **kwargs)


class GlobalAvgPool1D(_Pooling):
    def __init__(self, layout="NCW", **kwargs):
        super().__init__((1,), None, (0,), global_pool=True, pool_type="avg",
                         **kwargs)


class GlobalAvgPool2D(_Pooling):
    def __init__(self, layout="NCHW", **kwargs):
        super().__init__((1, 1), None, (0, 0), global_pool=True,
                         pool_type="avg", **kwargs)


class GlobalAvgPool3D(_Pooling):
    def __init__(self, layout="NCDHW", **kwargs):
        super().__init__((1, 1, 1), None, (0, 0, 0), global_pool=True,
                         pool_type="avg", **kwargs)


class ReflectionPad2D(HybridBlock):
    def __init__(self, padding=0, **kwargs):
        super().__init__(**kwargs)
        self._padding = _pair(padding, 2)

    def forward(self, x):
        from ... import numpy as _np

        p = self._padding
        return _np.pad(x, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])),
                       mode="reflect")
