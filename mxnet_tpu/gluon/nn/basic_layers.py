"""Basic Gluon layers (reference ``python/mxnet/gluon/nn/basic_layers.py``).

Dense/Dropout/BatchNorm/LayerNorm/GroupNorm/InstanceNorm/Embedding/Flatten/
activations + Sequential containers. Layers resolve deferred input-dim
shapes at first forward (the reference's deferred-init + shape-inference
flow) and lower to the ``npx`` op family.
"""
from __future__ import annotations

from ... import autograd
from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from ...ops import nn as _nn
from ..block import Block, HybridBlock
from ..parameter import Parameter


class Sequential(Block):
    """Stack of Blocks run sequentially."""

    def __init__(self, *blocks):
        super().__init__()
        for b in blocks:
            self.add(b)

    def add(self, *blocks):
        for b in blocks:
            self.register_child(b)
        return self

    def forward(self, x, *args):
        for block in self._children.values():
            x = block(x, *args)
            args = ()
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            out = type(self)()
            for b in list(self._children.values())[idx]:
                out.add(b)
            return out
        return list(self._children.values())[idx]

    def __iter__(self):
        return iter(self._children.values())


class HybridSequential(HybridBlock, Sequential):
    """Hybridizable Sequential."""

    def __init__(self, *blocks):
        HybridBlock.__init__(self)
        for b in blocks:
            self.add(b)

    forward = Sequential.forward
    add = Sequential.add
    __len__ = Sequential.__len__
    __getitem__ = Sequential.__getitem__
    __iter__ = Sequential.__iter__


class Dense(HybridBlock):
    """Fully-connected layer: ``activation(dot(x, W^T) + b)``.

    Reference ``gluon/nn/basic_layers.py`` Dense → ``npx.fully_connected``
    (kernel ``src/operator/nn/fully_connected.cc``).
    """

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None, bias_initializer="zeros",
                 in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._units = units
        self._flatten = flatten
        self._act_type = activation
        self.weight = Parameter("weight", shape=(units, in_units), dtype=dtype,
                                init=weight_initializer)
        self.bias = (
            Parameter("bias", shape=(units,), dtype=dtype, init=bias_initializer)
            if use_bias else None
        )

    def forward(self, x):
        if self.weight.shape[1] == 0:
            in_units = (
                int(x.size // x.shape[0]) if self._flatten else int(x.shape[-1]))
            self.weight.shape = (self._units, in_units)
        out = _nn.fully_connected(
            x, self.weight.data(), self.bias.data() if self.bias is not None else None,
            num_hidden=self._units, no_bias=self.bias is None,
            flatten=self._flatten)
        if self._act_type:
            out = _nn.activation(out, self._act_type)
        return out

    def __repr__(self):
        return (f"Dense({self._units}"
                f"{', ' + self._act_type if self._act_type else ''})")


class Dropout(HybridBlock):
    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def forward(self, x):
        if not autograd.is_training() or self._rate <= 0:
            return x
        return _nn.dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"Dropout(p={self._rate})"


class Activation(HybridBlock):
    def __init__(self, activation, **kwargs):
        super().__init__(**kwargs)
        self._act_type = activation

    def forward(self, x):
        return _nn.activation(x, self._act_type)

    def __repr__(self):
        return f"Activation({self._act_type})"


class LeakyReLU(HybridBlock):
    def __init__(self, alpha=0.01, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return _nn.leaky_relu(x, act_type="leaky", slope=self._alpha)


class ELU(HybridBlock):
    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def forward(self, x):
        return _nn.leaky_relu(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    def forward(self, x):
        return _nn.leaky_relu(x, act_type="selu")


class GELU(HybridBlock):
    def __init__(self, approximation="erf", **kwargs):
        super().__init__(**kwargs)
        self._approx = approximation

    def forward(self, x):
        return _nn.activation(
            x, "gelu_tanh" if self._approx == "tanh" else "erf_gelu")


class SiLU(HybridBlock):
    def forward(self, x):
        return _nn.activation(x, "silu")


class Swish(HybridBlock):
    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def forward(self, x):
        if self._beta == 1.0:
            return _nn.activation(x, "silu")
        return x * _nn.sigmoid(self._beta * x)


class PReLU(HybridBlock):
    def __init__(self, alpha_initializer=None, in_channels=1, **kwargs):
        super().__init__(**kwargs)
        from ...initializer import Constant

        self.alpha = Parameter("alpha", shape=(in_channels,),
                               init=alpha_initializer or Constant(0.25))

    def forward(self, x):
        return _nn.leaky_relu(x, gamma=self.alpha.data(), act_type="prelu")


class Flatten(HybridBlock):
    def forward(self, x):
        return x.reshape((x.shape[0], -1))

    def __repr__(self):
        return "Flatten"


class Identity(HybridBlock):
    def forward(self, x):
        return x


class Lambda(Block):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        self._func = function

    def forward(self, *args):
        return self._func(*args)


class HybridLambda(HybridBlock):
    def __init__(self, function, **kwargs):
        super().__init__(**kwargs)
        if isinstance(function, str):
            from ... import numpy as _np
            from ... import numpy_extension as _npx

            fn = getattr(_npx, function, None) or getattr(_np, function)
            self._func = fn
        else:
            self._func = function

    def forward(self, *args):
        return self._func(*args)


class Embedding(HybridBlock):
    """Index → vector lookup (reference Embedding; sparse_grad supported as
    dense-on-TPU with row-sparse conversion available on the grad)."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._sparse_grad = sparse_grad
        self.weight = Parameter(
            "weight", shape=(input_dim, output_dim), dtype=dtype,
            init=weight_initializer,
            grad_stype="row_sparse" if sparse_grad else "default")

    def forward(self, x):
        return _nn.embedding(x, self.weight.data(), input_dim=self._input_dim,
                             output_dim=self._output_dim,
                             sparse_grad=self._sparse_grad)

    def __repr__(self):
        return f"Embedding({self._input_dim} -> {self._output_dim})"


class _NormBase(HybridBlock):
    pass


class BatchNorm(_NormBase):
    """Batch normalization with running-stat state.

    State update happens functionally: in training the op returns batch
    stats; the layer folds them into ``running_*`` parameters under
    ``autograd.pause`` (the reference mutates aux states inside the CUDA
    kernel, ``src/operator/nn/batch_norm.cc``). Inside a hybridized trace
    the rebound state values become extra executable outputs (see CachedOp).
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._momentum = momentum
        self._eps = epsilon
        self._center = center
        self._scale = scale
        self._use_global_stats = use_global_stats
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              differentiable=center)
        self.running_mean = Parameter("running_mean", shape=shape,
                                      init=running_mean_initializer,
                                      differentiable=False)
        self.running_var = Parameter("running_var", shape=shape,
                                     init=running_variance_initializer,
                                     differentiable=False)

    def _finalize(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            if p.shape[0] == 0:
                p.shape = (c,)

    def forward(self, x):
        self._finalize(x)
        training = autograd.is_training() and not self._use_global_stats
        if training:
            out, mean, var = _nn.batch_norm(
                x, self.gamma.data(), self.beta.data(),
                self.running_mean.data(), self.running_var.data(),
                eps=self._eps, momentum=self._momentum,
                fix_gamma=not self._scale, output_mean_var=True,
                axis=self._axis)
            m = self._momentum
            with autograd.pause():
                rm = self.running_mean.data()
                rv = self.running_var.data()
                n = x.size / x.shape[self._axis]
                unbiased = var.detach() * (n / max(n - 1, 1))
                new_rm = m * rm + (1 - m) * mean.detach()
                new_rv = m * rv + (1 - m) * unbiased
                rm._set_data_internal(new_rm._data)
                rv._set_data_internal(new_rv._data)
            return out
        return _nn.batch_norm(
            x, self.gamma.data(), self.beta.data(),
            self.running_mean.data(), self.running_var.data(),
            eps=self._eps, momentum=self._momentum,
            fix_gamma=not self._scale, use_global_stats=True,
            axis=self._axis)

    def __repr__(self):
        return f"BatchNorm(axis={self._axis}, momentum={self._momentum})"


class SyncBatchNorm(BatchNorm):
    """Cross-device BatchNorm.

    On TPU, batch stats are computed over the *global* batch automatically
    when the batch axis is sharded over the mesh and the reduction runs in
    jit (XLA inserts the collective) — so this is BatchNorm plus a mesh
    assertion, replacing the reference's NCCL-based implementation
    (``src/operator/contrib/sync_batch_norm.cc``).
    """

    def __init__(self, in_channels=0, num_devices=None, **kwargs):
        super().__init__(in_channels=in_channels, **kwargs)
        self._num_devices = num_devices


class LayerNorm(HybridBlock):
    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              differentiable=center)

    def forward(self, x):
        c = x.shape[self._axis]
        if self.gamma.shape[0] == 0:
            self.gamma.shape = (c,)
            self.beta.shape = (c,)
        return _nn.layer_norm(x, self.gamma.data(), self.beta.data(),
                              axis=self._axis, eps=self._eps)

    def __repr__(self):
        return f"LayerNorm(eps={self._eps})"


class RMSNorm(HybridBlock):
    """Root-mean-square norm (for the LLM model family; no reference analog)."""

    def __init__(self, axis=-1, epsilon=1e-6, gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer)

    def forward(self, x):
        if self.gamma.shape[0] == 0:
            self.gamma.shape = (x.shape[self._axis],)
        return _nn.rms_norm(x, self.gamma.data(), axis=self._axis, eps=self._eps)


class GroupNorm(HybridBlock):
    def __init__(self, num_groups=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._ngroups = num_groups
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        if self.gamma.shape[0] == 0:
            self.gamma.shape = (c,)
            self.beta.shape = (c,)
        return _nn.group_norm(x, self.gamma.data(), self.beta.data(),
                              num_groups=self._ngroups, eps=self._eps)


class InstanceNorm(HybridBlock):
    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        if axis != 1:
            raise MXNetError("InstanceNorm supports axis=1 (NC...)")
        self._eps = epsilon
        shape = (in_channels,) if in_channels else (0,)
        self.gamma = Parameter("gamma", shape=shape, init=gamma_initializer,
                               differentiable=scale)
        self.beta = Parameter("beta", shape=shape, init=beta_initializer,
                              differentiable=center)

    def forward(self, x):
        c = x.shape[1]
        if self.gamma.shape[0] == 0:
            self.gamma.shape = (c,)
            self.beta.shape = (c,)
        return _nn.instance_norm(x, self.gamma.data(), self.beta.data(),
                                 eps=self._eps)
