"""Probabilistic programming (reference: ``python/mxnet/gluon/probability/``,
5,516 LoC: distributions, StochasticBlock, transformations).

Distributions operate on NDArrays through the normal dispatch layer, so
``log_prob`` participates in autograd and everything jits inside
``hybridize``. Sampling draws from the framework RNG (trace-aware keys)."""
from . import constraint
from . import distributions
from . import exp_family
from .distributions import (
    ExponentialFamily,
    Bernoulli,
    Beta,
    Binomial,
    Categorical,
    Cauchy,
    Chi2,
    Dirichlet,
    Distribution,
    Exponential,
    FisherSnedecor,
    Gamma,
    Geometric,
    Gumbel,
    HalfCauchy,
    HalfNormal,
    Independent,
    Laplace,
    Multinomial,
    MultivariateNormal,
    NegativeBinomial,
    Normal,
    OneHotCategorical,
    Pareto,
    Poisson,
    RelaxedBernoulli,
    RelaxedOneHotCategorical,
    StudentT,
    Uniform,
    Weibull,
    empirical_kl,
    kl_divergence,
    register_kl,
)
from . import transformation
from .transformation import (
    AbsTransform,
    AffineTransform,
    ComposeTransform,
    ExpTransform,
    PowerTransform,
    SigmoidTransform,
    SoftmaxTransform,
    Transformation,
    TransformedDistribution,
)
from .stochastic_block import StochasticBlock, StochasticSequential
