"""Probabilistic programming (reference: ``python/mxnet/gluon/probability/``,
5,516 LoC: distributions, StochasticBlock, transformations).

Distributions operate on NDArrays through the normal dispatch layer, so
``log_prob`` participates in autograd and everything jits inside
``hybridize``. Sampling draws from the framework RNG (trace-aware keys)."""
from . import distributions
from .distributions import (
    Bernoulli,
    Beta,
    Categorical,
    Cauchy,
    Chi2,
    Dirichlet,
    Distribution,
    Exponential,
    Gamma,
    Geometric,
    Gumbel,
    HalfNormal,
    Laplace,
    MultivariateNormal,
    Normal,
    Poisson,
    StudentT,
    Uniform,
    Weibull,
    kl_divergence,
    register_kl,
)
from . import transformation
from .transformation import (
    AbsTransform,
    AffineTransform,
    ComposeTransform,
    ExpTransform,
    PowerTransform,
    SigmoidTransform,
    SoftmaxTransform,
    Transformation,
    TransformedDistribution,
)
from .stochastic_block import StochasticBlock, StochasticSequential
