"""Probabilistic programming (reference: ``python/mxnet/gluon/probability/``,
5,516 LoC: distributions, StochasticBlock, transformations).

Distributions operate on NDArrays through the normal dispatch layer, so
``log_prob`` participates in autograd and everything jits inside
``hybridize``. Sampling draws from the framework RNG (trace-aware keys)."""
from . import distributions
from .distributions import (
    Bernoulli,
    Beta,
    Categorical,
    Dirichlet,
    Distribution,
    Exponential,
    Gamma,
    Laplace,
    MultivariateNormal,
    Normal,
    Poisson,
    Uniform,
    kl_divergence,
    register_kl,
)
from .stochastic_block import StochasticBlock, StochasticSequential
