"""Distributions (reference:
``python/mxnet/gluon/probability/distributions/``)."""
from __future__ import annotations

import math

from ... import random as _rng
from ...base import MXNetError
from ...ops.registry import apply as _apply
from . import constraint as _constraint


def _jnp():
    import jax.numpy as jnp

    return jnp


def _jr():
    import jax.random as jr

    return jr


def _data(x):
    from ...ndarray.ndarray import NDArray

    return x._data if isinstance(x, NDArray) else x


def _wrap(fn, *args, name="dist"):
    return _apply(fn, args, name=name)


def _owning_init_class(t):
    """First class in ``t``'s MRO that defines ``__init__`` — the one
    whose (wrapped) constructor actually finishes last."""
    for c in t.__mro__:
        if "__init__" in c.__dict__:
            return c
    return None


class Distribution:
    """Base distribution (reference ``distribution.py``).

    Argument validation (reference ``distribution.py:54-66`` +
    ``constraint.py``): each subclass declares ``arg_constraints``
    (param name → Constraint) and ``support``; with
    ``validate_args=True`` (or after
    ``Distribution.set_default_validate_args(True)``) the constructor
    checks every supplied parameter and ``log_prob`` checks its input
    against the support, raising ``ValueError`` on violation. Validation
    hooks are installed by ``__init_subclass__`` so the ~30 subclasses
    stay declarative."""

    has_grad = True
    support = None
    arg_constraints = {}
    _default_validate_args = False

    @staticmethod
    def set_default_validate_args(value):
        """Process-wide default for ``validate_args`` (reference
        ``distribution.py:48-52``)."""
        Distribution._default_validate_args = bool(value)

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        import functools

        init = cls.__dict__.get("__init__")
        if init is not None:
            @functools.wraps(init)
            def wrapped_init(self, *a, __init=init, __cls=cls, **k):
                __init(self, *a, **k)
                # validate exactly once, after the MOST-DERIVED __init__
                # finished (params are assigned after super().__init__
                # here, unlike the reference)
                if _owning_init_class(type(self)) is __cls:
                    self._validate_params()

            cls.__init__ = wrapped_init
        lp = cls.__dict__.get("log_prob")
        if lp is not None:
            @functools.wraps(lp)
            def wrapped_lp(self, value, *a, __lp=lp, **k):
                if self._should_validate():
                    self._validate_samples(value)
                return __lp(self, value, *a, **k)

            cls.log_prob = wrapped_lp

    def _should_validate(self):
        v = getattr(self, "_validate_args", None)
        return Distribution._default_validate_args if v is None else v

    def _validate_params(self):
        from .constraint import is_dependent

        if not self._should_validate():
            return
        for name, con in self.arg_constraints.items():
            if is_dependent(con):
                continue
            # __dict__ first, not getattr: derived parameterizations
            # (prob from logit) must not be materialized just to
            # validate. "_<name>" covers prob/logit storage, "<name>_param"
            # covers attributes renamed to dodge method collisions
            # (Gamma.shape).
            found = False
            val = None
            for attr in (name, "_" + name, name + "_param"):
                if attr in self.__dict__:
                    found = True
                    val = self.__dict__[attr]
                    if val is not None:
                        break
            if val is None and not found:
                # wrapper classes (OneHotCategorical→Categorical) store
                # the duals on a _base distribution: look there BEFORE
                # the property fallback, so the unused side of a dual
                # parameterization is skipped instead of materialized
                # (softmax'ing logits just to re-check Simplex both
                # wastes a device launch and can spuriously reject valid
                # logits at float32 summation tolerance)
                base = self.__dict__.get("_base")
                if base is not None:
                    for attr in (name, "_" + name, name + "_param"):
                        if attr in base.__dict__:
                            found = True
                            val = base.__dict__[attr]
                            if val is not None:
                                break
            if val is None and not found:
                # a param only ever exposed as a property (no dual
                # storage anywhere): materializing it is the only way
                # to validate — validation is opt-in
                if isinstance(getattr(type(self), name, None), property):
                    found = True
                    val = getattr(self, name)
            if not found:
                # a declared constraint that maps to NO storage is a
                # programming error, not a pass (silently skipping is
                # how dead validation ships)
                raise TypeError(
                    f"{type(self).__name__}.arg_constraints declares "
                    f"{name!r} but no attribute or property stores it")
            if val is None:
                continue  # unused side of a dual parameterization
            con.check(val)

    def _validate_samples(self, value):
        """Check ``value`` lies in ``self.support`` (reference
        ``distribution.py:193-198``)."""
        from .constraint import Constraint, is_dependent

        sup = self.support  # dependent_property resolves on the instance
        if isinstance(sup, Constraint) and not is_dependent(sup):
            sup.check(value)
        return value

    def __init__(self, event_dim=0, validate_args=None):
        self.event_dim = event_dim
        self._validate_args = validate_args

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ... import numpy as mnp

        return mnp.exp(self.log_prob(value))

    def sample(self, size=None):
        raise NotImplementedError

    def sample_n(self, n):
        return self.sample((n,))

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return self.variance.sqrt()

    def entropy(self):
        raise NotImplementedError

    def _shape(self, size, param, *more_params):
        import numpy as onp

        base = tuple(param.shape)
        if more_params:
            base = onp.broadcast_shapes(
                base, *[tuple(p.shape) for p in more_params])
        if size is None:
            return base
        if isinstance(size, int):
            size = (size,)
        return tuple(size) + base


class ExponentialFamily(Distribution):
    r"""Base for densities ``p(x;θ) = exp(⟨t(x),θ⟩ − F(θ) + k(x))``.

    Reference ``exp_family.py`` (68 LoC) declares the
    ``_natural_params`` / ``_log_normalizer`` / ``_mean_carrier_measure``
    interface but leaves the generic identities unimplemented; here they
    are computed TPU-natively with jax autodiff of the log-normalizer:

        H(P)    = F(θ) − ⟨θ, ∇F(θ)⟩ − E_p[k(x)]
        KL(P‖Q) = F(θ_q) − F(θ_p) − ⟨∇F(θ_p), θ_q − θ_p⟩  (same family)

    so members with natural parameters need no per-class entropy/KL math
    (``kl_divergence`` falls back to the Bregman form for same-class
    pairs with no registered closed form).
    """

    @property
    def _natural_params(self):
        """Tuple of natural-parameter NDArrays."""
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        """F(θ) on raw jax arrays (must be jax-differentiable)."""
        raise NotImplementedError

    def _mean_carrier_measure(self):
        """E_p[k(x)] — 0 for most members; required for entropy."""
        raise NotImplementedError

    def entropy(self):
        import jax

        theta = self._natural_params
        carrier = self._mean_carrier_measure()
        n = len(theta)

        def f(*ts):
            grads = jax.grad(
                lambda *args: self._log_normalizer(*args).sum(),
                argnums=tuple(range(n)))(*ts)
            lognorm = self._log_normalizer(*ts)
            inner = sum(
                (t * g).reshape(lognorm.shape + (-1,)).sum(-1)
                for t, g in zip(ts, grads))
            return lognorm - inner

        return _wrap(f, *theta, name="expfam_entropy") - carrier

    def _kl_same_family(self, other):
        import jax

        tp = self._natural_params
        tq = other._natural_params
        n = len(tp)

        def f(*ts):
            p, q = ts[:n], ts[n:]
            grads = jax.grad(
                lambda *args: self._log_normalizer(*args).sum(),
                argnums=tuple(range(n)))(*p)
            lognorm_p = self._log_normalizer(*p)
            lognorm_q = self._log_normalizer(*q)
            inner = sum(
                (g * (qi - pi)).reshape(lognorm_p.shape + (-1,)).sum(-1)
                for g, pi, qi in zip(grads, p, q))
            return lognorm_q - lognorm_p - inner

        return _wrap(f, *tp, *tq, name="expfam_kl")


class Normal(ExponentialFamily):
    arg_constraints = {"loc": _constraint.Real(),
                       "scale": _constraint.Positive()}
    support = _constraint.Real()

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    @property
    def _natural_params(self):
        return (self.loc / self.scale ** 2,
                -0.5 / self.scale ** 2)

    def _log_normalizer(self, t1, t2):
        jnp = _jnp()
        return -(t1 ** 2) / (4 * t2) + 0.5 * jnp.log(-math.pi / t2)

    def _mean_carrier_measure(self):
        return 0.0

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))

        return _wrap(f, value, self.loc, self.scale, name="normal_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc, self.scale)

        def f(loc, scale):
            return loc + scale * jr.normal(key, shape)

        return _wrap(f, self.loc, self.scale, name="normal_sample")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        jnp = _jnp()

        def f(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)

        return _wrap(f, self.scale, name="normal_entropy")


class Laplace(Distribution):
    arg_constraints = {'loc': _constraint.Real(), 'scale': _constraint.Positive()}
    support = _constraint.Real()

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)

        return _wrap(f, value, self.loc, self.scale, name="laplace_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc, self.scale)

        def f(loc, scale):
            return loc + scale * jr.laplace(key, shape)

        return _wrap(f, self.loc, self.scale, name="laplace_sample")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale ** 2


class _ProbLogitMixin:
    """Shared prob=/logit= dual parameterization (sigmoid link) used by
    Bernoulli, Binomial and NegativeBinomial."""

    def _init_prob_logit(self, prob, logit):
        from ... import numpy as mnp

        if (prob is None) == (logit is None):
            raise MXNetError("give exactly one of prob=/logit=")
        self._prob = (mnp.array(prob) if prob is not None
                      and not hasattr(prob, "_data") else prob)
        self._logit = (mnp.array(logit) if logit is not None
                       and not hasattr(logit, "_data") else logit)

    @property
    def prob(self):
        if self._prob is not None:
            return self._prob
        jnp = _jnp()
        return _wrap(lambda l: 1 / (1 + jnp.exp(-l)), self._logit,
                     name="sigmoid")

    @property
    def logit(self):
        if self._logit is not None:
            return self._logit
        jnp = _jnp()
        return _wrap(lambda p: jnp.log(p) - jnp.log1p(-p), self._prob,
                     name="logit")


class Bernoulli(_ProbLogitMixin, ExponentialFamily):
    arg_constraints = {'prob': _constraint.Interval(0, 1), 'logit': _constraint.Real()}
    support = _constraint.Boolean()

    @property
    def _natural_params(self):
        return (self.logit,)

    def _log_normalizer(self, t):
        return _jnp().logaddexp(0.0, t)

    def _mean_carrier_measure(self):
        return 0.0

    def __init__(self, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self._init_prob_logit(prob, logit)

    def log_prob(self, value):
        jnp = _jnp()
        logit = self.logit

        def f(v, l):
            # -softplus(-l)*v - softplus(l)*(1-v) stable form
            return v * l - jnp.logaddexp(0.0, l)

        return _wrap(f, value, logit, name="bernoulli_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        p = self.prob
        shape = self._shape(size, p)

        def f(pp):
            return jr.bernoulli(key, pp, shape).astype("float32")

        return _wrap(f, p, name="bernoulli_sample")

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        p = self.prob
        return p * (1 - p)


class Categorical(Distribution):
    arg_constraints = {'prob': _constraint.Simplex(), 'logit': _constraint.Real()}

    def __init__(self, num_events=None, prob=None, logit=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        if (prob is None) == (logit is None):
            raise MXNetError("give exactly one of prob=/logit=")
        self._prob = (mnp.array(prob) if prob is not None
                      and not hasattr(prob, "_data") else prob)
        self._logit = (mnp.array(logit) if logit is not None
                       and not hasattr(logit, "_data") else logit)
        self.num_events = num_events
    @_constraint.dependent_property
    def support(self):
        n = self.num_events
        if n is None:
            n = int(self.prob.shape[-1]) if self._prob is not None \
                else int(self.logit.shape[-1])
        return _constraint.IntegerInterval(0, n - 1)

    @property
    def logit(self):
        if self._logit is not None:
            return self._logit
        jnp = _jnp()
        return _wrap(lambda p: jnp.log(p), self._prob, name="log")

    @property
    def prob(self):
        if self._prob is not None:
            return self._prob
        import jax

        return _wrap(lambda l: jax.nn.softmax(l, axis=-1), self._logit,
                     name="softmax")

    def log_prob(self, value):
        import jax
        jnp = _jnp()
        logit = self.logit

        def f(v, l):
            logp = jax.nn.log_softmax(l, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]

        return _wrap(f, value, logit, name="categorical_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        logit = self.logit
        shape = (tuple(size) if isinstance(size, (tuple, list))
                 else ((size,) if size else ())) + tuple(logit.shape[:-1])

        def f(l):
            return jr.categorical(key, l, shape=shape).astype("float32")

        return _wrap(f, logit, name="categorical_sample")


class Uniform(Distribution):
    arg_constraints = {'low': _constraint.Real(), 'high': _constraint.Real()}

    def __init__(self, low=0.0, high=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.low = mnp.array(low) if not hasattr(low, "_data") else low
        self.high = mnp.array(high) if not hasattr(high, "_data") else high

    @_constraint.dependent_property
    def support(self):
        return _constraint.Interval(self.low, self.high)

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, lo, hi):
            inside = (v >= lo) & (v <= hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return _wrap(f, value, self.low, self.high, name="uniform_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.low, self.high)

        def f(lo, hi):
            return lo + (hi - lo) * jr.uniform(key, shape)

        return _wrap(f, self.low, self.high, name="uniform_sample")

    @property
    def mean(self):
        return (self.low + self.high) / 2


class Exponential(ExponentialFamily):
    arg_constraints = {'scale': _constraint.Positive()}
    support = _constraint.NonNegative()

    @property
    def _natural_params(self):
        return (-1.0 / self.scale,)

    def _log_normalizer(self, t):
        return -_jnp().log(-t)

    def _mean_carrier_measure(self):
        return 0.0

    def __init__(self, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, s):
            return -v / s - jnp.log(s)

        return _wrap(f, value, self.scale, name="exponential_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.scale)

        def f(s):
            return s * jr.exponential(key, shape)

        return _wrap(f, self.scale, name="exponential_sample")

    @property
    def mean(self):
        return self.scale


class Gamma(ExponentialFamily):
    arg_constraints = {'shape': _constraint.Positive(), 'scale': _constraint.Positive()}
    support = _constraint.Positive()

    @property
    def _natural_params(self):
        return (self.shape_param - 1.0, -1.0 / self.scale)

    def _log_normalizer(self, t1, t2):
        from jax.scipy.special import gammaln

        return gammaln(t1 + 1) - (t1 + 1) * _jnp().log(-t2)

    def _mean_carrier_measure(self):
        return 0.0

    def __init__(self, shape=1.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.shape_param = (mnp.array(shape) if not hasattr(shape, "_data")
                            else shape)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, a, s):
            return ((a - 1) * jnp.log(v) - v / s - jax.lax.lgamma(a)
                    - a * jnp.log(s))

        return _wrap(f, value, self.shape_param, self.scale,
                     name="gamma_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.shape_param, self.scale)

        def f(a, s):
            return s * jr.gamma(key, a, shape)

        return _wrap(f, self.shape_param, self.scale, name="gamma_sample")

    @property
    def mean(self):
        return self.shape_param * self.scale


class Beta(ExponentialFamily):
    arg_constraints = {'alpha': _constraint.Positive(), 'beta': _constraint.Positive()}
    support = _constraint.UnitInterval()

    @property
    def _natural_params(self):
        return (self.alpha - 1.0, self.beta - 1.0)

    def _log_normalizer(self, t1, t2):
        from jax.scipy.special import gammaln

        return gammaln(t1 + 1) + gammaln(t2 + 1) - gammaln(t1 + t2 + 2)

    def _mean_carrier_measure(self):
        return 0.0

    def __init__(self, alpha=1.0, beta=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.alpha = mnp.array(alpha) if not hasattr(alpha, "_data") else alpha
        self.beta = mnp.array(beta) if not hasattr(beta, "_data") else beta

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, a, b):
            lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                     - jax.lax.lgamma(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta

        return _wrap(f, value, self.alpha, self.beta, name="beta_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.alpha, self.beta)

        def f(a, b):
            return jr.beta(key, a, b, shape)

        return _wrap(f, self.alpha, self.beta, name="beta_sample")


class Poisson(ExponentialFamily):
    arg_constraints = {'rate': _constraint.Positive()}
    support = _constraint.NonNegativeInteger()

    @property
    def _natural_params(self):
        from ... import numpy as mnp

        return (mnp.log(self.rate),)

    def _log_normalizer(self, t):
        return _jnp().exp(t)

    def __init__(self, rate=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.rate = mnp.array(rate) if not hasattr(rate, "_data") else rate

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, r):
            return v * jnp.log(r) - r - jax.lax.lgamma(v + 1)

        return _wrap(f, value, self.rate, name="poisson_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.rate)

        def f(r):
            return jr.poisson(_rng.as_threefry(key), r, shape).astype("float32")

        return _wrap(f, self.rate, name="poisson_sample")

    @property
    def mean(self):
        return self.rate


class Dirichlet(ExponentialFamily):
    arg_constraints = {'alpha': _constraint.Positive()}
    support = _constraint.Simplex()

    @property
    def _natural_params(self):
        return (self.alpha - 1.0,)

    def _log_normalizer(self, t):
        from jax.scipy.special import gammaln

        return gammaln(t + 1).sum(-1) - gammaln((t + 1).sum(-1))

    def _mean_carrier_measure(self):
        return 0.0

    def __init__(self, alpha, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        self.alpha = mnp.array(alpha) if not hasattr(alpha, "_data") else alpha

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, a):
            lnorm = (jnp.sum(jax.lax.lgamma(a), -1)
                     - jax.lax.lgamma(jnp.sum(a, -1)))
            return jnp.sum((a - 1) * jnp.log(v), -1) - lnorm

        return _wrap(f, value, self.alpha, name="dirichlet_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(a):
            return jr.dirichlet(key, a, pre + tuple(a.shape[:-1]))

        return _wrap(f, self.alpha, name="dirichlet_sample")


class MultivariateNormal(Distribution):
    arg_constraints = {'loc': _constraint.Real(), 'cov': _constraint.PositiveDefinite(), 'scale_tril': _constraint.LowerCholesky()}
    support = _constraint.Real()

    def __init__(self, loc, cov=None, scale_tril=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        if (cov is None) == (scale_tril is None):
            raise MXNetError("give exactly one of cov=/scale_tril=")
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self._cov = mnp.array(cov) if cov is not None \
            and not hasattr(cov, "_data") else cov
        self._scale_tril = mnp.array(scale_tril) if scale_tril is not None \
            and not hasattr(scale_tril, "_data") else scale_tril

    @property
    def scale_tril(self):
        if self._scale_tril is not None:
            return self._scale_tril
        jnp = _jnp()
        return _wrap(lambda c: jnp.linalg.cholesky(c), self._cov,
                     name="cholesky")

    def log_prob(self, value):
        jnp = _jnp()
        tril = self.scale_tril

        def f(v, loc, L):
            d = loc.shape[-1]
            diff = v - loc
            sol = jnp.linalg.solve(L, diff[..., None])[..., 0]
            maha = jnp.sum(sol ** 2, -1)
            logdet = 2 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2,
                                                      axis2=-1)), -1)
            return -0.5 * (maha + d * math.log(2 * math.pi) + logdet)

        return _wrap(f, value, self.loc, tril, name="mvn_logp")

    def sample(self, size=None):
        jr = _jr()
        jnp = _jnp()
        key = _rng.next_key()
        tril = self.scale_tril
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(loc, L):
            eps = jr.normal(key, pre + tuple(loc.shape))
            return loc + jnp.einsum("...ij,...j->...i", L, eps)

        return _wrap(f, self.loc, tril, name="mvn_sample")

    @property
    def mean(self):
        return self.loc


# -- KL divergence registry (reference ``divergence/``) ----------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        # same-class exponential-family pairs fall back to the Bregman
        # divergence of the log-normalizer (exact, via jax.grad) — no
        # closed form needs registering
        if type(p) is type(q) and isinstance(p, ExponentialFamily):
            try:
                return p._kl_same_family(q)
            except NotImplementedError:
                pass
        raise MXNetError(
            f"no KL registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    from ... import numpy as mnp

    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - mnp.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    jnp = _jnp()

    def f(pp, qp):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return (pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))

    return _wrap(f, p.prob, q.prob, name="kl_bernoulli")


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    import jax
    jnp = _jnp()

    def f(pl, ql):
        plog = jax.nn.log_softmax(pl, -1)
        qlog = jax.nn.log_softmax(ql, -1)
        return jnp.sum(jnp.exp(plog) * (plog - qlog), -1)

    return _wrap(f, p.logit, q.logit, name="kl_categorical")


class StudentT(Distribution):
    """Student's t (reference studentT.py)."""

    arg_constraints = {'df': _constraint.Positive(), 'loc': _constraint.Real(), 'scale': _constraint.Real()}
    support = _constraint.Real()

    def __init__(self, df, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.df = mnp.array(df) if not hasattr(df, "_data") else df
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, df, loc, scale):
            import jax.scipy.special as jss

            z = (v - loc) / scale
            return (jss.gammaln((df + 1) / 2) - jss.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(scale)
                    - (df + 1) / 2 * jnp.log1p(z ** 2 / df))

        return _wrap(f, value, self.df, self.loc, self.scale,
                     name="studentt_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc, self.df, self.scale)

        def f(df, loc, scale):
            return loc + scale * jr.t(key, df, shape)

        return _wrap(f, self.df, self.loc, self.scale, name="studentt_sample")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        from ... import numpy as mnp

        return self.scale ** 2 * self.df / (self.df - 2)


class Cauchy(Distribution):
    arg_constraints = {'loc': _constraint.Real(), 'scale': _constraint.Real()}
    support = _constraint.Real()

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            z = (v - loc) / scale
            return -jnp.log(math.pi * scale * (1 + z ** 2))

        return _wrap(f, value, self.loc, self.scale, name="cauchy_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc, self.scale)

        def f(loc, scale):
            return loc + scale * jr.cauchy(key, shape)

        return _wrap(f, self.loc, self.scale, name="cauchy_sample")


class HalfNormal(Distribution):
    arg_constraints = {'scale': _constraint.Positive()}
    support = _constraint.NonNegative()

    def __init__(self, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, scale):
            return (0.5 * math.log(2 / math.pi) - jnp.log(scale)
                    - v ** 2 / (2 * scale ** 2)
                    + jnp.where(v >= 0, 0.0, -jnp.inf))

        return _wrap(f, value, self.scale, name="halfnormal_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.scale)

        def f(scale):
            return _jnp().abs(scale * jr.normal(key, shape))

        return _wrap(f, self.scale, name="halfnormal_sample")

    @property
    def mean(self):
        return self.scale * math.sqrt(2 / math.pi)


class Chi2(Distribution):
    arg_constraints = {'df': _constraint.Positive()}
    support = _constraint.Positive()

    def __init__(self, df, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.df = mnp.array(df) if not hasattr(df, "_data") else df

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, df):
            import jax.scipy.special as jss

            k = df / 2
            return ((k - 1) * jnp.log(v) - v / 2 - jss.gammaln(k)
                    - k * math.log(2.0))

        return _wrap(f, value, self.df, name="chi2_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.df)

        def f(df):
            return 2.0 * jr.gamma(key, df / 2, shape)

        return _wrap(f, self.df, name="chi2_sample")

    @property
    def mean(self):
        return self.df

    @property
    def variance(self):
        return 2 * self.df


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    arg_constraints = {'prob': _constraint.Interval(0, 1)}
    support = _constraint.NonNegativeInteger()

    def __init__(self, prob, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.prob = mnp.array(prob) if not hasattr(prob, "_data") else prob

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, p):
            return v * jnp.log1p(-p) + jnp.log(p)

        return _wrap(f, value, self.prob, name="geometric_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.prob)

        def f(p):
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return _jnp().floor(_jnp().log(u) / _jnp().log1p(-p))

        return _wrap(f, self.prob, name="geometric_sample")

    @property
    def mean(self):
        return (1 - self.prob) / self.prob


class Gumbel(Distribution):
    arg_constraints = {'loc': _constraint.Real(), 'scale': _constraint.Positive()}
    support = _constraint.Real()

    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)

        return _wrap(f, value, self.loc, self.scale, name="gumbel_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc, self.scale)

        def f(loc, scale):
            return loc + scale * jr.gumbel(key, shape)

        return _wrap(f, self.loc, self.scale, name="gumbel_sample")

    @property
    def mean(self):
        return self.loc + self.scale * 0.5772156649015329


class Binomial(_ProbLogitMixin, Distribution):
    """Binomial(n, p) (reference ``distributions/binomial.py``)."""

    arg_constraints = {'n': _constraint.NonNegativeInteger(), 'prob': _constraint.Interval(0, 1), 'logit': _constraint.Real()}

    def __init__(self, n=1, prob=None, logit=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.n = mnp.array(n) if not hasattr(n, "_data") else n
        self._init_prob_logit(prob, logit)

    @_constraint.dependent_property
    def support(self):
        return _constraint.IntegerInterval(0, self.n)

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, n, l):
            import jax.scipy.special as jss

            binom = (jss.gammaln(n + 1) - jss.gammaln(v + 1)
                     - jss.gammaln(n - v + 1))
            # v*l - n*softplus(l) is the stable logit form
            return binom + v * l - n * jnp.logaddexp(0.0, l)

        return _wrap(f, value, self.n, self.logit, name="binomial_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        p = self.prob
        shape = self._shape(size, p, self.n)

        def f(n, pp):
            return jr.binomial(key, n, pp, shape=shape).astype("float32")

        return _wrap(f, self.n, p, name="binomial_sample")

    @property
    def mean(self):
        return self.n * self.prob

    @property
    def variance(self):
        p = self.prob
        return self.n * p * (1 - p)


class NegativeBinomial(_ProbLogitMixin, Distribution):
    """Failures-before-n-successes form: P(X=k) = C(k+n-1,k)(1-p)^n p^k
    (reference ``distributions/negative_binomial.py``)."""

    arg_constraints = {'n': _constraint.GreaterThanEq(0), 'prob': _constraint.Interval(0, 1), 'logit': _constraint.Real()}
    support = _constraint.NonNegativeInteger()

    def __init__(self, n=1, prob=None, logit=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.n = mnp.array(n) if not hasattr(n, "_data") else n
        self._init_prob_logit(prob, logit)

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, n, p):
            import jax.scipy.special as jss

            coef = (jss.gammaln(v + n) - jss.gammaln(n)
                    - jss.gammaln(v + 1))
            return coef + n * jnp.log1p(-p) + v * jnp.log(p)

        return _wrap(f, value, self.n, self.prob, name="negbinomial_logp")

    def sample(self, size=None):
        # Gamma-Poisson mixture: lam ~ Gamma(n, p/(1-p)), X ~ Poisson(lam)
        jr = _jr()
        import jax

        k1, k2 = jax.random.split(_rng.next_key())
        p = self.prob
        shape = self._shape(size, p, self.n)

        def f(n, pp):
            lam = jr.gamma(k1, n, shape) * (pp / (1 - pp))
            return jr.poisson(_rng.as_threefry(k2), lam).astype("float32")

        return _wrap(f, self.n, p, name="negbinomial_sample")

    @property
    def mean(self):
        p = self.prob
        return self.n * p / (1 - p)

    @property
    def variance(self):
        p = self.prob
        return self.n * p / (1 - p) ** 2


class Multinomial(Distribution):
    """Counts over ``num_events`` categories from ``total_count`` draws
    (reference ``distributions/multinomial.py``)."""

    arg_constraints = {'prob': _constraint.Simplex(), 'logit': _constraint.Real()}

    def __init__(self, num_events=None, prob=None, logit=None,
                 total_count=1, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        if (prob is None) == (logit is None):
            raise MXNetError("give exactly one of prob=/logit=")
        self._prob = (mnp.array(prob) if prob is not None
                      and not hasattr(prob, "_data") else prob)
        self._logit = (mnp.array(logit) if logit is not None
                       and not hasattr(logit, "_data") else logit)
        self.total_count = int(total_count)
        self.num_events = num_events

    @property
    def prob(self):
        if self._prob is not None:
            return self._prob
        import jax

        return _wrap(lambda l: jax.nn.softmax(l, axis=-1), self._logit,
                     name="softmax")

    @property
    def logit(self):
        if self._logit is not None:
            return self._logit
        jnp = _jnp()
        return _wrap(lambda p: jnp.log(p), self._prob, name="log")

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, p):
            import jax.scipy.special as jss

            n = jnp.sum(v, -1)
            coef = jss.gammaln(n + 1) - jnp.sum(jss.gammaln(v + 1), -1)
            # xlogy: 0 * log(0) contributes 0 for empty categories
            return coef + jnp.sum(jss.xlogy(v, p), -1)

        return _wrap(f, value, self.prob, name="multinomial_logp")

    def sample(self, size=None):
        jr = _jr()
        jnp = _jnp()
        key = _rng.next_key()
        p = self.prob
        count = self.total_count
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(pp):
            # jr.multinomial produces the counts directly — O(batch*k)
            # memory regardless of total_count
            n = jnp.full(pre + tuple(pp.shape[:-1]), float(count))
            probs = jnp.broadcast_to(pp, pre + tuple(pp.shape))
            return jr.multinomial(key, n, probs).astype("float32")

        return _wrap(f, p, name="multinomial_sample")

    @property
    def mean(self):
        return self.total_count * self.prob


class FisherSnedecor(Distribution):
    """F-distribution (reference ``distributions/fishersnedecor.py``)."""

    arg_constraints = {'df1': _constraint.Positive(), 'df2': _constraint.Positive()}
    support = _constraint.Positive()

    def __init__(self, df1, df2, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.df1 = mnp.array(df1) if not hasattr(df1, "_data") else df1
        self.df2 = mnp.array(df2) if not hasattr(df2, "_data") else df2

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, d1, d2):
            import jax.scipy.special as jss

            lbeta = (jss.gammaln(d1 / 2) + jss.gammaln(d2 / 2)
                     - jss.gammaln((d1 + d2) / 2))
            safe_v = jnp.where(v > 0, v, 1.0)
            lp = (d1 / 2 * jnp.log(d1) + d2 / 2 * jnp.log(d2)
                  + (d1 / 2 - 1) * jnp.log(safe_v)
                  - (d1 + d2) / 2 * jnp.log(d2 + d1 * safe_v) - lbeta)
            return jnp.where(v > 0, lp, -jnp.inf)

        return _wrap(f, value, self.df1, self.df2, name="fishersnedecor_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.df1, self.df2)

        def f(d1, d2):
            return jr.f(key, d1, d2, shape)

        return _wrap(f, self.df1, self.df2, name="fishersnedecor_sample")

    @property
    def mean(self):
        # undefined for df2 <= 2 (same guard discipline as Pareto.mean)
        jnp = _jnp()

        d2 = self.df2._data if hasattr(self.df2, "_data") else self.df2
        from ...ndarray.ndarray import NDArray

        return NDArray(jnp.where(d2 > 2, d2 / (d2 - 2), jnp.nan))


class HalfCauchy(Distribution):
    """|Cauchy(0, scale)| (reference ``distributions/half_cauchy.py``)."""

    arg_constraints = {'scale': _constraint.Positive()}
    support = _constraint.NonNegative()

    def __init__(self, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, scale):
            z = v / scale
            return (math.log(2 / math.pi) - jnp.log(scale)
                    - jnp.log1p(z ** 2)
                    + jnp.where(v >= 0, 0.0, -jnp.inf))

        return _wrap(f, value, self.scale, name="halfcauchy_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.scale)

        def f(scale):
            return _jnp().abs(scale * jr.cauchy(key, shape))

        return _wrap(f, self.scale, name="halfcauchy_sample")


class Pareto(Distribution):
    """Pareto Type I (reference ``distributions/pareto.py``)."""

    arg_constraints = {'alpha': _constraint.Positive(), 'scale': _constraint.Positive()}

    @_constraint.dependent_property
    def support(self):
        return _constraint.GreaterThanEq(self.scale)

    def __init__(self, alpha, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.alpha = mnp.array(alpha) if not hasattr(alpha, "_data") else alpha
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, a, m):
            inside = v >= m
            return jnp.where(
                inside,
                jnp.log(a) + a * jnp.log(m) - (a + 1) * jnp.log(v),
                -jnp.inf)

        return _wrap(f, value, self.alpha, self.scale, name="pareto_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.alpha, self.scale)

        def f(a, m):
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return m * u ** (-1.0 / a)

        return _wrap(f, self.alpha, self.scale, name="pareto_sample")

    @property
    def mean(self):
        from ... import numpy as mnp

        return mnp.where(self.alpha > 1,
                         self.alpha * self.scale / (self.alpha - 1),
                         mnp.array(float("inf")))


class OneHotCategorical(Distribution):
    """One-hot coded categorical (reference
    ``distributions/one_hot_categorical.py``)."""

    arg_constraints = {'prob': _constraint.Simplex(), 'logit': _constraint.Real()}

    def __init__(self, num_events=None, prob=None, logit=None, **kwargs):
        super().__init__(**kwargs)
        self._base = Categorical(num_events=num_events, prob=prob,
                                 logit=logit)
        self.event_dim = 1
        self.num_events = num_events

    @property
    def prob(self):
        return self._base.prob

    @property
    def logit(self):
        return self._base.logit

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, l):
            return jnp.sum(v * jax.nn.log_softmax(l, -1), -1)

        return _wrap(f, value, self.logit, name="onehot_categorical_logp")

    def sample(self, size=None):
        jr = _jr()
        jnp = _jnp()
        key = _rng.next_key()
        logit = self.logit
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(l):
            k = l.shape[-1]
            draws = jr.categorical(key, l, shape=pre + tuple(l.shape[:-1]))
            return (draws[..., None] == jnp.arange(k)).astype("float32")

        return _wrap(f, logit, name="onehot_categorical_sample")

    @property
    def mean(self):
        return self.prob


class RelaxedBernoulli(Distribution):
    """Concrete / Gumbel-sigmoid relaxation (reference
    ``distributions/relaxed_bernoulli.py``)."""

    arg_constraints = {'prob': _constraint.Interval(0, 1), 'logit': _constraint.Real()}
    support = _constraint.UnitInterval()

    def __init__(self, T=1.0, prob=None, logit=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self._base = Bernoulli(prob=prob, logit=logit)
        self.T = mnp.array(T) if not hasattr(T, "_data") else T

    @property
    def prob(self):
        return self._base.prob

    @property
    def logit(self):
        return self._base.logit

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, t, l):
            # BinConcrete density (Maddison et al. 2017, eq. 24) in log space
            z = jnp.log(v) - jnp.log1p(-v)
            u = l - t * z
            return (jnp.log(t) + u - 2 * jnp.logaddexp(0.0, u)
                    - jnp.log(v) - jnp.log1p(-v))

        return _wrap(f, value, self.T, self.logit,
                     name="relaxed_bernoulli_logp")

    def sample(self, size=None):
        jr = _jr()
        jnp = _jnp()
        key = _rng.next_key()
        logit = self.logit
        shape = self._shape(size, logit)

        def f(t, l):
            u = jr.uniform(key, shape, minval=1e-7, maxval=1 - 1e-7)
            noise = jnp.log(u) - jnp.log1p(-u)
            return 1 / (1 + jnp.exp(-(l + noise) / t))

        return _wrap(f, self.T, logit, name="relaxed_bernoulli_sample")


class RelaxedOneHotCategorical(Distribution):
    """Gumbel-softmax relaxation (reference
    ``distributions/relaxed_one_hot_categorical.py``)."""

    arg_constraints = {'prob': _constraint.Simplex(), 'logit': _constraint.Real()}
    support = _constraint.Simplex()

    def __init__(self, T=1.0, num_events=None, prob=None, logit=None,
                 **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        self._base = Categorical(num_events=num_events, prob=prob,
                                 logit=logit)
        self.T = mnp.array(T) if not hasattr(T, "_data") else T
        self.num_events = num_events

    @property
    def prob(self):
        return self._base.prob

    @property
    def logit(self):
        return self._base.logit

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, t, l):
            import jax.scipy.special as jss

            k = l.shape[-1]
            score = l - t * jnp.log(v)
            score = score - jss.logsumexp(score, -1, keepdims=True)
            return (jss.gammaln(jnp.asarray(float(k)))
                    + (k - 1) * jnp.log(t)
                    + jnp.sum(score - jnp.log(v), -1))

        return _wrap(f, value, self.T, self.logit,
                     name="relaxed_onehot_logp")

    def sample(self, size=None):
        jr = _jr()
        import jax
        key = _rng.next_key()
        logit = self.logit
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(t, l):
            g = jr.gumbel(key, pre + tuple(l.shape))
            return jax.nn.softmax((l + g) / t, axis=-1)

        return _wrap(f, self.T, logit, name="relaxed_onehot_sample")


class Independent(Distribution):
    """Reinterpret trailing batch dims as event dims (reference
    ``distributions/independent.py``): log_prob sums over them."""

    def __init__(self, base_distribution, reinterpreted_batch_ndims,
                 **kwargs):
        super().__init__(
            event_dim=base_distribution.event_dim
            + reinterpreted_batch_ndims, **kwargs)
        self.base_dist = base_distribution
        self.reinterpreted_batch_ndims = int(reinterpreted_batch_ndims)

    def log_prob(self, value):
        jnp = _jnp()
        base_lp = self.base_dist.log_prob(value)
        n = self.reinterpreted_batch_ndims

        def f(lp):
            return jnp.sum(lp, axis=tuple(range(-n, 0)))

        return _wrap(f, base_lp, name="independent_logp")

    def sample(self, size=None):
        return self.base_dist.sample(size)

    def sample_n(self, n):
        return self.base_dist.sample_n(n)

    @property
    def mean(self):
        return self.base_dist.mean

    @property
    def variance(self):
        return self.base_dist.variance

    def entropy(self):
        jnp = _jnp()
        base_ent = self.base_dist.entropy()
        n = self.reinterpreted_batch_ndims

        def f(e):
            return jnp.sum(e, axis=tuple(range(-n, 0)))

        return _wrap(f, base_ent, name="independent_entropy")


class Weibull(Distribution):
    arg_constraints = {'concentration': _constraint.Positive(), 'scale': _constraint.Positive()}
    support = _constraint.Positive()

    def __init__(self, concentration, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.concentration = mnp.array(concentration) \
            if not hasattr(concentration, "_data") else concentration
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, k, scale):
            z = v / scale
            return (jnp.log(k / scale) + (k - 1) * jnp.log(z) - z ** k)

        return _wrap(f, value, self.concentration, self.scale,
                     name="weibull_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.concentration, self.scale)

        def f(k, scale):
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return scale * (-_jnp().log(u)) ** (1.0 / k)

        return _wrap(f, self.concentration, self.scale, name="weibull_sample")


# -- KL registry, part 2: the full reference registration set ----------------
# (reference ``distributions/divergence.py`` registers same-family KLs for
# every closed-form pair plus Uniform->Normal/Gumbel and
# Exponential->Gumbel/Normal/Gamma cross terms. All formulas below are the
# standard closed forms, written against jnp directly.)

def empirical_kl(p, q, n_samples=1):
    """Monte-Carlo estimate of KL(p||q): mean of log p(x) - log q(x) over
    ``n_samples`` draws from p (reference ``divergence.py:empirical_kl``)."""
    samples = p.sample_n(n_samples)
    jnp = _jnp()

    def f(lp, lq):
        return jnp.mean(lp - lq, axis=0)

    return _wrap(f, p.log_prob(samples), q.log_prob(samples),
                 name="empirical_kl")


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    jnp = _jnp()

    def f(sp, sq):
        return jnp.log(sq / sp) + sp / sq - 1.0

    return _wrap(f, p.scale, q.scale, name="kl_exponential")


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    jnp = _jnp()

    def f(pl, ph, ql, qh):
        contained = (ql <= pl) & (qh >= ph)
        return jnp.where(contained, jnp.log((qh - ql) / (ph - pl)), jnp.inf)

    return _wrap(f, p.low, p.high, q.low, q.high, name="kl_uniform")


@register_kl(Cauchy, Cauchy)
def _kl_cauchy_cauchy(p, q):
    jnp = _jnp()

    def f(l1, s1, l2, s2):
        return (jnp.log((s1 + s2) ** 2 + (l1 - l2) ** 2)
                - jnp.log(4 * s1 * s2))

    return _wrap(f, p.loc, p.scale, q.loc, q.scale, name="kl_cauchy")


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    jnp = _jnp()

    def f(l1, s1, l2, s2):
        d = jnp.abs(l1 - l2)
        return (jnp.log(s2 / s1) + d / s2
                + s1 / s2 * jnp.exp(-d / s1) - 1.0)

    return _wrap(f, p.loc, p.scale, q.loc, q.scale, name="kl_laplace")


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    jnp = _jnp()

    def f(rp, rq):
        return rp * (jnp.log(rp) - jnp.log(rq)) + rq - rp

    return _wrap(f, p.rate, q.rate, name="kl_poisson")


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    jnp = _jnp()

    def f(p1, p2):
        return (jnp.log(p1 / p2)
                + (1 - p1) / p1 * (jnp.log1p(-p1) - jnp.log1p(-p2)))

    return _wrap(f, p.prob, q.prob, name="kl_geometric")


@register_kl(Pareto, Pareto)
def _kl_pareto_pareto(p, q):
    jnp = _jnp()

    def f(a1, m1, a2, m2):
        kl = (jnp.log(a1 / a2) + a2 * jnp.log(m1 / m2)
              + (a2 - a1) / a1)
        return jnp.where(m1 >= m2, kl, jnp.inf)

    return _wrap(f, p.alpha, p.scale, q.alpha, q.scale, name="kl_pareto")


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    jnp = _jnp()

    def f(l1, s1, l2, s2):
        import jax.lax as lax

        euler = 0.5772156649015329
        return (jnp.log(s2 / s1) + (l1 - l2 + s1 * euler) / s2
                - euler - 1.0
                + jnp.exp((l2 - l1) / s2) * jnp.exp(lax.lgamma(1 + s1 / s2)))

    return _wrap(f, p.loc, p.scale, q.loc, q.scale, name="kl_gumbel")


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    jnp = _jnp()

    def f(a1, s1, a2, s2):
        import jax.scipy.special as jss

        return ((a1 - a2) * jss.digamma(a1) - jss.gammaln(a1)
                + jss.gammaln(a2) + a2 * jnp.log(s2 / s1)
                + a1 * (s1 / s2 - 1.0))

    return _wrap(f, p.shape_param, p.scale, q.shape_param, q.scale,
                 name="kl_gamma")


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    jnp = _jnp()

    def f(a1, b1, a2, b2):
        import jax.scipy.special as jss

        def lbeta(a, b):
            return jss.gammaln(a) + jss.gammaln(b) - jss.gammaln(a + b)

        return (lbeta(a2, b2) - lbeta(a1, b1)
                + (a1 - a2) * jss.digamma(a1)
                + (b1 - b2) * jss.digamma(b1)
                + (a2 - a1 + b2 - b1) * jss.digamma(a1 + b1))

    return _wrap(f, p.alpha, p.beta, q.alpha, q.beta, name="kl_beta")


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    jnp = _jnp()

    def f(a1, a2):
        import jax.scipy.special as jss

        s1 = jnp.sum(a1, -1)
        return (jss.gammaln(s1) - jnp.sum(jss.gammaln(a1), -1)
                - jss.gammaln(jnp.sum(a2, -1))
                + jnp.sum(jss.gammaln(a2), -1)
                + jnp.sum((a1 - a2)
                          * (jss.digamma(a1)
                             - jss.digamma(s1)[..., None]), -1))

    return _wrap(f, p.alpha, q.alpha, name="kl_dirichlet")


@register_kl(HalfNormal, HalfNormal)
def _kl_halfnormal_halfnormal(p, q):
    jnp = _jnp()

    def f(s1, s2):
        return jnp.log(s2 / s1) + s1 ** 2 / (2 * s2 ** 2) - 0.5

    return _wrap(f, p.scale, q.scale, name="kl_halfnormal")


@register_kl(HalfCauchy, HalfCauchy)
def _kl_halfcauchy_halfcauchy(p, q):
    # identical to the full-Cauchy KL (both densities are doubled)
    jnp = _jnp()

    def f(s1, s2):
        return jnp.log((s1 + s2) ** 2) - jnp.log(4 * s1 * s2)

    return _wrap(f, p.scale, q.scale, name="kl_halfcauchy")


@register_kl(Binomial, Binomial)
def _kl_binomial_binomial(p, q):
    jnp = _jnp()

    # closed form only exists for equal counts; p.n > q.n has disjoint
    # support (KL = inf); p.n < q.n has no closed form — returned as nan
    # INSIDE the traced computation (an eager asnumpy() check here would
    # force a host sync and break kl_divergence under jit; every other
    # registered KL stays on-device)

    def f(n1, n2, p1, p2):
        kl = n1 * (p1 * (jnp.log(p1) - jnp.log(p2))
                   + (1 - p1) * (jnp.log1p(-p1) - jnp.log1p(-p2)))
        return jnp.where(n1 == n2, kl,
                         jnp.where(n1 > n2, jnp.inf, jnp.nan))

    return _wrap(f, p.n, q.n, p.prob, q.prob, name="kl_binomial")


@register_kl(OneHotCategorical, OneHotCategorical)
def _kl_onehot_onehot(p, q):
    return _kl_categorical_categorical(p._base, q._base)


@register_kl(MultivariateNormal, MultivariateNormal)
def _kl_mvn_mvn(p, q):
    jnp = _jnp()

    def f(mu1, L1, mu2, L2):
        d = mu1.shape[-1]
        # tr(S2^-1 S1) = ||L2^-1 L1||_F^2 via triangular solve
        M = jnp.linalg.solve(L2, L1)
        tr = jnp.sum(M ** 2, axis=(-2, -1))
        diff = jnp.linalg.solve(L2, (mu2 - mu1)[..., None])[..., 0]
        maha = jnp.sum(diff ** 2, -1)
        logdet = 2 * (jnp.sum(jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)),
                              -1)
                      - jnp.sum(jnp.log(jnp.diagonal(L1, axis1=-2,
                                                     axis2=-1)), -1))
        return 0.5 * (tr + maha - d + logdet)

    return _wrap(f, p.loc, p.scale_tril, q.loc, q.scale_tril, name="kl_mvn")


@register_kl(Uniform, Normal)
def _kl_uniform_normal(p, q):
    jnp = _jnp()

    def f(lo, hi, loc, scale):
        w = hi - lo
        t1 = jnp.log(math.sqrt(2 * math.pi) * scale / w)
        t2 = w ** 2 / 12
        t3 = ((hi + lo - 2 * loc) / 2) ** 2
        return t1 + 0.5 * (t2 + t3) / scale ** 2

    return _wrap(f, p.low, p.high, q.loc, q.scale, name="kl_uniform_normal")


@register_kl(Uniform, Gumbel)
def _kl_uniform_gumbel(p, q):
    jnp = _jnp()

    def f(lo, hi, loc, scale):
        common = scale / (hi - lo)
        zh = (hi - loc) / scale
        zl = (lo - loc) / scale
        t1 = jnp.log(common) + 0.5 * (zh + zl)
        t2 = common * (jnp.exp(-zh) - jnp.exp(-zl))
        return t1 - t2

    return _wrap(f, p.low, p.high, q.loc, q.scale, name="kl_uniform_gumbel")


@register_kl(Exponential, Normal)
def _kl_exponential_normal(p, q):
    jnp = _jnp()

    def f(s, loc, scale):
        # E[x] = s, E[x^2] = 2 s^2 under Exponential(scale=s)
        var = scale ** 2
        t1 = 0.5 * jnp.log(2 * math.pi * var / s ** 2)
        return t1 - 1 + (2 * s ** 2 - 2 * loc * s + loc ** 2) / (2 * var)

    return _wrap(f, p.scale, q.loc, q.scale, name="kl_exponential_normal")


@register_kl(Exponential, Gumbel)
def _kl_exponential_gumbel(p, q):
    jnp = _jnp()

    def f(s, loc, scale):
        ratio = scale / s
        lsr = loc / scale
        t1 = jnp.log(ratio) - 1
        t2 = jnp.exp(lsr) * ratio / (ratio + 1)
        return t1 - lsr + t2 + 1 / ratio

    return _wrap(f, p.scale, q.loc, q.scale, name="kl_exponential_gumbel")


@register_kl(Exponential, Gamma)
def _kl_exponential_gamma(p, q):
    jnp = _jnp()

    def f(sp, a, sq):
        import jax.scipy.special as jss

        euler = 0.5772156649015329
        ratio = sp / sq
        return (-a * jnp.log(ratio) + ratio + jss.gammaln(a)
                + a * euler - (1 + euler))

    return _wrap(f, p.scale, q.shape_param, q.scale,
                 name="kl_exponential_gamma")
