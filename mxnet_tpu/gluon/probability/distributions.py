"""Distributions (reference:
``python/mxnet/gluon/probability/distributions/``)."""
from __future__ import annotations

import math

from ... import random as _rng
from ...base import MXNetError
from ...ops.registry import apply as _apply


def _jnp():
    import jax.numpy as jnp

    return jnp


def _jr():
    import jax.random as jr

    return jr


def _data(x):
    from ...ndarray.ndarray import NDArray

    return x._data if isinstance(x, NDArray) else x


def _wrap(fn, *args, name="dist"):
    return _apply(fn, args, name=name)


class Distribution:
    """Base distribution (reference ``distribution.py``)."""

    has_grad = True
    support = None
    arg_constraints = {}

    def __init__(self, event_dim=0, validate_args=None):
        self.event_dim = event_dim
        self._validate_args = validate_args

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        from ... import numpy as mnp

        return mnp.exp(self.log_prob(value))

    def sample(self, size=None):
        raise NotImplementedError

    def sample_n(self, n):
        return self.sample((n,))

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return self.variance.sqrt()

    def entropy(self):
        raise NotImplementedError

    def _shape(self, size, param):
        base = tuple(param.shape)
        if size is None:
            return base
        if isinstance(size, int):
            size = (size,)
        return tuple(size) + base


class Normal(Distribution):
    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            var = scale ** 2
            return (-((v - loc) ** 2) / (2 * var)
                    - jnp.log(scale) - 0.5 * math.log(2 * math.pi))

        return _wrap(f, value, self.loc, self.scale, name="normal_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc)

        def f(loc, scale):
            return loc + scale * jr.normal(key, shape)

        return _wrap(f, self.loc, self.scale, name="normal_sample")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return self.scale ** 2

    def entropy(self):
        jnp = _jnp()

        def f(scale):
            return 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(scale)

        return _wrap(f, self.scale, name="normal_entropy")


class Laplace(Distribution):
    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            return -jnp.abs(v - loc) / scale - jnp.log(2 * scale)

        return _wrap(f, value, self.loc, self.scale, name="laplace_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc)

        def f(loc, scale):
            return loc + scale * jr.laplace(key, shape)

        return _wrap(f, self.loc, self.scale, name="laplace_sample")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        return 2.0 * self.scale ** 2


class Bernoulli(Distribution):
    def __init__(self, prob=None, logit=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        if (prob is None) == (logit is None):
            raise MXNetError("give exactly one of prob=/logit=")
        self._prob = (mnp.array(prob) if prob is not None
                      and not hasattr(prob, "_data") else prob)
        self._logit = (mnp.array(logit) if logit is not None
                       and not hasattr(logit, "_data") else logit)

    @property
    def prob(self):
        if self._prob is not None:
            return self._prob
        jnp = _jnp()
        return _wrap(lambda l: 1 / (1 + jnp.exp(-l)), self._logit,
                     name="sigmoid")

    @property
    def logit(self):
        if self._logit is not None:
            return self._logit
        jnp = _jnp()
        return _wrap(lambda p: jnp.log(p) - jnp.log1p(-p), self._prob,
                     name="logit")

    def log_prob(self, value):
        jnp = _jnp()
        logit = self.logit

        def f(v, l):
            # -softplus(-l)*v - softplus(l)*(1-v) stable form
            return v * l - jnp.logaddexp(0.0, l)

        return _wrap(f, value, logit, name="bernoulli_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        p = self.prob
        shape = self._shape(size, p)

        def f(pp):
            return jr.bernoulli(key, pp, shape).astype("float32")

        return _wrap(f, p, name="bernoulli_sample")

    @property
    def mean(self):
        return self.prob

    @property
    def variance(self):
        p = self.prob
        return p * (1 - p)


class Categorical(Distribution):
    def __init__(self, num_events=None, prob=None, logit=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        if (prob is None) == (logit is None):
            raise MXNetError("give exactly one of prob=/logit=")
        self._prob = (mnp.array(prob) if prob is not None
                      and not hasattr(prob, "_data") else prob)
        self._logit = (mnp.array(logit) if logit is not None
                       and not hasattr(logit, "_data") else logit)
        self.num_events = num_events

    @property
    def logit(self):
        if self._logit is not None:
            return self._logit
        jnp = _jnp()
        return _wrap(lambda p: jnp.log(p), self._prob, name="log")

    @property
    def prob(self):
        if self._prob is not None:
            return self._prob
        import jax

        return _wrap(lambda l: jax.nn.softmax(l, axis=-1), self._logit,
                     name="softmax")

    def log_prob(self, value):
        import jax
        jnp = _jnp()
        logit = self.logit

        def f(v, l):
            logp = jax.nn.log_softmax(l, axis=-1)
            return jnp.take_along_axis(
                logp, v.astype(jnp.int32)[..., None], axis=-1)[..., 0]

        return _wrap(f, value, logit, name="categorical_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        logit = self.logit
        shape = (tuple(size) if isinstance(size, (tuple, list))
                 else ((size,) if size else ())) + tuple(logit.shape[:-1])

        def f(l):
            return jr.categorical(key, l, shape=shape).astype("float32")

        return _wrap(f, logit, name="categorical_sample")


class Uniform(Distribution):
    def __init__(self, low=0.0, high=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.low = mnp.array(low) if not hasattr(low, "_data") else low
        self.high = mnp.array(high) if not hasattr(high, "_data") else high

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, lo, hi):
            inside = (v >= lo) & (v <= hi)
            return jnp.where(inside, -jnp.log(hi - lo), -jnp.inf)

        return _wrap(f, value, self.low, self.high, name="uniform_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.low)

        def f(lo, hi):
            return lo + (hi - lo) * jr.uniform(key, shape)

        return _wrap(f, self.low, self.high, name="uniform_sample")

    @property
    def mean(self):
        return (self.low + self.high) / 2


class Exponential(Distribution):
    def __init__(self, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, s):
            return -v / s - jnp.log(s)

        return _wrap(f, value, self.scale, name="exponential_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.scale)

        def f(s):
            return s * jr.exponential(key, shape)

        return _wrap(f, self.scale, name="exponential_sample")

    @property
    def mean(self):
        return self.scale


class Gamma(Distribution):
    def __init__(self, shape=1.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.shape_param = (mnp.array(shape) if not hasattr(shape, "_data")
                            else shape)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, a, s):
            return ((a - 1) * jnp.log(v) - v / s - jax.lax.lgamma(a)
                    - a * jnp.log(s))

        return _wrap(f, value, self.shape_param, self.scale,
                     name="gamma_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.shape_param)

        def f(a, s):
            return s * jr.gamma(key, a, shape)

        return _wrap(f, self.shape_param, self.scale, name="gamma_sample")

    @property
    def mean(self):
        return self.shape_param * self.scale


class Beta(Distribution):
    def __init__(self, alpha=1.0, beta=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.alpha = mnp.array(alpha) if not hasattr(alpha, "_data") else alpha
        self.beta = mnp.array(beta) if not hasattr(beta, "_data") else beta

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, a, b):
            lbeta = (jax.lax.lgamma(a) + jax.lax.lgamma(b)
                     - jax.lax.lgamma(a + b))
            return (a - 1) * jnp.log(v) + (b - 1) * jnp.log1p(-v) - lbeta

        return _wrap(f, value, self.alpha, self.beta, name="beta_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.alpha)

        def f(a, b):
            return jr.beta(key, a, b, shape)

        return _wrap(f, self.alpha, self.beta, name="beta_sample")


class Poisson(Distribution):
    def __init__(self, rate=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.rate = mnp.array(rate) if not hasattr(rate, "_data") else rate

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, r):
            return v * jnp.log(r) - r - jax.lax.lgamma(v + 1)

        return _wrap(f, value, self.rate, name="poisson_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.rate)

        def f(r):
            return jr.poisson(key, r, shape).astype("float32")

        return _wrap(f, self.rate, name="poisson_sample")

    @property
    def mean(self):
        return self.rate


class Dirichlet(Distribution):
    def __init__(self, alpha, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        self.alpha = mnp.array(alpha) if not hasattr(alpha, "_data") else alpha

    def log_prob(self, value):
        import jax
        jnp = _jnp()

        def f(v, a):
            lnorm = (jnp.sum(jax.lax.lgamma(a), -1)
                     - jax.lax.lgamma(jnp.sum(a, -1)))
            return jnp.sum((a - 1) * jnp.log(v), -1) - lnorm

        return _wrap(f, value, self.alpha, name="dirichlet_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(a):
            return jr.dirichlet(key, a, pre + tuple(a.shape[:-1]))

        return _wrap(f, self.alpha, name="dirichlet_sample")


class MultivariateNormal(Distribution):
    def __init__(self, loc, cov=None, scale_tril=None, **kwargs):
        from ... import numpy as mnp

        super().__init__(event_dim=1, **kwargs)
        if (cov is None) == (scale_tril is None):
            raise MXNetError("give exactly one of cov=/scale_tril=")
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self._cov = mnp.array(cov) if cov is not None \
            and not hasattr(cov, "_data") else cov
        self._tril = mnp.array(scale_tril) if scale_tril is not None \
            and not hasattr(scale_tril, "_data") else scale_tril

    @property
    def scale_tril(self):
        if self._tril is not None:
            return self._tril
        jnp = _jnp()
        return _wrap(lambda c: jnp.linalg.cholesky(c), self._cov,
                     name="cholesky")

    def log_prob(self, value):
        jnp = _jnp()
        tril = self.scale_tril

        def f(v, loc, L):
            d = loc.shape[-1]
            diff = v - loc
            sol = jnp.linalg.solve(L, diff[..., None])[..., 0]
            maha = jnp.sum(sol ** 2, -1)
            logdet = 2 * jnp.sum(jnp.log(jnp.diagonal(L, axis1=-2,
                                                      axis2=-1)), -1)
            return -0.5 * (maha + d * math.log(2 * math.pi) + logdet)

        return _wrap(f, value, self.loc, tril, name="mvn_logp")

    def sample(self, size=None):
        jr = _jr()
        jnp = _jnp()
        key = _rng.next_key()
        tril = self.scale_tril
        pre = (tuple(size) if isinstance(size, (tuple, list))
               else ((size,) if size else ()))

        def f(loc, L):
            eps = jr.normal(key, pre + tuple(loc.shape))
            return loc + jnp.einsum("...ij,...j->...i", L, eps)

        return _wrap(f, self.loc, tril, name="mvn_sample")

    @property
    def mean(self):
        return self.loc


# -- KL divergence registry (reference ``divergence/``) ----------------------

_KL_REGISTRY = {}


def register_kl(p_cls, q_cls):
    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def kl_divergence(p, q):
    fn = _KL_REGISTRY.get((type(p), type(q)))
    if fn is None:
        raise MXNetError(
            f"no KL registered for ({type(p).__name__}, "
            f"{type(q).__name__})")
    return fn(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    from ... import numpy as mnp

    var_ratio = (p.scale / q.scale) ** 2
    t1 = ((p.loc - q.loc) / q.scale) ** 2
    return 0.5 * (var_ratio + t1 - 1 - mnp.log(var_ratio))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    jnp = _jnp()

    def f(pp, qp):
        eps = 1e-7
        pp = jnp.clip(pp, eps, 1 - eps)
        qp = jnp.clip(qp, eps, 1 - eps)
        return (pp * (jnp.log(pp) - jnp.log(qp))
                + (1 - pp) * (jnp.log1p(-pp) - jnp.log1p(-qp)))

    return _wrap(f, p.prob, q.prob, name="kl_bernoulli")


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    import jax
    jnp = _jnp()

    def f(pl, ql):
        plog = jax.nn.log_softmax(pl, -1)
        qlog = jax.nn.log_softmax(ql, -1)
        return jnp.sum(jnp.exp(plog) * (plog - qlog), -1)

    return _wrap(f, p.logit, q.logit, name="kl_categorical")


class StudentT(Distribution):
    """Student's t (reference studentT.py)."""

    def __init__(self, df, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.df = mnp.array(df) if not hasattr(df, "_data") else df
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, df, loc, scale):
            import jax.scipy.special as jss

            z = (v - loc) / scale
            return (jss.gammaln((df + 1) / 2) - jss.gammaln(df / 2)
                    - 0.5 * jnp.log(df * math.pi) - jnp.log(scale)
                    - (df + 1) / 2 * jnp.log1p(z ** 2 / df))

        return _wrap(f, value, self.df, self.loc, self.scale,
                     name="studentt_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc)

        def f(df, loc, scale):
            return loc + scale * jr.t(key, df, shape)

        return _wrap(f, self.df, self.loc, self.scale, name="studentt_sample")

    @property
    def mean(self):
        return self.loc

    @property
    def variance(self):
        from ... import numpy as mnp

        return self.scale ** 2 * self.df / (self.df - 2)


class Cauchy(Distribution):
    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            z = (v - loc) / scale
            return -jnp.log(math.pi * scale * (1 + z ** 2))

        return _wrap(f, value, self.loc, self.scale, name="cauchy_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc)

        def f(loc, scale):
            return loc + scale * jr.cauchy(key, shape)

        return _wrap(f, self.loc, self.scale, name="cauchy_sample")


class HalfNormal(Distribution):
    def __init__(self, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, scale):
            return (0.5 * math.log(2 / math.pi) - jnp.log(scale)
                    - v ** 2 / (2 * scale ** 2)
                    + jnp.where(v >= 0, 0.0, -jnp.inf))

        return _wrap(f, value, self.scale, name="halfnormal_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.scale)

        def f(scale):
            return _jnp().abs(scale * jr.normal(key, shape))

        return _wrap(f, self.scale, name="halfnormal_sample")

    @property
    def mean(self):
        return self.scale * math.sqrt(2 / math.pi)


class Chi2(Distribution):
    def __init__(self, df, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.df = mnp.array(df) if not hasattr(df, "_data") else df

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, df):
            import jax.scipy.special as jss

            k = df / 2
            return ((k - 1) * jnp.log(v) - v / 2 - jss.gammaln(k)
                    - k * math.log(2.0))

        return _wrap(f, value, self.df, name="chi2_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.df)

        def f(df):
            return 2.0 * jr.gamma(key, df / 2, shape)

        return _wrap(f, self.df, name="chi2_sample")

    @property
    def mean(self):
        return self.df

    @property
    def variance(self):
        return 2 * self.df


class Geometric(Distribution):
    """P(X=k) = (1-p)^k p, k = 0, 1, ... (failures before first success)."""

    def __init__(self, prob, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.prob = mnp.array(prob) if not hasattr(prob, "_data") else prob

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, p):
            return v * jnp.log1p(-p) + jnp.log(p)

        return _wrap(f, value, self.prob, name="geometric_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.prob)

        def f(p):
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return _jnp().floor(_jnp().log(u) / _jnp().log1p(-p))

        return _wrap(f, self.prob, name="geometric_sample")

    @property
    def mean(self):
        return (1 - self.prob) / self.prob


class Gumbel(Distribution):
    def __init__(self, loc=0.0, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.loc = mnp.array(loc) if not hasattr(loc, "_data") else loc
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, loc, scale):
            z = (v - loc) / scale
            return -(z + jnp.exp(-z)) - jnp.log(scale)

        return _wrap(f, value, self.loc, self.scale, name="gumbel_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.loc)

        def f(loc, scale):
            return loc + scale * jr.gumbel(key, shape)

        return _wrap(f, self.loc, self.scale, name="gumbel_sample")

    @property
    def mean(self):
        return self.loc + self.scale * 0.5772156649015329


class Weibull(Distribution):
    def __init__(self, concentration, scale=1.0, **kwargs):
        from ... import numpy as mnp

        super().__init__(**kwargs)
        self.concentration = mnp.array(concentration) \
            if not hasattr(concentration, "_data") else concentration
        self.scale = mnp.array(scale) if not hasattr(scale, "_data") else scale

    def log_prob(self, value):
        jnp = _jnp()

        def f(v, k, scale):
            z = v / scale
            return (jnp.log(k / scale) + (k - 1) * jnp.log(z) - z ** k)

        return _wrap(f, value, self.concentration, self.scale,
                     name="weibull_logp")

    def sample(self, size=None):
        jr = _jr()
        key = _rng.next_key()
        shape = self._shape(size, self.concentration)

        def f(k, scale):
            u = jr.uniform(key, shape, minval=1e-7, maxval=1.0)
            return scale * (-_jnp().log(u)) ** (1.0 / k)

        return _wrap(f, self.concentration, self.scale, name="weibull_sample")
