"""Constraints: argument/support validation for distributions.

Reference: ``python/mxnet/gluon/probability/distributions/constraint.py``
(548 LoC, 27 classes) — semantics ported, not code. The reference embeds a
``constraint_check`` op into the graph whose failure surfaces at engine
wait time; here validation is **eager**: ``check(value)`` computes the
condition with jax.numpy and raises ``ValueError`` immediately on
violation. Inside a jit trace the condition is abstract (no data), so the
check passes through unchanged — the same behavior as the reference's
symbolic mode, where the message only surfaces when executed. Cross-graph
dataflow ordering is XLA's job; there is no deferred-exception channel to
thread through.
"""
from __future__ import annotations

__all__ = ["Constraint", "Real", "Boolean",
           "Interval", "OpenInterval", "HalfOpenInterval", "UnitInterval",
           "IntegerInterval", "IntegerOpenInterval",
           "IntegerHalfOpenInterval",
           "GreaterThan", "GreaterThanEq", "IntegerGreaterThan",
           "IntegerGreaterThanEq",
           "LessThan", "LessThanEq", "IntegerLessThan", "IntegerLessThanEq",
           "Positive", "NonNegative", "PositiveInteger",
           "NonNegativeInteger",
           "Simplex", "LowerTriangular", "LowerCholesky",
           "PositiveDefinite", "Cat", "Stack",
           "dependent_property", "is_dependent"]


def _jnp():
    import jax.numpy as jnp

    return jnp


def _raw(value):
    """Underlying jax array (or scalar→array) of an NDArray/number."""
    jnp = _jnp()
    data = getattr(value, "_data", value)
    return jnp.asarray(data)


def _enforce(condition, value, err_msg):
    """Raise ``ValueError(err_msg)`` unless ``condition`` holds everywhere.
    Abstract (traced) conditions pass through: data-dependent raising is
    impossible under jit, exactly like the reference's symbolic mode."""
    import jax

    jnp = _jnp()
    cond = jnp.all(condition)
    if isinstance(cond, jax.core.Tracer):
        return value
    if not bool(cond):
        raise ValueError(err_msg)
    return value


class Constraint:
    """A region over which a variable is valid. ``check(value)`` returns
    ``value`` if valid, raises ``ValueError`` otherwise (reference
    ``constraint.py:34-51``)."""

    def check(self, value):
        raise NotImplementedError


class _Dependent(Constraint):
    """Placeholder for supports that depend on other variables
    (reference ``constraint.py:54-60``)."""

    def check(self, value):
        raise ValueError("Cannot validate dependent constraint")


def is_dependent(constraint):
    return isinstance(constraint, _Dependent)


class _DependentProperty(property, _Dependent):
    """``@dependent_property``: a ``_Dependent`` constraint on the class,
    an ordinary property on the instance (reference
    ``constraint.py:67-80``)."""


dependent_property = _DependentProperty


class Real(Constraint):
    """Real (NaN-free) tensor."""

    def check(self, value):
        v = _raw(value)
        return _enforce(
            v == v,  # noqa: PLR0124 — False exactly where v has NaNs
            value, f"Constraint violated: {value} should be a real tensor")


class Boolean(Constraint):
    """Constrain to ``{0, 1}``."""

    def check(self, value):
        v = _raw(value)
        return _enforce(
            (v == 0) | (v == 1), value,
            f"Constraint violated: {value} should be either 0 or 1.")


class Interval(Constraint):
    """Real interval ``[lower_bound, upper_bound]``."""

    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            (v >= self._lower_bound) & (v <= self._upper_bound), value,
            f"Constraint violated: {value} should be >= "
            f"{self._lower_bound} and <= {self._upper_bound}.")


class OpenInterval(Constraint):
    """Real interval ``(lower_bound, upper_bound)``."""

    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            (v > self._lower_bound) & (v < self._upper_bound), value,
            f"Constraint violated: {value} should be > "
            f"{self._lower_bound} and < {self._upper_bound}.")


class HalfOpenInterval(Constraint):
    """Real interval ``[lower_bound, upper_bound)``."""

    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            (v >= self._lower_bound) & (v < self._upper_bound), value,
            f"Constraint violated: {value} should be >= "
            f"{self._lower_bound} and < {self._upper_bound}.")


class UnitInterval(Interval):
    """``[0, 1]``."""

    def __init__(self):
        super().__init__(0, 1)


class _IntegerMixin:
    @staticmethod
    def _integral(v):
        return v % 1 == 0


class IntegerInterval(_IntegerMixin, Constraint):
    """Integer interval ``[lower_bound, upper_bound]``."""

    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v >= self._lower_bound)
            & (v <= self._upper_bound), value,
            f"Constraint violated: {value} should be integer and be >= "
            f"{self._lower_bound} and <= {self._upper_bound}.")


class IntegerOpenInterval(_IntegerMixin, Constraint):
    """Integer interval ``(lower_bound, upper_bound)``."""

    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v > self._lower_bound)
            & (v < self._upper_bound), value,
            f"Constraint violated: {value} should be integer and be > "
            f"{self._lower_bound} and < {self._upper_bound}.")


class IntegerHalfOpenInterval(_IntegerMixin, Constraint):
    """Integer interval ``[lower_bound, upper_bound)``."""

    def __init__(self, lower_bound, upper_bound):
        self._lower_bound = lower_bound
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v >= self._lower_bound)
            & (v < self._upper_bound), value,
            f"Constraint violated: {value} should be integer and be >= "
            f"{self._lower_bound} and < {self._upper_bound}.")


class GreaterThan(Constraint):
    """``value > lower_bound``."""

    def __init__(self, lower_bound):
        self._lower_bound = lower_bound

    def check(self, value):
        return _enforce(
            _raw(value) > self._lower_bound, value,
            f"Constraint violated: {value} should be greater than "
            f"{self._lower_bound}")


class GreaterThanEq(Constraint):
    """``value >= lower_bound``."""

    def __init__(self, lower_bound):
        self._lower_bound = lower_bound

    def check(self, value):
        return _enforce(
            _raw(value) >= self._lower_bound, value,
            f"Constraint violated: {value} should be greater than or "
            f"equal to {self._lower_bound}")


class LessThan(Constraint):
    """``value < upper_bound``."""

    def __init__(self, upper_bound):
        self._upper_bound = upper_bound

    def check(self, value):
        return _enforce(
            _raw(value) < self._upper_bound, value,
            f"Constraint violated: {value} should be less than "
            f"{self._upper_bound}")


class LessThanEq(Constraint):
    """``value <= upper_bound``."""

    def __init__(self, upper_bound):
        self._upper_bound = upper_bound

    def check(self, value):
        return _enforce(
            _raw(value) <= self._upper_bound, value,
            f"Constraint violated: {value} should be less than or equal "
            f"to {self._upper_bound}")


class IntegerGreaterThan(_IntegerMixin, Constraint):
    """Integer and ``> lower_bound``."""

    def __init__(self, lower_bound):
        self._lower_bound = lower_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v > self._lower_bound), value,
            f"Constraint violated: {value} should be integer and be "
            f"greater than {self._lower_bound}")


class IntegerGreaterThanEq(_IntegerMixin, Constraint):
    """Integer and ``>= lower_bound``."""

    def __init__(self, lower_bound):
        self._lower_bound = lower_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v >= self._lower_bound), value,
            f"Constraint violated: {value} should be integer and be "
            f"greater than or equal to {self._lower_bound}")


class IntegerLessThan(_IntegerMixin, Constraint):
    """Integer and ``< upper_bound``."""

    def __init__(self, upper_bound):
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v < self._upper_bound), value,
            f"Constraint violated: {value} should be integer and be less "
            f"than {self._upper_bound}")


class IntegerLessThanEq(_IntegerMixin, Constraint):
    """Integer and ``<= upper_bound``."""

    def __init__(self, upper_bound):
        self._upper_bound = upper_bound

    def check(self, value):
        v = _raw(value)
        return _enforce(
            self._integral(v) & (v <= self._upper_bound), value,
            f"Constraint violated: {value} should be integer and be less "
            f"than or equal to {self._upper_bound}")


class Positive(GreaterThan):
    """``> 0``."""

    def __init__(self):
        super().__init__(0)


class NonNegative(GreaterThanEq):
    """``>= 0``."""

    def __init__(self):
        super().__init__(0)


class PositiveInteger(IntegerGreaterThan):
    """Positive integer."""

    def __init__(self):
        super().__init__(0)


class NonNegativeInteger(IntegerGreaterThanEq):
    """Non-negative integer."""

    def __init__(self):
        super().__init__(0)


class Simplex(Constraint):
    """Rightmost dimension lies on a simplex: ``x >= 0``,
    ``x.sum(-1) == 1``."""

    def check(self, value):
        jnp = _jnp()
        v = _raw(value)
        cond = jnp.all(v >= 0, axis=-1) \
            & (jnp.abs(v.sum(-1) - 1) < 1e-6)
        return _enforce(
            cond, value,
            f"Constraint violated: {value} should be >= 0 and its "
            f"rightmost dimension should sum up to 1")


class LowerTriangular(Constraint):
    """Square lower-triangular matrices."""

    def check(self, value):
        jnp = _jnp()
        v = _raw(value)
        return _enforce(
            jnp.tril(v) == v, value,
            f"Constraint violated: {value} should be square lower "
            f"triangular matrices")


class LowerCholesky(Constraint):
    """Lower-triangular with positive diagonal."""

    def check(self, value):
        jnp = _jnp()
        v = _raw(value)
        cond = jnp.all(jnp.tril(v) == v, axis=-1) \
            & (jnp.diagonal(v, axis1=-2, axis2=-1) > 0)
        return _enforce(
            cond, value,
            f"Constraint violated: {value} should be square lower "
            f"triangular matrices with real and positive diagonal entries")


class PositiveDefinite(Constraint):
    """Symmetric positive-definite matrices. The reference checks
    ``eigvals > 0``; a Cholesky probe is the TPU-native equivalent
    (eigvals of a non-symmetric general matrix is complex and unsupported
    on accelerators), but eager host eigvals keeps exact parity here."""

    def check(self, value):
        import numpy as onp

        jnp = _jnp()
        v = _raw(value)
        sym = jnp.all(jnp.abs(v - jnp.swapaxes(v, -1, -2)) < 1e-5)
        import jax

        if isinstance(sym, jax.core.Tracer):
            return value  # traced: pass through (see module docstring)
        if not bool(sym):
            raise ValueError(
                f"Constraint violated: {value} should be positive "
                f"definite matrices")
        eig = onp.linalg.eigvalsh(onp.asarray(v))
        if not bool((eig > 0).all()):
            raise ValueError(
                f"Constraint violated: {value} should be positive "
                f"definite matrices")
        return value


class Cat(Constraint):
    """Apply ``constraint_seq`` to consecutive submatrices of sizes
    ``lengths`` along ``axis`` (compatible with ``np.concatenate``)."""

    def __init__(self, constraint_seq, axis=0, lengths=None):
        assert all(isinstance(c, Constraint) for c in constraint_seq)
        self._constraint_seq = list(constraint_seq)
        if lengths is None:
            lengths = [1] * len(self._constraint_seq)
        self._lengths = list(lengths)
        assert len(self._lengths) == len(self._constraint_seq), \
            f"The number of lengths {len(self._lengths)} should be equal " \
            f"to number of constraints {len(self._constraint_seq)}"
        self._axis = axis

    def check(self, value):
        jnp = _jnp()
        v = _raw(value)
        start = 0
        pieces = []
        for length, con in zip(self._lengths, self._constraint_seq):
            piece = jnp.take(v, jnp.arange(start, start + length),
                             axis=self._axis)
            con.check(piece)
            pieces.append(piece)
            start += length
        out = jnp.concatenate(pieces, self._axis)
        return value if hasattr(value, "_data") else out


class Stack(Constraint):
    """Apply ``constraint_seq`` along ``axis`` slices (compatible with
    ``np.stack``). Eager-only, like the reference."""

    def __init__(self, constraint_seq, axis=0):
        assert all(isinstance(c, Constraint) for c in constraint_seq)
        self._constraint_seq = list(constraint_seq)
        self._axis = axis

    def check(self, value):
        import jax

        jnp = _jnp()
        v = _raw(value)
        if isinstance(v, jax.core.Tracer):
            raise AssertionError(
                "Stack constraint is only supported when hybridization "
                "is turned off")
        size = v.shape[self._axis]
        for i, con in enumerate(self._constraint_seq[:size]):
            con.check(jnp.squeeze(
                jnp.take(v, jnp.asarray([i]), axis=self._axis),
                axis=self._axis))
        return value
