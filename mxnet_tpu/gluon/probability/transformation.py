"""Bijective transformations + TransformedDistribution.

Reference: ``python/mxnet/gluon/probability/transformation/transformation.py``
(part of the 5,516-LoC probability package). Each transformation knows its
forward map, inverse, and log|det J|, composing into reparameterized
distributions — all jnp-traceable so transformed samples flow through jit
and autograd.
"""
from __future__ import annotations

from ...base import MXNetError
from ...ndarray.ndarray import NDArray
from .distributions import Distribution, _data, _wrap


def _as_nd(x):
    return x if isinstance(x, NDArray) else NDArray(x)


def _jnp():
    import jax.numpy as jnp

    return jnp


class Transformation:
    """Bijection y = f(x) with tractable inverse and log-det-Jacobian."""

    bijective = True
    sign = 1  # sign of the Jacobian determinant (for CDF transforms)

    def __call__(self, x):
        return _wrap(self._forward, _as_nd(x), name=type(self).__name__)

    def inv(self, y):
        return _wrap(self._inverse, _as_nd(y),
                     name=type(self).__name__ + "_inv")

    def log_det_jacobian(self, x, y):
        return _wrap(self._log_det, _as_nd(x), _as_nd(y),
                     name=type(self).__name__ + "_ldj")

    # subclass hooks on raw jnp arrays
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _log_det(self, x, y):
        raise NotImplementedError


class ExpTransform(Transformation):
    """y = exp(x)."""

    def _forward(self, x):
        return _jnp().exp(x)

    def _inverse(self, y):
        return _jnp().log(y)

    def _log_det(self, x, y):
        return x


class AffineTransform(Transformation):
    """y = loc + scale * x."""

    def __init__(self, loc=0.0, scale=1.0):
        self.loc = _data(loc)
        self.scale = _data(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _log_det(self, x, y):
        jnp = _jnp()
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), jnp.shape(x))


class PowerTransform(Transformation):
    """y = x ** exponent (x > 0)."""

    def __init__(self, exponent):
        self.exponent = _data(exponent)

    def _forward(self, x):
        return x ** self.exponent

    def _inverse(self, y):
        return y ** (1.0 / self.exponent)

    def _log_det(self, x, y):
        jnp = _jnp()
        return jnp.log(jnp.abs(self.exponent * y / x))


class SigmoidTransform(Transformation):
    """y = 1 / (1 + exp(-x))."""

    def _forward(self, x):
        import jax

        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        jnp = _jnp()
        return jnp.log(y) - jnp.log1p(-y)

    def _log_det(self, x, y):
        import jax

        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class AbsTransform(Transformation):
    """y = |x| — not bijective; inverse picks the positive branch."""

    bijective = False

    def _forward(self, x):
        return _jnp().abs(x)

    def _inverse(self, y):
        return y

    def _log_det(self, x, y):
        return _jnp().zeros_like(x)


class SoftmaxTransform(Transformation):
    """y = softmax(x) over the last axis (not bijective: simplex)."""

    bijective = False

    def _forward(self, x):
        import jax

        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return _jnp().log(y)

    def _log_det(self, x, y):
        raise MXNetError("SoftmaxTransform has no scalar log-det "
                         "(dimension-reducing)")


class ComposeTransform(Transformation):
    """f = parts[-1] ∘ ... ∘ parts[0]."""

    def __init__(self, parts):
        self.parts = list(parts)
        self.bijective = all(p.bijective for p in self.parts)

    def _forward(self, x):
        for p in self.parts:
            x = p._forward(x)
        return x

    def _inverse(self, y):
        for p in reversed(self.parts):
            y = p._inverse(y)
        return y

    def _log_det(self, x, y):
        jnp = _jnp()
        total = None
        cur = x
        for p in self.parts:
            nxt = p._forward(cur)
            ld = p._log_det(cur, nxt)
            total = ld if total is None else total + ld
            cur = nxt
        return total


class TransformedDistribution(Distribution):
    """Push a base distribution through a chain of transformations
    (reference transformed_distribution.py): log_prob uses the
    change-of-variables formula."""

    has_grad = True

    def __init__(self, base: Distribution, transforms):
        if isinstance(transforms, Transformation):
            transforms = [transforms]
        self.base = base
        self.transform = ComposeTransform(transforms)
        super().__init__()

    def sample(self, size=None):
        x = self.base.sample(size)
        return self.transform(x)

    def sample_n(self, n):
        return self.sample((n,))

    def log_prob(self, value):
        if not self.transform.bijective:
            raise MXNetError("log_prob needs a bijective transform chain")

        def f(v):
            x = self.transform._inverse(v)
            ld = self.transform._log_det(x, v)
            base_lp = _data(self.base.log_prob(NDArray(x)))
            return base_lp - ld

        return _wrap(f, _as_nd(value), name="transformed_log_prob")
