"""Exponential family (reference
``python/mxnet/gluon/probability/distributions/exp_family.py``).

The class itself lives in ``distributions.py`` (its members — Normal,
Bernoulli, Exponential, Gamma, Beta, Dirichlet, Poisson — subclass it at
definition time); this module mirrors the reference layout for imports
like ``from ...probability.exp_family import ExponentialFamily``.
"""
from .distributions import ExponentialFamily

__all__ = ["ExponentialFamily"]
