"""StochasticBlock (reference:
``python/mxnet/gluon/probability/block/stochastic_block.py``): a HybridBlock
that can collect intermediate losses (e.g. KL terms) during forward."""
from __future__ import annotations

from ..block import HybridBlock
from ..nn.basic_layers import HybridSequential


class StochasticBlock(HybridBlock):
    """Adds ``add_loss``/``losses`` to HybridBlock for ELBO-style training."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._losses = []
        self._losscache = []

    def add_loss(self, loss):
        self._losscache.append(loss)

    @property
    def losses(self):
        return self._losses

    def __call__(self, *args, **kwargs):
        self._losscache = []
        out = super().__call__(*args, **kwargs)
        self._losses = self._losscache
        return out


class StochasticSequential(StochasticBlock):
    """Sequential whose children's collected losses aggregate."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._layers = []

    def add(self, *blocks):
        for b in blocks:
            self._layers.append(b)
            self.register_child(b, str(len(self._layers) - 1))

    def forward(self, x):
        for layer in self._layers:
            x = layer(x)
            if isinstance(layer, StochasticBlock):
                for l in layer.losses:
                    self.add_loss(l)
        return x
