"""Gluon Trainer — the data-parallel optimization driver.

Reference: ``python/mxnet/gluon/trainer.py:78-440`` — decides
``update_on_kvstore``, allreduces grads through the KVStore, then applies
the optimizer per parameter.

TPU redesign: ``step()`` = (1) optional grad allreduce via the KVStore
backend (identity on one device; psum over the mesh for ``dist_tpu_sync``),
(2) ONE jitted multi-tensor optimizer update over all parameters with donated
param/state buffers — the whole update is a single fused XLA executable,
playing the role of the reference's aggregated optimizer kernels
(``src/operator/optimizer_op.cc`` multi-tensor paths).
"""
from __future__ import annotations

from .. import autograd
from ..base import MXNetError
from ..kvstore import base as kv_base
from ..ndarray.ndarray import NDArray
from ..optimizer import Optimizer, create as create_optimizer
from .parameter import Parameter

# fault-injection hot-state (resilience.faults.FaultPlan slot, see
# ops/registry.py): None until a plan installs. The `trainer:grad` site is
# the one implementing the 'nan' kind — a matching rule poisons every
# parameter gradient before allreduce/update, which is how the numerical
# guardrails are exercised deterministically on CPU.
_FAULTS = None


def _guardrails():
    from ..resilience import guardrails

    return guardrails


class Trainer:
    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None,
                 loss_scaler=None, clip_global_norm=None):
        if isinstance(params, (dict,)):
            self._ordered_names = list(params.keys())
            params = list(params.values())
        else:
            params = list(params)
            self._ordered_names = [p.name for p in params]
        for p in params:
            if not isinstance(p, Parameter):
                raise MXNetError("Trainer expects Parameters")
        self._params = [p for p in params if p.grad_req != "null"]
        self._compression_params = compression_params
        optimizer_params = optimizer_params or {}
        self._optimizer: Optimizer = (
            create_optimizer(optimizer, **optimizer_params)
            if isinstance(optimizer, str) else optimizer)
        self._optimizer.param_dict = dict(enumerate(self._params))
        self._scale = self._optimizer.rescale_grad
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False
        self._states = None
        self._fused = None
        self._step_count = 0
        # numerical guardrails (resilience.guardrails / amp.LossScaler):
        # both default off — a trainer that uses neither pays one `is
        # None` test per step for each
        self._loss_scaler = loss_scaler
        if clip_global_norm is not None and not clip_global_norm > 0:
            raise MXNetError(
                f"clip_global_norm must be > 0, got {clip_global_norm}")
        self._clip_global_norm = clip_global_norm
        self._grad_fault_checked = False
        # gradient-bucketing plan cache (MXNET_KVSTORE_BUCKET_MB): built
        # lazily from the params, NOT the store, so it survives
        # rebind_kvstore across an elastic restart
        self._bucket_plan = None

    # -- properties -------------------------------------------------------
    @property
    def learning_rate(self):
        return self._optimizer.learning_rate

    @property
    def optimizer(self):
        return self._optimizer

    @property
    def loss_scaler(self):
        return self._loss_scaler

    def set_loss_scaler(self, scaler):
        """Attach (or detach with ``None``) a dynamic ``amp.LossScaler``:
        the trainer then checks the all-reduced grads each step, skips the
        update + scales down on overflow, and unscales inside the fused
        update otherwise."""
        self._loss_scaler = scaler

    def scale_loss(self, loss):
        """Scale one loss (or a list) by the attached scaler before
        ``backward`` — identity when no scaler is attached."""
        if self._loss_scaler is None:
            return loss
        from ..amp import scale_loss as _scale

        return _scale(loss, self._loss_scaler)

    def set_learning_rate(self, lr):
        self._optimizer.set_learning_rate(lr)

    # -- kvstore ----------------------------------------------------------
    def _init_kvstore(self):
        if self._kv_initialized:
            return
        kvstore = self._kvstore_type
        if kvstore is None:
            self._kvstore = None
            self._update_on_kvstore = False
        else:
            kv = kv_base.create(kvstore) if isinstance(kvstore, str) else kvstore
            self._kvstore = kv
            if self._update_on_kvstore is None:
                # reference default: update on kvstore iff backend supports it
                # and multi-device replicas exist; native TPU path updates on
                # worker (identical replicas after allreduce)
                self._update_on_kvstore = False
            if self._compression_params and hasattr(kv, "set_gradient_compression"):
                kv.set_gradient_compression(self._compression_params)
            if self._update_on_kvstore:
                if not kv.is_capable(kv_base.KVStoreBase.OPTIMIZER):
                    raise MXNetError(
                        f"kvstore {kv.type} cannot run the optimizer")
                kv.set_optimizer(self._optimizer)
                for i, p in enumerate(self._params):
                    kv.init(i, p.data())
        self._kv_initialized = True

    @property
    def kvstore(self):
        self._init_kvstore()
        return self._kvstore

    def rebind_kvstore(self, kvstore):
        """Swap the gradient-reduction backend mid-run (elastic restart:
        the old store's mesh lost a device group; the new store was built
        on the surviving mesh). The optimizer, states, step count, and
        gradient bucket plan (keyed by the params, not the store) are
        untouched — only the reduction path changes."""
        if self._update_on_kvstore:
            raise MXNetError(
                "rebind_kvstore is not supported with update_on_kvstore "
                "(the optimizer state lives on the store being replaced)")
        self._kvstore = kvstore
        self._kvstore_type = kvstore
        self._kv_initialized = True

    # -- state ------------------------------------------------------------
    def _init_states(self):
        if self._states is None:
            self._states = [
                self._optimizer.create_state_multi_precision(i, p.data())
                for i, p in enumerate(self._params)
            ]

    # -- core step --------------------------------------------------------
    def step(self, batch_size, ignore_stale_grad=False):
        """allreduce grads, then optimizer update; grads scaled by
        ``rescale_grad/batch_size`` (reference semantics).

        With a ``loss_scaler`` attached every grad replica is sentinel
        -checked before the allreduce: overflow ⇒ the update is skipped
        and the scale halves (the grads carry ``loss_scale`` from the
        scaled backward, so any inf/nan there is the overflow signal, and
        skipping pre-collective keeps it out of the NaN quarantine); a
        clean step folds the unscale into the update's rescale factor. With
        ``clip_global_norm`` set the grads are globally norm-clipped
        (threshold expressed in *unscaled* units) before the update.
        """
        self._init_kvstore()
        # the estimator's batch processor evaluates the site right after
        # backward (so pre-step sentinels see the corruption); only plain
        # training loops reach it here
        if not self._grad_fault_checked:
            self.check_grad_faults()
        self._grad_fault_checked = False
        if self._update_on_kvstore:
            if self._loss_scaler is not None \
                    or self._clip_global_norm is not None:
                # the server-side update path never sees the scaler's
                # unscale/overflow check or the clip — pushing scaled
                # grads would apply updates loss_scale-times too large,
                # silently
                raise MXNetError(
                    "loss_scaler/clip_global_norm are not supported with "
                    "update_on_kvstore=True (the optimizer runs on the "
                    "store, past the guardrails); update on worker "
                    "instead")
            # optimizer runs on the store (reference server-side update):
            # push grads, pull updated weights — no local update
            self._optimizer.rescale_grad = self._scale / batch_size
            for i, p in enumerate(self._params):
                kv = self._kvstore
                kv.pushpull(i, p.list_grad(), out=p.list_data())
            return
        scaler = self._loss_scaler
        if scaler is not None:
            gr = _guardrails()
            # the scale the backward actually used — captured BEFORE
            # update() may grow it at a window boundary
            cur_scale = scaler.loss_scale
            # overflow check BEFORE the allreduce: NaN on any replica
            # would be NaN on all of them after the collective anyway,
            # and skipping here keeps a scaler-managed overflow out of
            # the dist_tpu NaN quarantine (which would otherwise raise
            # before scaler.update ever ran — the scale would never
            # adapt)
            grads = []
            for p in self._params:
                grads.extend(p.list_grad())
            overflow = not gr.all_finite(grads)
            if scaler.update(overflow):
                from ..profiler import core as _prof
                from ..resilience import counters as _counters

                _counters.incr("resilience.loss_scale_overflows")
                if _prof.ENABLED:
                    _prof.record_instant(
                        "resilience::loss_scale(overflow)", "resilience",
                        args={"new_scale": scaler.loss_scale})
                return  # grads are garbage; next backward overwrites them
            self._allreduce_grads()
            self._apply_global_clip(scale_factor=cur_scale)
            # fold the unscale into the fused update's single multiply
            self._update(batch_size * cur_scale, ignore_stale_grad)
            self._check_param_faults()
            return
        self._allreduce_grads()
        self._apply_global_clip()
        self._update(batch_size, ignore_stale_grad)
        self._check_param_faults()

    def _apply_global_clip(self, scale_factor=1.0):
        if self._clip_global_norm is None:
            return
        # grads still carry the loss scale here, so the threshold (given
        # in unscaled units) is scaled up to match
        _guardrails().clip_by_global_norm(
            [p.grad() for p in self._params],
            self._clip_global_norm * scale_factor)

    def check_grad_faults(self):
        """Evaluate the ``trainer:grad`` fault site once per step: a
        matching ``nan`` rule poisons every gradient replica the way a bad
        bf16 kernel / overflowed backward would, so guardrail recovery is
        testable end to end on CPU. The estimator's ``fit_batch`` calls
        this right after ``backward`` (the poison must exist *before* the
        pre-step sentinels run); ``step()`` calls it for plain loops and
        skips it when the processor already did."""
        self._grad_fault_checked = True
        flt = _FAULTS
        if flt is not None and flt.check(
                "trainer:grad", {"step": self._step_count}) == "nan":
            self._poison_grads()

    def _poison_grads(self):
        import jax.numpy as jnp

        for p in self._params:
            for g in p.list_grad():
                g._set_data_internal(jnp.full_like(g._data, jnp.nan))

    def _check_param_faults(self):
        """Evaluate the ``trainer:param`` fault site after the optimizer
        update: a matching ``param_corrupt`` rule perturbs ONE replica's
        parameter copies — finite but drifted, the silent single-replica
        divergence the desync audit (``resilience.elastic``) exists to
        catch. No plan installed: one slot test per step."""
        flt = _FAULTS
        if flt is None:
            return
        mk = flt.check("trainer:param", {"step": self._step_count})
        if isinstance(mk, dict) and mk.get("kind") == "param_corrupt":
            self._corrupt_replica(int(mk.get("replica", 0)))

    def _corrupt_replica(self, replica):
        """Drift replica ``replica``'s parameter copies by a small finite
        perturbation (×(1+2^-10)+2^-10): large enough that a parameter
        fingerprint can never collide, small enough that training stays
        finite until the audit catches it."""
        for p in self._params:
            datas = p.list_data()
            if replica >= len(datas):
                continue
            d = datas[replica]
            d._set_data_internal(d._data * (1.0 + 2.0 ** -10) + 2.0 ** -10)

    def allreduce_grads(self):
        self._init_kvstore()
        if self._update_on_kvstore:
            raise MXNetError(
                "allreduce_grads() is not applicable when update_on_kvstore")
        self._allreduce_grads()

    def _allreduce_grads(self):
        # NOTE: compression is NOT applied here — set_gradient_compression
        # installed it on the store, and dist_tpu.pushpull quantizes each
        # replica (with per-(key, replica) error feedback) before the
        # reduce. The old Trainer-side branch pushed packed uint8 buffers
        # at float outs, which summed the *codes*.
        kv = self._kvstore
        if kv is None:
            return
        from .. import config as _cfg

        bucket_mb = float(_cfg.get("MXNET_KVSTORE_BUCKET_MB") or 0.0)
        if bucket_mb > 0 and self._allreduce_grads_bucketed(kv, bucket_mb):
            return
        for i, p in enumerate(self._params):
            grads = p.list_grad()
            if len(grads) > 1:
                # registration order ≈ forward order: the front layer's
                # grads are what the NEXT forward touches first, so they
                # carry the highest priority (higher settles first)
                kv.pushpull(i, grads, out=grads, priority=-i)

    def _grad_bucket_specs(self, bucket_mb):
        """(cached) bucket plan over the dense, multi-replica, floating
        grads, in registration order — deterministic, so every process
        builds the identical plan. Keyed by the params, not the store:
        it survives ``rebind_kvstore`` across an elastic restart."""
        if self._bucket_plan is not None \
                and self._bucket_plan[0] == bucket_mb:
            return self._bucket_plan[1], self._bucket_plan[2]
        import numpy as _onp

        from ..kvstore.bucketing import GradBucketer
        from ..ndarray.sparse import RowSparseNDArray

        items, index_of = [], {}
        for i, p in enumerate(self._params):
            grads = p.list_grad()
            if len(grads) < 2:
                continue
            g0 = grads[0]
            if isinstance(g0, RowSparseNDArray):
                continue
            dt = _onp.dtype(g0.dtype)
            if not _onp.issubdtype(dt, _onp.floating):
                continue
            items.append((str(i), tuple(g0.shape), dt))
            index_of[str(i)] = i
        specs = GradBucketer(bucket_mb=bucket_mb).plan(items)
        self._bucket_plan = (bucket_mb, specs, index_of)
        return specs, index_of

    def _allreduce_grads_bucketed(self, kv, bucket_mb):
        """Coalesced allreduce: registration-ordered grads packed into
        size-targeted fusion buffers, flushed front-layers-first, sliced
        back into the per-param grads. With ``MXNET_KVSTORE_OVERLAP`` on
        (default) all buckets go down in ONE grouped pushpull and the
        host never blocks between them — XLA's async dispatch overlaps
        the collectives; off, each bucket is flushed and synced in turn
        (the ablation baseline). Returns False when nothing is
        bucketable (single replica / sparse-only) so the caller falls
        back to the per-param path. Bitwise parity with the unbucketed
        path is by construction: concat + the same replica-ordered sum +
        slice touches each element with the identical add order."""
        specs, index_of = self._grad_bucket_specs(bucket_mb)
        if not specs:
            return False
        import time

        import jax
        import jax.numpy as jnp

        from .. import config as _cfg
        from ..kvstore import bucketing as _bk

        overlap = bool(_cfg.get("MXNET_KVSTORE_OVERLAP"))
        n_rep = len(self._params[index_of[specs[0].names[0]]].list_grad())
        t0 = time.perf_counter()
        keys, groups, prios = [], [], []
        bucketed_is = set()
        for spec in specs:
            vals = []
            for j in range(n_rep):
                parts = [self._params[index_of[nm]].list_grad()[j]
                         ._data.ravel() for nm in spec.names]
                pad = spec.total - spec.numel
                if pad:
                    parts.append(jnp.zeros((pad,), dtype=spec.dtype))
                vals.append(NDArray(jnp.concatenate(parts)
                                    if len(parts) > 1 else parts[0]))
            keys.append(spec.key)
            groups.append(vals)
            prios.append(spec.priority)
            _bk.record_flush(spec.nbytes)
            bucketed_is.update(index_of[nm] for nm in spec.names)
        if overlap:
            # one grouped dispatch; the store settles buckets by priority
            kv.pushpull(keys, groups, out=groups, priority=prios)
        else:
            for k, g, pr in zip(keys, groups, prios):
                kv.pushpull(k, g, out=g, priority=pr)
                jax.block_until_ready([nd._data for nd in g])
        # leftover multi-replica grads (sparse / non-float) reduce behind
        # the buckets on the per-param path
        for i, p in enumerate(self._params):
            if i in bucketed_is:
                continue
            grads = p.list_grad()
            if len(grads) > 1:
                kv.pushpull(i, grads, out=grads, priority=-i)
        if overlap:
            jax.block_until_ready(
                [nd._data for g in groups for nd in g])
        _bk.record_overlap_window_ms((time.perf_counter() - t0) * 1e3)
        # slice the reduced flat buffers back into the per-param grads
        for spec, g in zip(specs, groups):
            for j in range(n_rep):
                fd = g[j]._data
                for nm, off, size, shape in spec.items():
                    self._params[index_of[nm]].list_grad()[j] \
                        ._set_data_internal(
                            fd[off:off + size].reshape(shape))
        return True

    def update(self, batch_size, ignore_stale_grad=False):
        self._init_kvstore()
        self._update(batch_size, ignore_stale_grad)

    def _update(self, batch_size, ignore_stale_grad=False):  # pylint: disable=unused-argument
        self._init_states()
        scale = self._scale / batch_size
        opt = self._optimizer
        import numpy as _onp

        from ..ndarray.sparse import RowSparseNDArray

        sparse_is = {i for i, p in enumerate(self._params)
                     if isinstance(p.grad(), RowSparseNDArray)}
        # data-parallel replica count: >1 when parameters were initialized
        # on a context LIST (one replica per device). Every replica must
        # be stepped — updating only replica 0 would silently desync the
        # mesh (exactly the drift the desync audit exists to catch).
        n_rep = max((len(p._data) for p in self._params), default=1)
        if n_rep > 1 and any(len(p._data) != n_rep for p in self._params):
            raise MXNetError(
                "multi-replica update: parameters carry inconsistent "
                f"replica counts {[len(p._data) for p in self._params]} — "
                "initialize every parameter on the same context list")
        if sparse_is:
            # row-sparse grads take the per-param lazy path (reading them
            # through the fused jit would densify); dense params continue
            # through the fused executable below
            self._step_count += 1
            prev_rescale = opt.rescale_grad
            opt.rescale_grad = scale
            try:
                for i in sorted(sparse_is):
                    p = self._params[i]
                    opt._index_update_count[i] = self._step_count - 1
                    opt.update_multi_precision(i, p.data(), p.grad(),
                                               self._states[i])
            finally:
                opt.rescale_grad = prev_rescale
            self._step_count -= 1  # dense path below re-advances it
            if len(sparse_is) == len(self._params):
                self._step_count += 1
                return

        fused_safe = getattr(opt, "fused_safe", True) and not (
            opt.multi_precision
            and any(p.dtype == _onp.float16 for p in self._params))
        if n_rep > 1 and (sparse_is or not fused_safe):
            raise MXNetError(
                "multi-replica (data-parallel context list) training is "
                "only supported through the fused dense update path; "
                "sparse grads or fused_safe=False optimizers would update "
                "replica 0 only and silently desync the others")
        if not fused_safe:
            # eager per-param path (reference semantics; needed for
            # optimizers with python-side state or per-step RNG). The
            # optimizer applies rescale_grad itself in _prep_grad, so hand
            # it the combined scale instead of pre-multiplying.
            self._step_count += 1
            prev_rescale = opt.rescale_grad
            opt.rescale_grad = scale
            try:
                for i, p in enumerate(self._params):
                    if i in sparse_is:
                        continue  # already updated via the lazy path
                    opt.update_multi_precision(i, p.data(), p.grad(),
                                               self._states[i])
            finally:
                opt.rescale_grad = prev_rescale
            return
        # one fused jitted update across all params (multi-tensor path)
        import jax

        if getattr(self, "_fused_scale", None) != scale:
            self._fused = None  # batch size changed: rebuild closure
        if self._fused is None:
            def fused(pdatas, gdatas, sdatas, lrs, wds, t):
                new_p = []
                new_s = []
                for pd, gd, sd, lr, wd in zip(pdatas, gdatas, sdatas, lrs, wds):
                    # ordering contract: rescale THEN clip, exactly like
                    # Optimizer._prep_grad on the non-fused path — the two
                    # paths must produce identical updates for the same
                    # grads (regression:
                    # tests/test_guardrails.py::test_fused_vs_eager_clip_ordering_parity)
                    g = gd.astype(pd.dtype) * scale
                    if opt.clip_gradient is not None:
                        import jax.numpy as jnp

                        g = jnp.clip(g, -opt.clip_gradient, opt.clip_gradient)
                    np_, ns_ = opt._update_raw(pd, g, sd, lr, wd, t)
                    new_p.append(np_)
                    new_s.append(ns_)
                return new_p, new_s

            self._fused = jax.jit(fused, donate_argnums=(0, 2))
            self._fused_scale = scale

        self._step_count += 1
        t = self._step_count
        dense_is = [i for i in range(len(self._params))
                    if i not in sparse_is]
        for i in range(len(self._params)):
            opt._index_update_count[i] = t
        pdatas = [self._params[i].data()._data for i in dense_is]
        gdatas = [self._params[i].grad()._data for i in dense_is]

        if n_rep > 1:
            # an elastic restart can re-home replica 0 onto a different
            # device than the states were created on (the killed chip
            # WAS device 0): migrate each single-device state buffer to
            # its param's device — jit refuses mixed placements. Same
            # -device (the steady state) is an identity; the single
            # -replica path below never pays this scan.
            def _colocated_state(i, pd):
                out = []
                for s in _flatten_state(self._states[i]):
                    d = s._data
                    try:
                        devs = d.devices()
                    except AttributeError:
                        devs = None
                    if devs is not None and len(devs) == 1 \
                            and pd is not None \
                            and next(iter(devs)) != pd:
                        import jax as _jx0

                        d = _jx0.device_put(d, pd)
                    out.append(d)
                return tuple(out)

            pdevs = [next(iter(pd.devices())) if len(pd.devices()) == 1
                     else None for pd in pdatas]
            sdatas = [_colocated_state(i, pdev)
                      for i, pdev in zip(dense_is, pdevs)]
        else:
            sdatas = [tuple(s._data
                            for s in _flatten_state(self._states[i]))
                      for i in dense_is]
        lrs = [opt._get_lr(i) for i in dense_is]
        wds = [opt._get_wd(i) for i in dense_is]
        # replicas 1..R-1 step through the SAME fused executable on their
        # own devices with their own (identical, post-allreduce) grads —
        # the classic per-device update, so replicas stay bitwise in sync
        # and a corrupted replica drifts honestly instead of being
        # papered over by a broadcast. Inputs are staged BEFORE the
        # replica-0 call: that call donates the state buffers, and the
        # other replicas need the PRE-update state values (each computes
        # the identical new state on its own device). The optimizer
        # state is deliberately re-staged from the replica-0 copy every
        # step rather than cached per replica: the canonical copy is the
        # single source of truth that checkpoint rewind/resume restores,
        # and a per-replica cache going stale after such a restore would
        # desync the replicas through their states — the exact failure
        # the desync audit exists to catch. (R-1) small transfers per
        # step is the price of that invariant.
        rep_inputs = []
        if n_rep > 1:
            import jax as _jx
            for j in range(1, n_rep):
                pj = [self._params[i].list_data()[j]._data for i in dense_is]
                gj = [self._params[i].list_grad()[j]._data for i in dense_is]
                dev_j = next(iter(pj[0].devices())) if pj else None
                sj = [tuple(_jx.device_put(s._data, dev_j)
                            for s in _flatten_state(self._states[i]))
                      for i in dense_is]
                rep_inputs.append((j, pj, gj, sj))
        new_p, new_s = self._fused(pdatas, gdatas, sdatas, lrs, wds, t)
        for i, np_ in zip(dense_is, new_p):
            self._params[i].data()._set_data_internal(np_)
        for i, ns in zip(dense_is, new_s):
            for s, nsd in zip(_flatten_state(self._states[i]), ns):
                s._set_data_internal(nsd)
        for j, pj, gj, sj in rep_inputs:
            new_pj, _ = self._fused(pj, gj, sj, lrs, wds, t)
            for i, np_ in zip(dense_is, new_pj):
                self._params[i].list_data()[j]._set_data_internal(np_)

    # -- persistence ------------------------------------------------------
    # the byte-level pair below is THE states format: save_states /
    # load_states and the resilience checkpoint container both delegate
    # here, so the two can never drift apart
    def states_to_bytes(self) -> bytes:
        self._init_states()
        import pickle

        return pickle.dumps({
            "step": self._step_count,
            "states": [
                [s.asnumpy() for s in _flatten_state(st)] for st in self._states
            ],
        })

    def load_states_from_bytes(self, raw: bytes):
        self._init_states()
        import pickle

        blob = pickle.loads(raw)
        self._step_count = blob["step"]
        for st, arrs in zip(self._states, blob["states"]):
            for s, a in zip(_flatten_state(st), arrs):
                s._set_data_internal(NDArray(a)._data)

    def save_states(self, fname):
        with open(fname, "wb") as f:
            f.write(self.states_to_bytes())

    def load_states(self, fname):
        with open(fname, "rb") as f:
            self.load_states_from_bytes(f.read())


def _flatten_state(st):
    if st is None:
        return ()
    if isinstance(st, NDArray):
        return (st,)
    out = []
    for s in st:
        out.extend(_flatten_state(s))
    return tuple(out)
