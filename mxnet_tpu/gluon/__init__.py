"""Gluon — the imperative/hybrid modeling API (reference ``python/mxnet/gluon``)."""
from __future__ import annotations

from . import loss, metric, nn, utils
from .block import Block, HybridBlock, SymbolBlock
from .parameter import Constant, Parameter, ParameterDict, replica_context
from .trainer import Trainer


def __getattr__(name):
    # heavier submodules load lazily: data (multiprocessing), rnn (scan
    # layers), model_zoo (vision nets), contrib (estimator), probability
    import importlib

    if name in ("data", "rnn", "model_zoo", "contrib", "probability"):
        mod = importlib.import_module("." + name, __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'mxnet_tpu.gluon' has no attribute {name!r}")
