"""Gluon losses (reference ``python/mxnet/gluon/loss.py``, 15 classes)."""
from __future__ import annotations

import numpy as _onp

from ..base import MXNetError
from ..ndarray.ndarray import NDArray
from ..ops import nn as _nn
from ..ops.registry import apply as _apply
from .block import HybridBlock


def _jnp():
    import jax.numpy as jnp

    return jnp


def _reshape_like(pred, label):
    if isinstance(label, NDArray) and label.shape != pred.shape:
        return label.reshape(pred.shape)
    return label


def _apply_weighting(loss, weight=None, sample_weight=None):
    if sample_weight is not None:
        loss = loss * sample_weight
    if weight is not None:
        loss = loss * weight
    return loss


class Loss(HybridBlock):
    """Base loss: scalar weighting + batch-axis mean semantics."""

    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(**kwargs)
        self._weight = weight
        self._batch_axis = batch_axis

    def _mean_nonbatch(self, loss):
        axes = tuple(i for i in range(loss.ndim) if i != self._batch_axis)
        return loss.mean(axis=axes) if axes else loss

    def __repr__(self):
        return f"{type(self).__name__}(batch_axis={self._batch_axis})"


class L2Loss(Loss):
    def __init__(self, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = _jnp_square(pred - label)
        loss = _apply_weighting(loss, self._weight / 2, sample_weight)
        return self._mean_nonbatch(loss)


def _jnp_square(x):
    return x.square() if isinstance(x, NDArray) else x * x


class L1Loss(Loss):
    def __init__(self, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        loss = (pred - label).abs()
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class SoftmaxCrossEntropyLoss(Loss):
    """Softmax + CE fused (reference SoftmaxCrossEntropyLoss).

    ``sparse_label=True`` takes class indices; otherwise one-hot/probs.
    """

    def __init__(self, axis=-1, sparse_label=True, from_logits=False,
                 weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._axis = axis
        self._sparse_label = sparse_label
        self._from_logits = from_logits

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits and self._sparse_label:
            # fused sparse-label path: loss = logsumexp(z) - z[label].
            # Never materializes the (..., V) log-probability tensor — at
            # BERT's 30k-vocab MLM head the log_softmax+pick form costs
            # two extra HBM sweeps of a (B, T, V) array (profiled on v5e)
            def f(z, lab):
                import jax
                import jax.numpy as jnp

                lse = jax.nn.logsumexp(
                    z.astype(jnp.float32), axis=self._axis)
                picked = jnp.take_along_axis(
                    z, jnp.expand_dims(lab.astype(jnp.int32), self._axis),
                    axis=self._axis).squeeze(self._axis)
                return lse - picked.astype(jnp.float32)

            loss = _apply(f, (pred, label), name="softmax_ce_fused")
        else:
            if not self._from_logits:
                logp = _nn.log_softmax(pred, axis=self._axis)
            else:
                logp = pred
            if self._sparse_label:
                loss = -_nn.pick(logp, label, axis=self._axis,
                                 keepdims=False)
            else:
                label = _reshape_like(logp, label)
                loss = -(logp * label).sum(axis=self._axis)
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


SoftmaxCELoss = SoftmaxCrossEntropyLoss


class SigmoidBinaryCrossEntropyLoss(Loss):
    def __init__(self, from_sigmoid=False, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_sigmoid = from_sigmoid

    def forward(self, pred, label, sample_weight=None, pos_weight=None):
        label = _reshape_like(pred, label)
        if not self._from_sigmoid:
            def f(p, l, *pw):
                import jax

                jnp = _jnp()
                relu_neg = jnp.maximum(-p, 0.0)
                if pw:
                    w = 1.0 + (pw[0] - 1.0) * l
                    return (1.0 - l) * p + w * (
                        jnp.log1p(jnp.exp(-jnp.abs(p))) + relu_neg)
                return relu_neg + p * (1.0 - l) + jnp.log1p(jnp.exp(-jnp.abs(p)))

            args = (pred, label) + ((pos_weight,) if pos_weight is not None else ())
            loss = _apply(f, args, name="sigmoid_bce")
        else:
            eps = 1e-12

            def f(p, l, *pw):
                jnp = _jnp()
                if pw:
                    return -(jnp.log(p + eps) * l * pw[0]
                             + jnp.log(1 - p + eps) * (1 - l))
                return -(jnp.log(p + eps) * l + jnp.log(1 - p + eps) * (1 - l))

            args = (pred, label) + ((pos_weight,) if pos_weight is not None else ())
            loss = _apply(f, args, name="sigmoid_bce")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


SigmoidBCELoss = SigmoidBinaryCrossEntropyLoss


class KLDivLoss(Loss):
    def __init__(self, from_logits=True, axis=-1, weight=None, batch_axis=0,
                 **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._axis = axis

    def forward(self, pred, label, sample_weight=None):
        if not self._from_logits:
            pred = _nn.log_softmax(pred, axis=self._axis)

        def f(p, l):
            jnp = _jnp()
            return l * (jnp.log(l + 1e-12) - p)

        loss = _apply(f, (pred, label), name="kldiv")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class CTCLoss(Loss):
    def __init__(self, layout="NTC", label_layout="NT", weight=None, **kwargs):
        if layout not in ("NTC", "TNC"):
            raise MXNetError(f"bad layout {layout!r}")
        super().__init__(weight, 0, **kwargs)
        self._layout = layout
        self._label_layout = label_layout

    def forward(self, pred, label, pred_lengths=None, label_lengths=None,
                sample_weight=None):
        if self._layout == "NTC":
            pred = pred.swapaxes(0, 1)
        if self._label_layout == "TN":
            label = label.swapaxes(0, 1)
        loss = _nn.ctc_loss(pred, label, pred_lengths, label_lengths,
                            use_data_lengths=pred_lengths is not None,
                            use_label_lengths=label_lengths is not None,
                            blank_label="last")
        return _apply_weighting(loss, self._weight, sample_weight)


class HuberLoss(Loss):
    def __init__(self, rho=1.0, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._rho = rho

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        rho = self._rho

        def f(p, l):
            jnp = _jnp()
            d = jnp.abs(p - l)
            return jnp.where(d > rho, d - 0.5 * rho, (0.5 / rho) * d * d)

        loss = _apply(f, (pred, label), name="huber")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class HingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        m = self._margin

        def f(p, l):
            jnp = _jnp()
            return jnp.maximum(0.0, m - p * l)

        loss = _apply(f, (pred, label), name="hinge")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class SquaredHingeLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        m = self._margin

        def f(p, l):
            jnp = _jnp()
            return _jnp().square(jnp.maximum(0.0, m - p * l))

        loss = _apply(f, (pred, label), name="sq_hinge")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class LogisticLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, label_format="signed", **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        if label_format not in ("signed", "binary"):
            raise MXNetError(f"bad label_format {label_format!r}")
        self._label_format = label_format

    def forward(self, pred, label, sample_weight=None):
        label = _reshape_like(pred, label)
        fmt = self._label_format

        def f(p, l):
            jnp = _jnp()
            if fmt == "signed":
                l2 = (l + 1.0) / 2.0
            else:
                l2 = l
            return jnp.maximum(-p, 0.0) + p * (1.0 - l2) + jnp.log1p(jnp.exp(-jnp.abs(p)))

        loss = _apply(f, (pred, label), name="logistic")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return self._mean_nonbatch(loss)


class TripletLoss(Loss):
    def __init__(self, margin=1, weight=None, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, pred, positive, negative, sample_weight=None):
        m = self._margin

        def f(p, pos, neg):
            jnp = _jnp()
            d = jnp.sum(jnp.square(p - pos) - jnp.square(p - neg),
                        axis=tuple(range(1, p.ndim)))
            return jnp.maximum(d + m, 0.0)

        loss = _apply(f, (pred, positive, negative), name="triplet")
        return _apply_weighting(loss, self._weight, sample_weight)


class PoissonNLLLoss(Loss):
    def __init__(self, weight=None, from_logits=True, batch_axis=0,
                 compute_full=False, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._from_logits = from_logits
        self._compute_full = compute_full

    def forward(self, pred, label, sample_weight=None, epsilon=1e-08):
        label = _reshape_like(pred, label)
        from_logits = self._from_logits
        full = self._compute_full

        def f(p, l):
            jnp = _jnp()
            if from_logits:
                loss = jnp.exp(p) - l * p
            else:
                loss = p - l * jnp.log(p + epsilon)
            if full:
                stirling = (l * jnp.log(l + 1e-12) - l
                            + 0.5 * jnp.log(2.0 * _onp.pi * (l + 1e-12)))
                loss = loss + jnp.where(l > 1, stirling, 0.0)
            return loss

        loss = _apply(f, (pred, label), name="poisson_nll")
        loss = _apply_weighting(loss, self._weight, sample_weight)
        return loss.mean()


class CosineEmbeddingLoss(Loss):
    def __init__(self, weight=None, batch_axis=0, margin=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._margin = margin

    def forward(self, input1, input2, label, sample_weight=None):
        m = self._margin

        def f(a, b, l):
            jnp = _jnp()
            ab = jnp.sum(a * b, axis=-1)
            na = jnp.sqrt(jnp.sum(a * a, axis=-1) + 1e-12)
            nb = jnp.sqrt(jnp.sum(b * b, axis=-1) + 1e-12)
            cos = ab / (na * nb)
            lr = l.reshape(cos.shape)
            return jnp.where(lr == 1, 1.0 - cos, jnp.maximum(0.0, cos - m))

        loss = _apply(f, (input1, input2, label), name="cosine_embedding")
        return _apply_weighting(loss, self._weight, sample_weight)


class SDMLLoss(Loss):
    """Smoothed deep metric learning loss (reference SDMLLoss)."""

    def __init__(self, smoothing_parameter=0.3, weight=1.0, batch_axis=0, **kwargs):
        super().__init__(weight, batch_axis, **kwargs)
        self._sp = smoothing_parameter

    def forward(self, x1, x2):
        sp = self._sp

        def f(a, b):
            import jax

            jnp = _jnp()
            n = a.shape[0]
            dist = jnp.sqrt(
                jnp.sum(jnp.square(a[:, None, :] - b[None, :, :]), axis=-1) + 1e-12)
            neg_log = jax.nn.log_softmax(-dist, axis=1)
            smoothed = (1 - sp) * jnp.eye(n) + sp / max(n - 1, 1) * (1 - jnp.eye(n))
            return -jnp.sum(smoothed * neg_log, axis=1)

        return _apply(f, (x1, x2), name="sdml")
