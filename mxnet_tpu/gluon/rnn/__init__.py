"""Recurrent layers and cells (reference: ``python/mxnet/gluon/rnn/``)."""
from .rnn_cell import (
    BidirectionalCell,
    DropoutCell,
    GRUCell,
    HybridRecurrentCell,
    HybridSequentialRNNCell,
    LSTMCell,
    ModifierCell,
    RecurrentCell,
    ResidualCell,
    RNNCell,
    SequentialRNNCell,
    ZoneoutCell,
)
from .rnn_layer import GRU, LSTM, RNN
