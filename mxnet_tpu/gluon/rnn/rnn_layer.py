"""Fused recurrent layers (reference: ``python/mxnet/gluon/rnn/rnn_layer.py``
over the fused ``RNN`` op, ``src/operator/rnn.cc``).

``RNN``/``LSTM``/``GRU`` hold per-layer/direction ``{l,r}{i}_{i2h,h2h}_
{weight,bias}`` parameters (same naming as the reference so checkpoints map
1:1) and execute through :func:`mxnet_tpu.ops.rnn.rnn_fused` — input
projection hoisted to one MXU matmul per layer, recurrence in ``lax.scan``.
"""
from __future__ import annotations

from ... import random as _rng
from ...base import MXNetError
from ...ops import registry as _registry
from ...ops.rnn import rnn_fused
from ..block import HybridBlock
from ..parameter import Parameter


class _RNNLayer(HybridBlock):
    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, mode,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        if layout not in ("TNC", "NTC"):
            raise MXNetError(f"invalid layout {layout!r}; TNC or NTC")
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]

        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight", (ng * nh, ni),
                                     i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight", (ng * nh, nh),
                                     h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias", (ng * nh,),
                                     i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias", (ng * nh,),
                                     h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = Parameter(name, shape=shape, init=init)
        self._reg_params[name] = p
        setattr(self, name, p)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        from ... import numpy as mnp

        states = []
        for info in self.state_info(batch_size):
            if func is None:
                states.append(mnp.zeros(info["shape"], **kwargs))
            else:
                states.append(func(shape=info["shape"], **kwargs))
        return states

    def _materialize(self, input_size):
        ng, nh = self._gates, self._hidden_size
        ni = input_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                p = self._reg_params[f"{j}{i}_i2h_weight"]
                if 0 in p.shape:
                    p.shape = (ng * nh, ni)
            ni = nh * self._dir

    def forward(self, inputs, states=None):
        from ... import numpy as mnp

        self._materialize(inputs.shape[-1])
        skip_states = states is None
        batch_axis = self._layout.find("N")
        batch_size = inputs.shape[batch_axis]
        if skip_states:
            states = self.begin_state(batch_size)
        if self._layout == "NTC":
            inputs = mnp.swapaxes(inputs, 0, 1)

        h0 = states[0]
        c0 = states[1] if self._mode == "lstm" else None

        weights = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                for part in ("i2h_weight", "h2h_weight", "i2h_bias",
                             "h2h_bias"):
                    weights.append(self._reg_params[f"{j}{i}_{part}"].data())

        mode = self._mode
        L, D = self._num_layers, self._dir
        dropout = self._dropout
        from ... import autograd as _ag

        train = _ag.is_training()
        key = _rng.next_key() if (dropout > 0 and train) else None

        def f(x, h, *rest):
            if mode == "lstm":
                c, ws = rest[0], rest[1:]
            else:
                c, ws = None, rest
            out, h_T, c_T = rnn_fused(
                x, h, c, list(ws), mode, L, D == 2, dropout=dropout,
                train=train, rng_key=key)
            if c_T is None:
                return out, h_T
            return out, h_T, c_T

        args = ([inputs, h0, c0] if mode == "lstm" else [inputs, h0]) + weights
        res = _registry.apply(f, tuple(args), name=f"rnn_fused:{mode}")
        out = res[0]
        out_states = list(res[1:])
        if self._layout == "NTC":
            out = mnp.swapaxes(out, 0, 1)
        if skip_states:
            return out
        return out, out_states

    def __repr__(self):
        return (f"{type(self).__name__}({self._hidden_size}, "
                f"num_layers={self._num_layers}, layout={self._layout!r}, "
                f"bidirectional={self._dir == 2})")


class RNN(_RNNLayer):
    """Multi-layer Elman RNN with tanh/relu (reference ``rnn_layer.py:388``)."""

    def __init__(self, hidden_size, num_layers=1, activation="tanh",
                 layout="TNC", dropout=0, bidirectional=False, input_size=0,
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "rnn_" + activation,
                         **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """Multi-layer LSTM (reference ``rnn_layer.py:476``)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """Multi-layer GRU (reference ``rnn_layer.py:574``)."""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
