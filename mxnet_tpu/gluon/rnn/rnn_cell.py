"""Recurrent cells (reference: ``python/mxnet/gluon/rnn/rnn_cell.py``).

Cells are per-timestep HybridBlocks: ``cell(x_t, states) -> (out, states)``.
``unroll`` replays the cell over a time axis; under ``hybridize()`` the
unrolled ops trace into one XLA program. For long sequences prefer the fused
layers (``gluon.rnn.RNN/LSTM/GRU``) which lower to a single ``lax.scan``
(one XLA while-loop, compiled once regardless of length).

Gate layouts match the reference ops (``src/operator/rnn-inl.h``):
LSTM ``[i, f, c, o]``, GRU ``[r, z, n]``.
"""
from __future__ import annotations

from ...base import MXNetError
from ...ops import nn as _ops
from ..block import HybridBlock
from ..parameter import Parameter


class RecurrentCell(HybridBlock):
    """Base class for recurrent cells."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial states (zeros by default), one NDArray per state_info."""
        from ... import numpy as mnp

        assert not self._modified, (
            "After applying modifier cells the base cell cannot be called "
            "directly. Call the modifier cell instead.")
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            shape = info["shape"]
            if func is None:
                states.append(mnp.zeros(shape, **kwargs))
            else:
                states.append(func(shape=shape, **kwargs))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell ``length`` steps (reference ``rnn_cell.py:305``)."""
        from ... import numpy as mnp

        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        inputs_list = [
            x.squeeze(axis=axis)
            for x in mnp.split(inputs, length, axis=axis)
        ]
        batch_size = inputs_list[0].shape[batch_axis]
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)

        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs_list[i], states)
            outputs.append(output)
        if valid_length is not None:
            stacked = mnp.stack(outputs, axis=axis)
            outputs = _ops.sequence_mask(
                stacked, sequence_length=valid_length, use_sequence_length=True,
                axis=axis)
            if merge_outputs is False:
                outputs = [
                    x.squeeze(axis=axis)
                    for x in mnp.split(outputs, length, axis=axis)
                ]
        elif merge_outputs is None or merge_outputs:
            outputs = mnp.stack(outputs, axis=axis)
        return outputs, states

    def __call__(self, inputs, states, **kwargs):
        self._counter += 1
        return super().__call__(inputs, states, **kwargs)


class HybridRecurrentCell(RecurrentCell):
    pass


def _cell_fc(x, weight, bias):
    return _ops.fully_connected(x, weight, bias,
                                num_hidden=weight.shape[0],
                                no_bias=bias is None)


class RNNCell(HybridRecurrentCell):
    """Elman RNN cell: ``h' = act(W_ih x + b_ih + W_hh h + b_hh)``."""

    def __init__(self, hidden_size, activation="tanh", input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(hidden_size, input_size),
                                    init=i2h_weight_initializer)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def forward(self, inputs, states):
        if 0 in self.i2h_weight.shape:
            self.i2h_weight.shape = (self._hidden_size, inputs.shape[-1])
        i2h = _cell_fc(inputs, self.i2h_weight.data(), self.i2h_bias.data())
        h2h = _cell_fc(states[0], self.h2h_weight.data(), self.h2h_bias.data())
        output = _ops.activation(i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """LSTM cell (gates ``[i, f, c, o]``, reference ``rnn_cell.py:564``)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(4 * hidden_size, input_size),
                                    init=i2h_weight_initializer)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(4 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(4 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(4 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def forward(self, inputs, states):
        from ... import numpy as mnp

        if 0 in self.i2h_weight.shape:
            self.i2h_weight.shape = (4 * self._hidden_size, inputs.shape[-1])
        h = self._hidden_size
        gates = (_cell_fc(inputs, self.i2h_weight.data(), self.i2h_bias.data())
                 + _cell_fc(states[0], self.h2h_weight.data(),
                            self.h2h_bias.data()))
        in_gate = _ops.sigmoid(gates[..., 0:h])
        forget_gate = _ops.sigmoid(gates[..., h:2 * h])
        in_transform = _ops.tanh(gates[..., 2 * h:3 * h])
        out_gate = _ops.sigmoid(gates[..., 3 * h:4 * h])
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * _ops.tanh(next_c)
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """GRU cell (gates ``[r, z, n]``, reference ``rnn_cell.py:719``)."""

    def __init__(self, hidden_size, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(**kwargs)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = Parameter("i2h_weight",
                                    shape=(3 * hidden_size, input_size),
                                    init=i2h_weight_initializer)
        self.h2h_weight = Parameter("h2h_weight",
                                    shape=(3 * hidden_size, hidden_size),
                                    init=h2h_weight_initializer)
        self.i2h_bias = Parameter("i2h_bias", shape=(3 * hidden_size,),
                                  init=i2h_bias_initializer)
        self.h2h_bias = Parameter("h2h_bias", shape=(3 * hidden_size,),
                                  init=h2h_bias_initializer)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size), "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def forward(self, inputs, states):
        if 0 in self.i2h_weight.shape:
            self.i2h_weight.shape = (3 * self._hidden_size, inputs.shape[-1])
        h = self._hidden_size
        prev_h = states[0]
        i2h = _cell_fc(inputs, self.i2h_weight.data(), self.i2h_bias.data())
        h2h = _cell_fc(prev_h, self.h2h_weight.data(), self.h2h_bias.data())
        reset_gate = _ops.sigmoid(i2h[..., 0:h] + h2h[..., 0:h])
        update_gate = _ops.sigmoid(i2h[..., h:2 * h] + h2h[..., h:2 * h])
        next_h_tmp = _ops.tanh(i2h[..., 2 * h:3 * h]
                               + reset_gate * h2h[..., 2 * h:3 * h])
        next_h = (1.0 - update_gate) * next_h_tmp + update_gate * prev_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells sequentially (reference ``rnn_cell.py:843``)."""

    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        self._cells = []

    def add(self, cell):
        self._cells.append(cell)
        self.register_child(cell, str(len(self._cells) - 1))

    def state_info(self, batch_size=0):
        return _cells_state_info(self._cells, batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._cells, batch_size, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.extend(state)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            from ... import numpy as mnp  # noqa: F401 - shape probing

            batch_axis = layout.find("N")
            begin_state = self.begin_state(
                batch_size=inputs.shape[batch_axis])
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info())
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(states)
        return inputs, next_states

    def __getitem__(self, i):
        return self._cells[i]

    def __len__(self):
        return len(self._cells)


HybridSequentialRNNCell = SequentialRNNCell


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, batch_size, **kwargs):
    return sum([c.begin_state(batch_size=batch_size, **kwargs)
                for c in cells], [])


class DropoutCell(HybridRecurrentCell):
    """Dropout on cell inputs (reference ``rnn_cell.py:928``)."""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def forward(self, inputs, states):
        if self._rate > 0:
            inputs = _ops.dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states


class ModifierCell(HybridRecurrentCell):
    """Base for cells wrapping another cell (reference ``rnn_cell.py:997``)."""

    def __init__(self, base_cell, **kwargs):
        assert not base_cell._modified, (
            "The base cell has already been modified")
        base_cell._modified = True
        super().__init__(**kwargs)
        self.base_cell = base_cell

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, batch_size=0, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(batch_size=batch_size, func=func,
                                           **kwargs)
        self.base_cell._modified = True
        return begin


class ZoneoutCell(ModifierCell):
    """Zoneout regularization (Krueger et al. 2016; reference
    ``rnn_cell.py:1052``)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0,
                 **kwargs):
        assert not isinstance(base_cell, BidirectionalCell)
        super().__init__(base_cell, **kwargs)
        self._zoneout_outputs = zoneout_outputs
        self._zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def forward(self, inputs, states):
        from ... import numpy as mnp

        next_output, next_states = self.base_cell(inputs, states)
        p_outputs, p_states = self._zoneout_outputs, self._zoneout_states

        def mask(p, like):
            return _ops.dropout(mnp.ones_like(like), p=p)

        prev_output = (self._prev_output if self._prev_output is not None
                       else mnp.zeros_like(next_output))
        output = (mnp.where(mask(p_outputs, next_output), next_output,
                            prev_output)
                  if p_outputs != 0.0 else next_output)
        new_states = ([mnp.where(mask(p_states, new_s), new_s, old_s)
                       for new_s, old_s in zip(next_states, states)]
                      if p_states != 0.0 else next_states)
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """Adds the input to the output (reference ``rnn_cell.py:1119``)."""

    def forward(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        return output + inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        self.base_cell._modified = False
        outputs, states = self.base_cell.unroll(
            length, inputs=inputs, begin_state=begin_state, layout=layout,
            merge_outputs=True, valid_length=valid_length)
        self.base_cell._modified = True
        outputs = outputs + inputs
        if merge_outputs is False:
            from ... import numpy as mnp

            axis = layout.find("T")
            outputs = [x.squeeze(axis=axis)
                       for x in mnp.split(outputs, length, axis=axis)]
        return outputs, states


class BidirectionalCell(HybridRecurrentCell):
    """Runs l/r cells over both directions; only usable via ``unroll``."""

    def __init__(self, l_cell, r_cell, **kwargs):
        super().__init__(**kwargs)
        self.l_cell = l_cell
        self.r_cell = r_cell

    def __call__(self, inputs, states):
        raise MXNetError(
            "Bidirectional cells cannot be stepped; use unroll() instead")

    def state_info(self, batch_size=0):
        return _cells_state_info([self.l_cell, self.r_cell], batch_size)

    def begin_state(self, batch_size=0, **kwargs):
        assert not self._modified
        return _cells_begin_state([self.l_cell, self.r_cell], batch_size,
                                  **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        from ... import numpy as mnp

        self.reset()
        axis = layout.find("T")
        batch_axis = layout.find("N")
        if begin_state is None:
            begin_state = self.begin_state(
                batch_size=inputs.shape[batch_axis])
        n_l = len(self.l_cell.state_info())
        l_outputs, l_states = self.l_cell.unroll(
            length, inputs=inputs, begin_state=begin_state[:n_l],
            layout=layout, merge_outputs=True, valid_length=valid_length)
        if valid_length is not None:
            rev_inputs = _ops.sequence_reverse(
                inputs, sequence_length=valid_length,
                use_sequence_length=True, axis=axis)
        else:
            rev_inputs = mnp.flip(inputs, axis=axis)
        r_outputs, r_states = self.r_cell.unroll(
            length, inputs=rev_inputs, begin_state=begin_state[n_l:],
            layout=layout, merge_outputs=True, valid_length=valid_length)
        if valid_length is not None:
            r_outputs = _ops.sequence_reverse(
                r_outputs, sequence_length=valid_length,
                use_sequence_length=True, axis=axis)
        else:
            r_outputs = mnp.flip(r_outputs, axis=axis)
        outputs = mnp.concatenate([l_outputs, r_outputs], axis=2)
        if merge_outputs is False:
            outputs = [x.squeeze(axis=axis)
                       for x in mnp.split(outputs, length, axis=axis)]
        return outputs, l_states + r_states
