"""Control-flow operators (reference: ``src/operator/control_flow.cc`` —
``_foreach``/``_while_loop``/``_cond`` with hand-written backward graphs,
``control_flow.cc:1096-1262``).

TPU design: these lower directly onto ``lax.scan`` / ``lax.while_loop`` /
``lax.cond`` — XLA compiles one loop body and differentiates scan/cond
automatically (while_loop is forward-only, same as the reference's
restriction that ``_while_loop`` backward requires bounded unrolling).
Python callables receive/return NDArrays, so user code composes with the
rest of the framework and records on the autograd tape via the dispatch
layer.
"""
from __future__ import annotations

from ..base import MXNetError
from .registry import apply as _apply


def _split_state(out):
    if isinstance(out, (list, tuple)) and len(out) == 2:
        return out[0], out[1]
    raise MXNetError("body must return (outputs, states)")


def foreach(body, data, init_states):
    """Run ``body(slice, states) -> (out, states)`` over axis-0 slices of
    ``data`` (``npx.foreach`` / reference ``_foreach``): one compiled
    ``lax.scan``; differentiable.
    """
    import jax

    from .. import autograd
    from ..ndarray.ndarray import NDArray

    multi_data = isinstance(data, (list, tuple))
    datas = list(data) if multi_data else [data]
    multi_state = isinstance(init_states, (list, tuple))
    states = list(init_states) if multi_state else [init_states]
    n_data = len(datas)
    out_struct = {}

    if autograd.is_recording():
        # eager tape recording: unroll in Python so gradients flow to BOTH
        # the declared inputs and any closure-captured parameters (the
        # reference's foreach backward covers free variables the same way,
        # control_flow.cc:1096). The lax.scan path below serves inference
        # and hybridized traces, where jax differentiates the whole graph.
        from .. import numpy as mnp

        length = datas[0].shape[0]
        cur = states if multi_state else states[0]
        outs_acc = None
        for t in range(length):
            sl = [d[t] for d in datas]
            out, cur = _split_state(body(sl if multi_data else sl[0], cur))
            outs = out if isinstance(out, (list, tuple)) else [out]
            if outs_acc is None:
                outs_acc = [[] for _ in outs]
                multi_out = isinstance(out, (list, tuple))
            for acc, o in zip(outs_acc, outs):
                acc.append(o)
        stacked = [mnp.stack(acc) for acc in outs_acc]
        out_val = stacked if multi_out else stacked[0]
        return out_val, cur

    def f(*arrs):
        d_arrs = arrs[:n_data]
        s_arrs = arrs[n_data:]

        def step(carry, xs):
            s_nd = [NDArray(c) for c in carry]
            x_nd = [NDArray(x) for x in xs]
            out, new_s = _split_state(body(
                x_nd if multi_data else x_nd[0],
                s_nd if multi_state else s_nd[0]))
            outs = out if isinstance(out, (list, tuple)) else [out]
            new_states = (new_s if isinstance(new_s, (list, tuple))
                          else [new_s])
            out_struct["n_out"] = len(outs)
            out_struct["multi_out"] = isinstance(out, (list, tuple))
            return (tuple(o._data for o in new_states),
                    tuple(o._data for o in outs))

        carry, ys = jax.lax.scan(step, tuple(s_arrs), tuple(d_arrs))
        return tuple(ys) + tuple(carry)

    # cacheable=False: f populates out_struct at TRACE time; a jit-cache
    # hit would skip tracing and leave it empty
    res = _apply(f, tuple(datas + states), name="foreach", cacheable=False)
    n_out = out_struct["n_out"]
    outs = list(res[:n_out])
    final_states = list(res[n_out:])
    out_val = outs if out_struct["multi_out"] else outs[0]
    state_val = final_states if multi_state else final_states[0]
    return out_val, state_val


def while_loop(cond, func, loop_vars, max_iterations=None):
    """``npx.while_loop`` (reference ``_while_loop``): runs
    ``func(*loop_vars)`` while ``cond(*loop_vars)`` holds.

    Lowered to ``lax.while_loop`` (forward-only, like the reference's op
    without ``max_iterations`` unrolling). Outputs stacked per-step are not
    supported — the reference requires ``max_iterations`` for that; here
    ``func`` returns only the new loop vars.
    """
    import jax

    from ..ndarray.ndarray import NDArray

    multi = isinstance(loop_vars, (list, tuple))
    lvars = list(loop_vars) if multi else [loop_vars]

    def f(*arrs):
        def c(carry):
            vals, it = carry
            nd = [NDArray(v) for v in vals]
            keep = cond(*nd)
            k = keep._data if isinstance(keep, NDArray) else keep
            if max_iterations is not None:
                import jax.numpy as jnp

                return jnp.logical_and(k.astype(bool),
                                       it < max_iterations)
            return k.astype(bool) if hasattr(k, "astype") else k

        def b(carry):
            vals, it = carry
            nd = [NDArray(v) for v in vals]
            new = func(*nd)
            new = new if isinstance(new, (list, tuple)) else [new]
            return (tuple(v._data if isinstance(v, NDArray) else v
                          for v in new), it + 1)

        out, _ = jax.lax.while_loop(c, b, (tuple(arrs), 0))
        return tuple(out)

    res = _apply(f, tuple(lvars), name="while_loop", record=False,
                 cacheable=False)
    res = list(res) if isinstance(res, (list, tuple)) else [res]
    return res if multi else res[0]


def cond(pred, then_func, else_func, inputs):
    """``npx.cond`` (reference ``_cond``): branch on a scalar predicate;
    both branches trace into one ``lax.cond`` (differentiable)."""
    import jax

    from .. import autograd
    from ..ndarray.ndarray import NDArray

    multi = isinstance(inputs, (list, tuple))
    ins = list(inputs) if multi else [inputs]

    if autograd.is_recording():
        # eager tape recording: the predicate is known, run that branch so
        # gradients flow to closure-captured parameters too
        import numpy as onp

        take_then = bool(onp.asarray(
            pred.asnumpy() if isinstance(pred, NDArray) else pred).item())
        fn = then_func if take_then else else_func
        return fn(*ins)

    p = pred._data if isinstance(pred, NDArray) else pred

    def f(pd, *arrs):
        def run(fn):
            def inner(xs):
                nd = [NDArray(x) for x in xs]
                out = fn(*nd)
                outs = out if isinstance(out, (list, tuple)) else [out]
                return tuple(o._data if isinstance(o, NDArray) else o
                             for o in outs)

            return inner

        return jax.lax.cond(pd.astype(bool).reshape(()),
                            run(then_func), run(else_func), tuple(arrs))

    res = _apply(f, tuple([NDArray(p) if not isinstance(p, NDArray) else p
                           for p in [pred]] + ins), name="cond", cacheable=False)
    res = list(res) if isinstance(res, (list, tuple)) else [res]
    return res if len(res) > 1 else res[0]
