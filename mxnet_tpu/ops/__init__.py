"""Operator layer: registry + op families (math via jax.numpy, nn via lax,
hot kernels via Pallas). TPU analog of the reference's ``src/operator/``."""
from __future__ import annotations

from . import registry
from .registry import apply, get, list_ops, register
